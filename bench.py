#!/usr/bin/env python
"""Benchmark driver: SDXL-class txt2img throughput on the available device.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric matches BASELINE.md: images/sec for SDXL 1024², 30 steps (per chip;
pod scaling multiplies by data-parallel width). The reference publishes no
numbers (BASELINE.json "published": {}), so ``vs_baseline`` falls back to
1.0 with an explicit ``vs_baseline_note`` when nothing is published.

Hardened against the flaky accelerator tunnel (it can refuse connections,
die mid-compile, or hang ``jax.devices()`` outright):

- the accelerator attempt runs in a WATCHDOG SUBPROCESS with a wall-clock
  timeout, retried within ``CDT_BENCH_BUDGET_S`` (default 2400 s);
- a CPU downgrade is loud (stderr) and explicit in the JSON —
  ``tpu_attempted`` / ``tpu_error`` make a toy CPU line impossible to
  mistake for the real result;
- MFU comes from XLA's compiled cost analysis of the whole generation
  program divided by measured step time and chip peak (bf16).
"""

from __future__ import annotations

import argparse
import faulthandler
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# `kill -USR1 <pid>` dumps every thread's Python stack to stderr — the
# tunneled accelerator can wedge anywhere (tracing, compile RPC, transfer)
# and this is the only way to see where without a debugger.
try:
    faulthandler.register(signal.SIGUSR1)
except (AttributeError, ValueError):  # non-main thread / platform quirk
    pass

# bf16 peak FLOP/s per chip, by device_kind substring (lowercase match).
_PEAK_BF16 = [
    ("v5 lite", 197e12),   # v5e reports "TPU v5 lite"
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),        # Trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def _cost_analysis_flops(compiled) -> float | None:
    """Total FLOPs of the compiled program per XLA's cost model."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            f = ca.get("flops")
            if f and f > 0:
                return float(f)
    except Exception:
        pass
    return None


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache via the ONE shared config path
    (``utils/compile_cache.enable_compile_cache`` — same knobs as the
    server and the warmup pass): on the flaky tunneled accelerator, a
    successful compile from ANY earlier attempt (even one whose run died
    later) is reused, so watcher retries make monotonic progress.
    ``min_compile_secs=0.0``: bench wants every program persisted.
    Bench keeps its historical tmpdir default when the env var is unset
    (attempt subprocesses share it; a user HOME may not exist on CI)."""
    from comfyui_distributed_tpu.utils.compile_cache import \
        enable_compile_cache

    cache_dir = os.environ.get(
        "CDT_COMPILE_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "cdt_xla_cache"))
    if enable_compile_cache(cache_dir, min_compile_secs=0.0) is None:
        print("[bench] compile cache unavailable (continuing without)",
              file=sys.stderr)



def _analytic_flops(fn, *args, weights=None) -> float | None:
    """Analytic matmul+conv FLOPs of one ``fn(weights, *args)`` call via
    the jaxpr walk (``utils/flops.py``): the per-shard body is counted
    once = one CHIP's work. ``fn`` is a ``bind_weights`` wrapper
    (``.jitted``/``.weights``); pass ``weights`` to substitute abstract
    ShapeDtypeStructs (offload benches trace the equivalent resident
    program without materializing it). Diagnostics never sink a bench —
    failures return None."""
    try:
        from comfyui_distributed_tpu.utils.flops import estimate_flops

        w = fn.weights if weights is None else weights
        return estimate_flops(fn.jitted, w, *args)
    except Exception as e:
        print(f"[bench] analytic flops estimate failed: {e}", file=sys.stderr)
        return None


def _mfu_fields(per_chip_flops: float | None, median_s: float,
                on_accel: bool) -> dict:
    """Shared MFU accounting (r04 VERDICT weak #1: only the SDXL txt2img
    artifact carried ``mfu``): per-chip analytic FLOPs over the median
    wall-clock against the chip's bf16 peak. Emitted for every workload
    so regressions in any of them are visible release-over-release."""
    if not per_chip_flops:
        return {}
    import jax

    out = {
        "model_flops_per_chip": round(per_chip_flops),
        "flops_source": "analytic_jaxpr",
    }
    peak = _peak_flops(jax.devices()[0].device_kind) if on_accel else None
    if peak:
        out["mfu"] = round(per_chip_flops / median_s / peak, 4)
        out["peak_flops_per_chip_bf16"] = peak
    return out


def _timed_runs(run_once, n_runs: int) -> tuple[list, float]:
    """Shared timing harness: run n times, return (sorted times, median)
    — one place for the measurement methodology (BASELINE protocol)."""
    times = []
    for i in range(n_runs):
        t0 = time.perf_counter()
        run_once(i)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times, times[len(times) // 2]


def run_benchmark(steps: int, runs: int | None, force_cpu: bool) -> dict:
    """The actual measurement (single process, current JAX backend)."""
    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.pipeline import (
        GenerationSpec, Txt2ImgPipeline, sdxl_adm)
    from comfyui_distributed_tpu.models.text import TextEncoder, TextEncoderConfig
    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh

    if on_accel:
        # SDXL-base architecture, 1024² (latent 128²)
        unet_cfg = UNetConfig.sdxl()
        vae_cfg = VAEConfig.sdxl()
        text_cfg = TextEncoderConfig()
        spec = GenerationSpec(height=1024, width=1024, steps=steps,
                              guidance_scale=5.0, per_device_batch=1)
        lat_hw = (128, 128)
    else:
        unet_cfg = UNetConfig.tiny()
        vae_cfg = VAEConfig.tiny()
        text_cfg = TextEncoderConfig.tiny()
        spec = GenerationSpec(height=32, width=32, steps=steps,
                              guidance_scale=5.0, per_device_batch=1)
        lat_hw = (16, 16)

    key = jax.random.key(0)
    # bf16-resident weights on accel: halves per-step HBM weight traffic
    # (the UNet computes in bf16 regardless); cast fused into the init
    # program so the fp32 tree never fully materializes on device
    model, params = init_unet(
        unet_cfg, key, sample_shape=(*lat_hw, unet_cfg.in_channels),
        context_len=text_cfg.max_len,
        param_dtype=jnp.bfloat16 if on_accel else None)
    vae = AutoencoderKL(vae_cfg).init(
        jax.random.key(1),
        image_hw=(lat_hw[0] * vae_cfg.downscale, lat_hw[1] * vae_cfg.downscale))
    enc = TextEncoder(text_cfg).init(jax.random.key(2))
    pipe = Txt2ImgPipeline(model, params, vae)
    ctx, pooled = enc.encode(["benchmark prompt"])
    unc, upooled = enc.encode([""])

    n_dev = len(jax.devices())
    mesh = build_mesh({"dp": n_dev})

    y = uy = None
    if unet_cfg.adm_in_channels:
        if unet_cfg.adm_in_channels == 2816:
            y = sdxl_adm(pooled, (spec.height, spec.width))
            uy = sdxl_adm(upooled, (spec.height, spec.width))
        else:
            y = jnp.zeros((1, unet_cfg.adm_in_channels))
            uy = jnp.zeros_like(y)

    fn = pipe.generate_fn(mesh, spec)
    args = (jax.random.key(42), ctx, unc,
            y if y is not None else jnp.zeros((1, 1)),
            uy if uy is not None else jnp.zeros((1, 1)))

    # honesty flag for the cold-vs-warm fields below: the persistent
    # cache survives across attempts/runs BY DESIGN (watcher retries),
    # so on a re-run the "cold" compile below is really a cache load —
    # the artifact says so instead of overstating the delta
    _cache_dir = os.environ.get(
        "CDT_COMPILE_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "cdt_xla_cache"))
    try:
        cache_prepopulated = bool(os.listdir(_cache_dir))
    except OSError:
        cache_prepopulated = False

    # compile (timed separately) + cost analysis for the MFU estimate.
    # Weights are explicit jit arguments (fn.weights) — passing them
    # through lower() keeps multi-GB params out of the lowered module.
    t0 = time.perf_counter()
    compiled = fn.jitted.lower(fn.weights, *args).compile()
    compile_s = time.perf_counter() - t0
    xla_flops = _cost_analysis_flops(compiled)

    # analytic matmul+conv count: XLA's TPU cost analysis drops conv
    # FLOPs that lower into custom fusions (~10× under for SDXL), which
    # would make the MFU figure meaningless. The jaxpr walk counts the
    # per-shard program (shard_map body once) = per-chip work.
    total_flops, flops_source = xla_flops, "xla_cost_analysis"
    try:
        from comfyui_distributed_tpu.utils.flops import estimate_flops

        # × n_dev: the walker counts the shard_map body once (= one
        # chip's work); the whole program runs it on every chip
        analytic = estimate_flops(fn.jitted, fn.weights, *args) * n_dev
        if analytic and (not xla_flops or analytic > xla_flops):
            total_flops, flops_source = analytic, "analytic_jaxpr"
    except Exception as e:  # diagnostics must never sink the benchmark
        print(f"[bench] analytic flops estimate failed: {e}",
              file=sys.stderr)

    # warm-restart probe (ISSUE 6): drop jax's in-memory executable
    # caches and AOT-compile the same program again — with the
    # persistent cache now populated this measures the cache-LOAD cost a
    # rolling restart pays, vs the full compile above. The gap is the
    # cold-start elimination win the warmup pass banks per shape.
    jax.clear_caches()
    t0 = time.perf_counter()
    fn.jitted.lower(fn.weights, *args).compile()
    warm_compile_s = time.perf_counter() - t0

    # warmup run (first execution pays allocator/init overhead)
    jax.block_until_ready(compiled(fn.weights, *args))

    # timed runs (median of 5 per protocol in BASELINE.md; 3 on cpu)
    runs = runs or (5 if on_accel else 3)
    times, median = _timed_runs(
        lambda i: jax.block_until_ready(compiled(fn.weights,
                                                 jax.random.key(i),
                                                 *args[1:])), runs)
    images = n_dev * spec.per_device_batch
    ips = images / median

    mfu = None
    flops_per_image = None
    peak = _peak_flops(jax.devices()[0].device_kind) if on_accel else None
    if total_flops:
        flops_per_image = total_flops / images
        if peak:
            mfu = total_flops / median / (peak * n_dev)

    baseline = None
    note = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get("images_per_sec")
    except (OSError, json.JSONDecodeError):
        pass
    if baseline:
        vs = ips / baseline
    else:
        vs = 1.0
        note = "reference publishes no numbers (BASELINE.json published={})"

    result = {
        "metric": (f"sdxl_1024_{spec.steps}step_images_per_sec" if on_accel
                   else f"tiny_32_{spec.steps}step_images_per_sec_cpu"),
        "value": round(ips, 4),
        "unit": "images/sec",
        "vs_baseline": round(vs, 4),
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "devices": n_dev,
        "steps": spec.steps,
        "median_image_latency_s": round(median, 3),
        "median_step_time_s": round(median / spec.steps, 4),
        "compile_s": round(compile_s, 1),
        # cold vs warm-restart time-to-first-image: compile_s is the
        # cold path ONLY when compile_cache_prepopulated is false;
        # compile_warm_restart_s re-AOT-compiles after
        # jax.clear_caches() with the persistent cache populated — the
        # cost a restarted worker actually pays per shape
        "compile_cache_prepopulated": cache_prepopulated,
        "compile_warm_restart_s": round(warm_compile_s, 2),
        "ttfi_cold_s": round(compile_s + median, 2),
        "ttfi_warm_restart_s": round(warm_compile_s + median, 2),
        "run_times_s": [round(t, 3) for t in times],
    }
    if note:
        result["vs_baseline_note"] = note
    if flops_per_image:
        result["model_flops_per_image"] = round(flops_per_image)
        result["flops_source"] = flops_source
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
        result["peak_flops_per_chip_bf16"] = peak
    return result


def run_usdu_benchmark(steps: int, runs: int | None, force_cpu: bool) -> dict:
    """BASELINE's second headline: 4K Ultimate-SD-Upscale wall-clock
    (1024² → 4096², 512² tiles sharded over the mesh; tiny shapes on CPU)."""
    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.pipeline import Txt2ImgPipeline
    from comfyui_distributed_tpu.models.text import TextEncoder, TextEncoderConfig
    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh
    from comfyui_distributed_tpu.tiles.engine import TileUpscaler, UpscaleSpec

    if on_accel:
        unet_cfg, vae_cfg, text_cfg = (UNetConfig.sdxl(), VAEConfig.sdxl(),
                                       TextEncoderConfig())
        src_hw, lat_hw = (1024, 1024), (128, 128)
        spec = UpscaleSpec(scale=4.0, tile_w=512, tile_h=512, padding=32,
                           steps=steps, denoise=0.3, guidance_scale=5.0)
    else:
        unet_cfg, vae_cfg, text_cfg = (UNetConfig.tiny(), VAEConfig.tiny(),
                                       TextEncoderConfig.tiny())
        src_hw, lat_hw = (32, 32), (16, 16)
        spec = UpscaleSpec(scale=2.0, tile_w=32, tile_h=32, padding=4,
                           steps=min(steps, 4), denoise=0.3,
                           guidance_scale=1.0)

    model, params = init_unet(
        unet_cfg, jax.random.key(0),
        sample_shape=(*lat_hw, unet_cfg.in_channels),
        context_len=text_cfg.max_len,
        param_dtype=jnp.bfloat16 if on_accel else None)
    vae = AutoencoderKL(vae_cfg).init(
        jax.random.key(1),
        image_hw=(lat_hw[0] * vae_cfg.downscale, lat_hw[1] * vae_cfg.downscale))
    enc = TextEncoder(text_cfg).init(jax.random.key(2))
    pipe = Txt2ImgPipeline(model, params, vae)
    ctx, _ = enc.encode(["benchmark prompt"])
    unc, _ = enc.encode([""])

    n_dev = len(jax.devices())
    mesh = build_mesh({"dp": n_dev})
    ups = TileUpscaler(pipe)
    image = jax.random.uniform(jax.random.key(3), (1, *src_hw, 3))

    if on_accel:
        # Chunked farm path: the single-program engine batches ALL tiles
        # in one XLA program — right for a pod (tiles shard over chips),
        # an instant OOM for 64 4K-tiles on ONE chip. range_plan processes
        # `chunk = n_devices × tiles_per_device` tiles per dispatch (r04:
        # batching 8 tiles/device + async dispatch/fetch overlap cut the
        # 4K wall-clock 53.3 → 27.9 s — fewer dispatch RTTs, fuller MXU
        # at 512² tile shapes, transfers hidden behind compute; the
        # batch sweep plateaus from 4 through 16, 32 blows the compile
        # budget), exactly how the cross-host tile farm drives a host
        # (cluster/tile_farm.py).
        import numpy as _np

        plan = ups.range_plan(mesh, image[0], spec, 7, ctx, unc)
        T = plan.num_tiles

        def full_pass():
            # one wide range: run_range loops the compiled fixed-chunk
            # program internally, dispatching every sub-chunk before
            # fetching any result (compute/transfer overlap)
            tiles = plan.run_range(0, T)
            return jax.block_until_ready(ups.composite(tiles, plan))

        t0 = time.perf_counter()
        out = full_pass()                 # first pass pays the compile
        compile_s = time.perf_counter() - t0
        runs = runs or 2
        times, median = _timed_runs(lambda i: full_pass(), runs)
        # USEFUL-work MFU: fractional dispatches (T/chunk) so pad tiles
        # in a partial last chunk count as overhead, not work
        mfu_extra = {}
        if plan.flops_per_dispatch is not None:
            try:
                per_disp = plan.flops_per_dispatch()
            except Exception as e:   # diagnostics never sink a bench
                print(f"[bench] usdu flops estimate failed: {e}",
                      file=sys.stderr)
                per_disp = None
            if per_disp:
                mfu_extra = _mfu_fields(per_disp * (T / plan.chunk),
                                        median, on_accel)
                mfu_extra["tiles_per_sec"] = round(T / median, 2)
    else:
        mfu_extra = {}
        t0 = time.perf_counter()
        out = jax.block_until_ready(
            ups.upscale(mesh, image, spec, 7, ctx, unc))
        compile_s = time.perf_counter() - t0

        runs = runs or 2
        times, median = _timed_runs(
            lambda i: jax.block_until_ready(
                ups.upscale(mesh, image, spec, i, ctx, unc)), runs)
    grid = ups.grid_for(src_hw[0], src_hw[1], spec)

    return {
        **mfu_extra,
        "metric": ("sdxl_usdu_4k_wall_clock_s" if on_accel
                   else "tiny_usdu_wall_clock_s_cpu"),
        "value": round(median, 3),
        "unit": "seconds",
        "vs_baseline": 1.0,
        "vs_baseline_note": "reference publishes no numbers",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "devices": n_dev,
        "steps": spec.steps,
        "tiles": grid.num_tiles,
        "output_hw": [int(src_hw[0] * spec.scale), int(src_hw[1] * spec.scale)],
        "compile_s": round(compile_s, 1),
        "run_times_s": [round(t, 3) for t in times],
    }


def run_flux_benchmark(steps: int, runs: int | None, force_cpu: bool) -> dict:
    """BASELINE row 3: FLUX-class flow txt2img 1024². Full FLUX.1 is 12B
    params (24 GB bf16) — more than one v5e chip's 16 GB HBM. Default on
    accelerators: FULL depth with host-offloaded block streaming
    (``diffusion/offload.py``; CDT_OFFLOAD_RESIDENT_GB caps HBM
    residency). CDT_OFFLOAD=0 falls back to the bf16-resident half-depth
    surrogate; pods run dp×tp (``generate_tp_fn``, dry-run validated)."""
    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.pipeline_flow import (
        FlowPipeline, FlowSpec)
    from comfyui_distributed_tpu.models.dit import DiTConfig, init_dit
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh

    if on_accel:
        from comfyui_distributed_tpu.diffusion.offload import offload_enabled

        if offload_enabled(default=True):   # full depth needs streaming
            return _run_flux_offloaded(steps, runs, platform)

    half_depth = False
    if on_accel:
        import dataclasses as _dc

        cfg = _dc.replace(DiTConfig.flux(), depth_double=10, depth_single=19)
        half_depth = True
        vae_cfg = VAEConfig(latent_channels=16, scaling_factor=0.3611,
                            shift_factor=0.1159)
        hw, lat_hw, ctx_len = (1024, 1024), (128, 128), 512
    else:
        cfg = DiTConfig.tiny(pos_embed="rope")
        vae_cfg = VAEConfig.tiny()
        hw, lat_hw, ctx_len = (32, 32), (16, 16), 16

    model, params = init_dit(cfg, jax.random.key(0), sample_hw=lat_hw,
                             context_len=ctx_len,
                             param_dtype=jnp.bfloat16 if on_accel else None)
    vae = AutoencoderKL(vae_cfg).init(
        jax.random.key(1),
        image_hw=(lat_hw[0] * vae_cfg.downscale,
                  lat_hw[1] * vae_cfg.downscale))
    pipe = FlowPipeline(model, params, vae)
    n_dev = len(jax.devices())
    mesh = build_mesh({"dp": n_dev})
    spec = FlowSpec(height=hw[0], width=hw[1], steps=steps)
    ctx = jnp.zeros((1, ctx_len, cfg.context_dim))
    pooled = jnp.zeros((1, cfg.pooled_dim))

    fn = pipe.generate_fn(mesh, spec)
    t0 = time.perf_counter()
    jax.block_until_ready(fn(jax.random.key(0), ctx, pooled))
    compile_s = time.perf_counter() - t0

    runs = runs or (5 if on_accel else 3)
    times, median = _timed_runs(
        lambda i: jax.block_until_ready(
            fn(jax.random.key(i + 1), ctx, pooled)), runs)
    mfu_extra = _mfu_fields(
        _analytic_flops(fn, jax.random.key(0), ctx, pooled),
        median, on_accel)
    out = {
        **mfu_extra,
        "metric": (f"flux_half_depth_1024_{steps}step_images_per_sec"
                   if on_accel
                   else f"flux_tiny_{steps}step_images_per_sec_cpu"),
        "value": round(n_dev / median, 4),
        "unit": "images/sec",
        "vs_baseline": 1.0,
        "vs_baseline_note": "reference publishes no numbers",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "devices": n_dev, "steps": steps,
        "median_image_latency_s": round(median, 3),
        "compile_s": round(compile_s, 1),
        "run_times_s": [round(t, 3) for t in times],
    }
    if half_depth:
        out["note"] = ("full FLUX.1 (12B) exceeds one v5e chip's HBM; "
                       "pod runs use dp×tp (generate_tp_fn). This measures "
                       "the architecture at depth 10/19, bf16-resident "
                       "(CDT_OFFLOAD=0 fallback — the default flux metric "
                       "is full depth via host offload).")
    return out


def _rss_gb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1e6
    return 0.0


def _mem_available_gb() -> float:
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemAvailable"):
                return int(line.split()[1]) / 1e6
    return 0.0


def _probe_h2d_leak(dev) -> tuple[float, float]:
    """Warm host→device bandwidth + RSS-leak ratio of ONE 256 MB put —
    the tunneled IFRT-proxy client retains a host copy of every
    device_put for the process lifetime (observed 1.05 GB RSS per GB);
    real hosts measure ~0. Shared by every offload bench."""
    import numpy as np

    import jax

    probe = np.ones((64, 1024, 1024), np.float32)      # 256 MB
    a = jax.device_put(probe, dev)
    a.block_until_ready()
    a.delete()
    rss0 = _rss_gb()
    t0 = time.perf_counter()
    b = jax.device_put(probe, dev)
    b.block_until_ready()
    h2d_gbps = 0.25 / (time.perf_counter() - t0)
    b.delete()
    leak_ratio = max(0.0, (_rss_gb() - rss0) / 0.25)
    del probe, a, b
    return h2d_gbps, leak_ratio


def _affordable_forwards_or_raise(leak_ratio: float, param_bytes: int,
                                  resident_bytes: int,
                                  streamed_gb: float) -> float:
    """Host-RAM budget under the put-leak, checked BEFORE any multi-GB
    build: leave a 12 GB floor, reserve the flat block copies
    (~param_bytes) and the leaked resident upload; the remainder funds
    streamed forwards. Returns the affordable forward count (``inf``
    when the transport doesn't leak or nothing streams); raises rather
    than starting a run that would OOM the host. ONE budget model for
    every offload bench (flux, wan14b)."""
    if leak_ratio <= 0.5:
        return float("inf")
    headroom = max(0.0, _mem_available_gb() - 12.0 - param_bytes / 1e9)
    upload_need = resident_bytes / 1e9 * (1.0 + leak_ratio)
    if headroom < upload_need:
        raise RuntimeError(
            f"offload bench: transfer leak ({leak_ratio:.2f} GB RSS/GB)"
            f" and only {_mem_available_gb():.0f} GB available — the "
            f"{upload_need:.0f} GB resident upload itself would OOM the"
            " host; refusing to start")
    if streamed_gb <= 0.05:
        return float("inf")
    fwds = (headroom - upload_need) / max(streamed_gb, 0.5)
    if fwds < 2:                             # can't even warmup + 1 step
        raise RuntimeError(
            f"offload bench: transfer leak ({leak_ratio:.2f} GB RSS/GB)"
            f" and only {_mem_available_gb():.0f} GB available — fewer "
            "than 2 affordable forwards; refusing to start a run that "
            "would OOM the host")
    return fwds


def _extrapolate_steps(lat1: float, s1: int, lat2: float, s2: int,
                       steps: int) -> tuple[float, float, dict]:
    """Two-point per-step linear extrapolation (exact for the offload
    ladders: every step streams identical bytes and runs the same
    compiled program). Returns (median, per_step, derivation)."""
    if s2 != s1:
        per_step = (lat2 - lat1) / (s2 - s1)
        overhead = max(0.0, lat1 - per_step * s1)
    else:                                    # tightest budget: conservative
        per_step, overhead = lat1 / s1, 0.0
    median = overhead + per_step * steps
    return median, per_step, {
        "derived": True,
        "measured_steps": [s1, s2],
        "measured_latencies_s": [round(lat1, 2), round(lat2, 2)],
        "fixed_overhead_s": round(overhead, 2),
        "method": ("per-step linear extrapolation: every step streams "
                   "identical bytes and runs the same compiled "
                   "program(s)"),
    }


def _run_flux_offloaded(steps: int, runs: int | None, platform: str) -> dict:
    """FULL-depth FLUX.1 (19/38, 12B params) on ONE chip (VERDICT r3
    item #2 — replaces the half-depth surrogate). Under the default fp8
    stream dtype the quantized block set fits HBM-resident: one upload,
    zero bytes streamed per step, one scanned program per forward —
    compute-bound even through a tunneled chip. Under
    CDT_OFFLOAD_STREAM_DTYPE=native, exact bf16 blocks stream per step
    with double-buffered prefetch; the raw host→device bandwidth is
    measured so the transport share of the step time is explicit.

    TRANSFER-LEAK AWARENESS (r04): the tunneled IFRT-proxy client
    retains a host-side copy of EVERY ``device_put`` for the process
    lifetime (measured: +1 GB RSS per 1 GB streamed; ``delete()``/gc
    free nothing — ``scripts/offload_rss_probe.py``). A 30-step
    full-depth image streams ~420 GB, so the r04 first attempt was
    OOM-killed at 130 GB RSS mid-warmup. The bench now probes for the
    leak; when present it measures full-depth steady-state latency at
    two small step counts that fit the RAM budget and derives the
    requested-step latency from the exact per-step linearity of the
    python-level euler ladder (every step streams the same bytes and
    runs the same two compiled block programs — there is no cross-step
    amortization to mis-extrapolate). On leak-free hosts (real v5e DMA)
    the full run executes directly."""
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.diffusion.offload import (
        materialize_host_params, resident_budget_bytes, tree_bytes)
    from comfyui_distributed_tpu.diffusion.pipeline_flow import (
        FlowPipeline, FlowSpec)
    from comfyui_distributed_tpu.models.dit import DiTConfig, init_dit
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig

    cfg = DiTConfig.flux()            # FULL depth: 19 double / 38 single
    lat_hw, ctx_len = (128, 128), 512
    print("[bench] flux-offload: materializing 12B host params",
          file=sys.stderr, flush=True)
    model, abstract = init_dit(cfg, jax.random.key(0), sample_hw=lat_hw,
                               context_len=ctx_len, abstract=True,
                               param_dtype=jnp.bfloat16)
    params = materialize_host_params(abstract, seed=0)
    param_bytes = tree_bytes(params)

    dev = jax.devices()[0]
    h2d_gbps, leak_ratio = _probe_h2d_leak(dev)
    leak = leak_ratio > 0.5

    print("[bench] flux-offload: building pipeline", file=sys.stderr,
          flush=True)
    vae_cfg = VAEConfig(latent_channels=16, scaling_factor=0.3611,
                        shift_factor=0.1159)
    vae = AutoencoderKL(vae_cfg).init(
        jax.random.key(1), image_hw=(1024, 1024))
    # PLAN placement from shapes alone BEFORE any multi-GB build: the
    # leak RAM-budget guard below must be able to refuse a run that
    # would OOM the host without first paying the upload
    from comfyui_distributed_tpu.diffusion.offload import plan_offload
    plan = plan_offload(params, resident_budget_bytes())
    streamed = plan["streamed_bytes"]
    streamed_gb = max(0.5, streamed / 1e9)

    # TOTAL forwards this process can afford under the leak, computed
    # ONCE, before the executor exists (afterwards MemAvailable already
    # reflects the ~param_bytes of flat copies the build allocates —
    # recomputing would double-count them): leave a 12 GB floor so the
    # host never OOMs again, and reserve the flat block copies
    # (~param_bytes of host numpy).
    budget_fwds = _affordable_forwards_or_raise(
        leak_ratio, param_bytes, plan["resident_bytes"],
        streamed_gb if streamed > 0 else 0.0)

    # the PRODUCT path end-to-end: generate_offloaded builds + caches the
    # streamed executor, so the bench measures exactly what users run.
    # Under the default fp8 stream dtype the quantized block set fits
    # HBM resident, the forward is one scanned program and NOTHING
    # streams per step — the leak-budget derivation below only applies
    # while per-step streaming remains.
    pipe = FlowPipeline(model, params, vae)
    ctx = jnp.zeros((1, ctx_len, cfg.context_dim))
    pooled = jnp.zeros((1, cfg.pooled_dim))
    print("[bench] flux-offload: quantizing + uploading resident set",
          file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    off = pipe.offload_executor(resident_bytes=resident_budget_bytes())
    upload_s = time.perf_counter() - t0
    streamed = tree_bytes(off.streamed) if off.streamed else 0
    print(f"[bench] flux-offload: stream_dtype={off.stream_dtype} "
          f"resident={off.resident_bytes/1e9:.1f} GB "
          f"streamed/step={streamed/1e9:.1f} GB "
          f"(upload {upload_s:.0f}s)", file=sys.stderr, flush=True)

    def one_image(seed, n_steps):
        spec = FlowSpec(height=1024, width=1024, steps=n_steps)
        t0 = time.perf_counter()
        jax.block_until_ready(pipe.generate_offloaded(
            spec, seed, ctx, pooled,
            resident_bytes=resident_budget_bytes()))
        return time.perf_counter() - t0

    if leak and streamed > 0:
        for s1, s2 in ((1, 3), (1, 2), (1, 1)):
            if 1 + s1 + s2 <= budget_fwds:   # + 1-step warmup image
                break
        else:
            s1 = s2 = 1                      # budget 2: warmup + ONE timed
                                             # image; overhead folded into
                                             # per_step (conservative)
        print(f"[bench] flux-offload: transfer leak detected "
              f"({leak_ratio:.2f} GB RSS per GB streamed) — measuring "
              f"steps {s1} and {s2} within a {budget_fwds}-forward RAM "
              f"budget, deriving the {steps}-step latency",
              file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        one_image(0, 1)                   # warmup: compiles all programs
        compile_s = time.perf_counter() - t0
        lat1 = one_image(1, s1)
        lat2 = one_image(2, s2) if s2 != s1 else lat1
        median, per_step, derivation = _extrapolate_steps(
            lat1, s1, lat2, s2, steps)
        times = [lat1, lat2]
    else:
        print("[bench] flux-offload: warmup image (compiles + first "
              "stream)", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        one_image(0, steps)
        compile_s = time.perf_counter() - t0
        runs = runs or (3 if streamed == 0 else 2)
        print(f"[bench] flux-offload: {runs} timed runs", file=sys.stderr,
              flush=True)
        times, median = _timed_runs(lambda i: one_image(i + 1, steps), runs)
        per_step = median / steps
        derivation = {"derived": False}

    # analytic FLOPs of the EQUIVALENT resident program (same model, same
    # step count; the offload executor runs the same math through block
    # programs) — traced with abstract weights so the 24 GB tree is
    # never duplicated
    from comfyui_distributed_tpu.parallel import build_mesh
    mfu_extra = {}
    try:
        fn_ref = pipe.generate_fn(
            build_mesh({"dp": 1}),
            FlowSpec(height=1024, width=1024, steps=steps))
        struct_w = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            fn_ref.weights)
        mfu_extra = _mfu_fields(
            _analytic_flops(fn_ref, jax.random.key(0), ctx, pooled,
                            weights=struct_w),
            median, True)
    except Exception as e:
        print(f"[bench] flux-offload mfu estimate failed: {e}",
              file=sys.stderr)

    return {
        **mfu_extra,
        "metric": f"flux_full_depth_offload_1024_{steps}step_images_per_sec",
        "value": round(1.0 / median, 5),
        "unit": "images/sec",
        "vs_baseline": 1.0,
        "vs_baseline_note": "reference publishes no numbers",
        "platform": platform,
        "device_kind": dev.device_kind,
        "devices": 1, "steps": steps,
        "median_image_latency_s": round(median, 2),
        "per_step_s": round(per_step, 2),
        "compile_s": round(compile_s, 1),
        "run_times_s": [round(t, 2) for t in times],
        "param_bytes": param_bytes,
        "resident_bytes": off.resident_bytes,
        "streamed_bytes_per_step": streamed,
        "stream_dtype": off.stream_dtype,
        "quantization": ("weights-only per-output-channel absmax "
                         "float8_e4m3fn (kernels only; biases/norms/"
                         "qk-scales exact)" if off.stream_dtype
                         != "native" else None),
        "fully_resident": bool(off.stacked),
        "weight_upload_s": round(upload_s, 1),
        "host_to_device_gbps": round(h2d_gbps, 2),
        "transfer_leak_gb_per_gb": round(leak_ratio, 2),
        **derivation,
        "note": ("FULL FLUX.1 depth (19/38, ~12B params) on one chip: "
                 "under the default fp8 stream dtype the quantized "
                 "block set lives HBM-resident (one upload, zero bytes "
                 "streamed per step, one scanned program per forward); "
                 "CDT_OFFLOAD_STREAM_DTYPE=native restores exact bf16 "
                 "block streaming, which moves streamed_bytes_per_step "
                 "over host_to_device_gbps every step."),
    }


def _run_wan_like(steps: int, runs: int | None, force_cpu: bool,
                  moe: bool) -> dict:
    """Shared body of the ``wan`` / ``wan22`` workloads: identical
    geometry, pipeline construction, timing protocol, and result shape,
    so (wan22 − wan) isolates exactly the dual-expert switch."""
    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.pipeline_video import (
        VideoPipeline, VideoSpec)
    from comfyui_distributed_tpu.models.wan import WanConfig, init_wan
    from comfyui_distributed_tpu.models.wan_vae import (WanVAE3D,
                                                        WanVAEConfig)
    from comfyui_distributed_tpu.parallel import build_mesh

    if on_accel:
        # 1.3B-class config fits one v5e chip; 14B needs tp over a pod
        cfg, vae_cfg = WanConfig.wan_1_3b(), WanVAEConfig.wan()
        spec = VideoSpec(frames=33, height=480, width=832, steps=steps)
        ctx_len = 512
    else:
        cfg, vae_cfg = WanConfig.tiny(), WanVAEConfig.tiny()
        spec = VideoSpec(frames=5, height=16, width=16,
                         steps=min(steps, 2))
        ctx_len = 16

    n_dev = len(jax.devices())
    mesh = build_mesh({"dp": n_dev})
    vae = WanVAE3D(vae_cfg).init(jax.random.key(1), frames=5,
                                 image_hw=(vae_cfg.downscale * 4,) * 2)
    f_lat = vae_cfg.latent_frames(spec.padded_frames)
    sample_fhw = (f_lat, spec.height // vae_cfg.downscale,
                  spec.width // vae_cfg.downscale)
    dt = jnp.bfloat16 if on_accel else None
    model, params = init_wan(cfg, jax.random.key(0),
                             sample_fhw=sample_fhw,
                             context_len=ctx_len, param_dtype=dt)
    if moe:
        _, params_low = init_wan(cfg, jax.random.key(7),
                                 sample_fhw=sample_fhw,
                                 context_len=ctx_len, param_dtype=dt)
        pipe = VideoPipeline(model, params, vae,
                             dit_params_low=params_low,
                             expert_boundary=0.875)
        assert pipe.is_moe
    else:
        pipe = VideoPipeline(model, params, vae)
    ctx = jnp.zeros((1, ctx_len, cfg.text_dim))
    pooled = jnp.zeros((1, 16))

    fn = pipe.generate_fn(mesh, spec)
    t0 = time.perf_counter()
    jax.block_until_ready(fn(jax.random.key(0), ctx, pooled))
    compile_s = time.perf_counter() - t0

    runs = runs or (3 if on_accel else 2)
    times, median = _timed_runs(
        lambda i: jax.block_until_ready(
            fn(jax.random.key(i + 1), ctx, pooled)), runs)
    mfu_extra = _mfu_fields(
        _analytic_flops(fn, jax.random.key(0), ctx, pooled),
        median, on_accel)
    if moe:
        metric = ("wan22_moe_t2v_480p_33f_wall_clock_s" if on_accel
                  else "wan22_moe_tiny_t2v_wall_clock_s_cpu")
    else:
        metric = ("wan_t2v_480p_33f_wall_clock_s" if on_accel
                  else "wan_tiny_t2v_wall_clock_s_cpu")
    out = {
        **mfu_extra,
        "metric": metric,
        "value": round(median, 3),
        "unit": "seconds",
        "vs_baseline": 1.0,
        "vs_baseline_note": "reference publishes no numbers",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "devices": n_dev, "steps": spec.steps,
        "frames": spec.padded_frames, "latent_frames": f_lat,
        "compile_s": round(compile_s, 1),
        "run_times_s": [round(t, 3) for t in times],
    }
    if moe:
        out["expert_boundary"] = 0.875
    return out


def run_wan_benchmark(steps: int, runs: int | None, force_cpu: bool) -> dict:
    """BASELINE row 4: WAN t2v end-to-end (exact architecture over the 3D
    causal VAE; 33 frames 480×832 on accel, tiny shapes on CPU)."""
    return _run_wan_like(steps, runs, force_cpu, moe=False)


def run_wan14b_benchmark(steps: int, runs: int | None,
                         force_cpu: bool) -> dict:
    """WAN-2.1 **14B** t2v on ONE chip via the quantized offload
    executor (``diffusion/offload.OffloadedWan``) — the capability
    artifact for 'a 28 GB-bf16 expert on a 16 GB chip'. fp8(e4m3)
    residency holds ≥90% of the blocks in HBM (13 GB default budget);
    the overflow streams per step, so on a leaky tunneled host the
    latency is measured at two small step counts and extrapolated
    per-step (exact: the ladder streams identical bytes and runs the
    same program every step).

    Measured bound (r04, tunneled 16 GB v5e): this workload is wedged
    on that host — ≥12.4 GB resident OOMs at runtime (both ladder
    modes; the 33f×480×832 = 14k-token activations at dim 5120 need
    more headroom than residency leaves), while ≤11 GB resident streams
    more bytes per step than the leaky tunnel affords (13 forwards
    < the 16 the protocol needs). Capturing the artifact needs a host
    with real DMA (10-40 GB/s — any budget ≤11 GB then affords
    hundreds of forwards) or a ≥24 GB chip; the CPU tier and
    `tests/test_offload.py` keep the code path exercised meanwhile."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.offload import (
        materialize_host_params, plan_offload, resident_budget_bytes,
        tree_bytes, _WAN_GLUE_KEYS)
    from comfyui_distributed_tpu.diffusion.pipeline_video import (
        VideoPipeline, VideoSpec)
    from comfyui_distributed_tpu.models.wan import WanConfig, init_wan
    from comfyui_distributed_tpu.models.wan_vae import (WanVAE3D,
                                                        WanVAEConfig)

    if on_accel:
        cfg, vae_cfg = WanConfig.wan_14b(), WanVAEConfig.wan()
        spec = VideoSpec(frames=33, height=480, width=832, steps=steps)
        ctx_len = 512
    else:                      # CI-exercisable tiny path
        cfg, vae_cfg = WanConfig.tiny(), WanVAEConfig.tiny()
        spec = VideoSpec(frames=5, height=16, width=16,
                         steps=min(steps, 2))
        ctx_len = 16

    vae = WanVAE3D(vae_cfg).init(jax.random.key(1), frames=5,
                                 image_hw=(vae_cfg.downscale * 4,) * 2)
    f_lat = vae_cfg.latent_frames(spec.padded_frames)
    print(f"[bench] wan14b: materializing {cfg.dim}-dim "
          f"{cfg.num_layers}-layer host params", file=sys.stderr,
          flush=True)
    model, abstract = init_wan(
        cfg, jax.random.key(0),
        sample_fhw=(f_lat, spec.height // vae_cfg.downscale,
                    spec.width // vae_cfg.downscale),
        context_len=ctx_len, abstract=True,
        param_dtype=jnp.bfloat16 if on_accel else None)
    params = materialize_host_params(abstract, seed=0)
    param_bytes = tree_bytes(params)
    plan = plan_offload(params, resident_budget_bytes(),
                        block_prefixes=("block",),
                        glue_keys=_WAN_GLUE_KEYS)
    streamed_gb = plan["streamed_bytes"] / 1e9
    if on_accel:
        # same leaky-transport discipline as _run_flux_offloaded:
        # probe, then refuse BEFORE paying the multi-GB quantize +
        # upload (warmup + measurement stream 16 step-forwards total)
        _, leak_ratio = _probe_h2d_leak(jax.devices()[0])
        # warmup (s1 + s2 steps) + two measured videos of s1/s2 steps
        fwds_needed = 2 * (2 + 6)
        budget = _affordable_forwards_or_raise(
            leak_ratio, param_bytes, plan["resident_bytes"], streamed_gb)
        if budget < fwds_needed:
            raise RuntimeError(
                f"wan14b: only {budget:.0f} affordable streamed "
                f"forwards under the transfer leak; need {fwds_needed}")
    pipe = VideoPipeline(model, params, vae)
    ctx = jnp.zeros((1, ctx_len, cfg.text_dim))

    def one_video(seed, n_steps):
        sp = dataclasses.replace(spec, steps=n_steps)
        t0 = time.perf_counter()
        jax.block_until_ready(pipe.generate_offloaded(sp, seed, ctx))
        return time.perf_counter() - t0

    print(f"[bench] wan14b: {param_bytes/1e9:.1f} GB params, plan: "
          f"{plan['resident_bytes']/1e9:.1f} GB resident / "
          f"{streamed_gb:.1f} GB streamed per step", file=sys.stderr,
          flush=True)
    derived = on_accel and streamed_gb > 0.05
    # the resident ladder compiles per sigma-ladder LENGTH (scan over
    # steps) — warm up at exactly the step counts that get timed
    s1, s2 = 2, 6
    t0 = time.perf_counter()
    if derived:
        one_video(0, s1)            # upload + compiles
        one_video(0, s2)
    else:
        one_video(0, spec.steps)
    compile_s = time.perf_counter() - t0
    if derived:
        # leaky-transport discipline (see _run_flux_offloaded): measure
        # two small step counts, derive the requested-step latency from
        # exact per-step linearity
        lat1, lat2 = one_video(1, s1), one_video(2, s2)
        median, per_step, derivation = _extrapolate_steps(
            lat1, s1, lat2, s2, spec.steps)
        times = [lat1, lat2]
    else:
        runs = runs or 2
        times, median = _timed_runs(
            lambda i: one_video(i + 1, spec.steps), runs)
        per_step = median / spec.steps
        derivation = {"derived": False}

    off = pipe.offload_executor()
    return {
        "metric": (f"wan14b_t2v_33f_480x832_{spec.steps}step_wall_s"
                   if on_accel else "wan14b_tiny_wall_s_cpu"),
        "value": round(median, 2),
        "unit": "seconds",
        "vs_baseline": 1.0,
        "vs_baseline_note": "reference publishes no numbers",
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "devices": 1, "steps": spec.steps,
        "per_step_s": round(per_step, 2),
        "compile_s": round(compile_s, 1),
        "run_times_s": [round(t, 2) for t in times],
        "param_bytes": param_bytes,
        "resident_bytes": off.resident_bytes,
        "streamed_bytes_per_step": (tree_bytes(off.streamed)
                                    if off.streamed else 0),
        "stream_dtype": off.stream_dtype,
        "fully_resident": bool(off.stacked),
        **derivation,
        "note": ("WAN 14B t2v (28 GB bf16 params — ~2x one chip's HBM) "
                 "on ONE chip via fp8(e4m3) weight residency; blocks "
                 "past the budget stream per step. Pods run dp x tp "
                 "instead; the WAN-2.2 dual-expert pair adds one HBM "
                 "swap per video."),
    }


def run_wan22_benchmark(steps: int, runs: int | None,
                        force_cpu: bool) -> dict:
    """WAN-2.2-style dual-expert (MoE) t2v: TWO DiTs — a high-noise
    expert for sigmas ≥ the 0.875 t2v boundary, a low-noise expert
    below — with the sigma ladder split inside ONE compiled program
    (``pipeline_video._sample_expert``). Same geometry, protocol, and
    result shape as ``wan`` (shared ``_run_wan_like`` body), so
    (wan22 − wan) isolates what the expert switch costs on hardware —
    measured r04: 32.49 vs 32.46 s, i.e. free. Both experts' weights
    ride as jit arguments (2× upload, bf16-resident — 1.3B-class pairs
    fit one chip; published 14B pairs need the offload executor's HBM
    swap or tp over a pod)."""
    return _run_wan_like(steps, runs, force_cpu, moe=True)


def run_attn_benchmark(steps: int, runs: int | None,
                       force_cpu: bool) -> dict:
    """Per-geometry attention A/B from the tuning table (ISSUE 8): for every
    entry in the effective table (shipped model-zoo layer + any local
    sweeps) time each legal (tier, blocks) candidate on the live
    accelerator and report the table's choice against the measured best
    — the evidence that the shipped bake still matches this hardware
    generation.

    On CPU (no accelerator) timing is meaningless; instead the run
    verifies the decision chain end to end — every table entry passes
    the legality validator, the dry-policy sweep reproduces the shipped
    choice, and a small interpret-mode parity check runs the chosen tier
    — and says so explicitly (``platform: cpu``, ``ab_mode: decisions``)
    so a toy line can't be mistaken for hardware numbers."""
    import jax

    from comfyui_distributed_tpu.ops import autotune

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu" and not force_cpu
    # shipped model-zoo layer + any local sweeps (reads never raise —
    # a missing/corrupt local file degrades to the shipped layer)
    table = autotune.default_table()
    geometries = table.entries()
    per_geometry = []
    agreements = 0
    for key, choice in geometries.items():
        rec: dict = {"geometry": key.key_str(),
                     "table": choice.to_dict()}
        errors = autotune.validate_entry(key, choice)
        if errors:
            rec["legality_errors"] = errors
        if on_tpu:
            timings = []
            for cand in autotune.candidates_for(key):
                try:
                    us = autotune._time_candidate(
                        key, cand, runs=int(runs or 3)) * 1e6
                    timings.append(
                        {"tier": cand.tier, "block_q": cand.block_q,
                         "block_k": cand.block_k, "us": round(us, 1)})
                except Exception as e:  # noqa: BLE001 — candidate isolation
                    timings.append({"tier": cand.tier,
                                    "block_q": cand.block_q,
                                    "block_k": cand.block_k,
                                    "error": str(e)[:200]})
            ok = [t for t in timings if "us" in t]
            if ok:
                best = min(ok, key=lambda t: t["us"])
                rec["measured_best"] = best
                rec["table_matches_best"] = (
                    best["tier"] == choice.tier
                    and best.get("block_q") == choice.block_q
                    and best.get("block_k") == choice.block_k)
                agreements += bool(rec["table_matches_best"])
            rec["candidates"] = timings
        else:
            dry = autotune.sweep_geometry(key, mode="dry")
            rec["dry_policy"] = (dry.choice.to_dict()
                                 if dry.choice else None)
            rec["table_matches_policy"] = (
                dry.choice is not None
                and dry.choice.tier == choice.tier
                and dry.choice.block_q == choice.block_q
                and dry.choice.block_k == choice.block_k)
            agreements += bool(rec["table_matches_policy"])
        per_geometry.append(rec)

    # interpret-mode parity of the fused tier (CPU-safe, tiny shape):
    # the chain from dispatcher to kernel computes the right numbers
    parity = None
    try:
        import jax.numpy as jnp
        import numpy as np

        from comfyui_distributed_tpu.ops.flash_attention import (
            fused_qkv_attention)

        C, H = 128, 2
        x = jax.random.normal(jax.random.key(0), (1, 200, C))
        ws = [jax.random.normal(jax.random.key(i), (C, C)) / C ** 0.5
              for i in (1, 2, 3)]
        out = fused_qkv_attention(x, *ws, H, interpret=True)
        q, k, v = (jnp.reshape(x @ w, (1, 200, H, C // H)) for w in ws)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (C // H) ** 0.5
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        parity = float(np.abs(np.asarray(out) - np.asarray(ref)).max())
    except Exception as e:  # noqa: BLE001 — parity is evidence, not a gate
        parity = f"error: {e}"

    return {
        "metric": ("attn_ab_table_agreement" if on_tpu
                   else "attn_ab_decisions_cpu"),
        "value": round(agreements / max(len(per_geometry), 1), 4),
        "unit": "fraction",
        "vs_baseline": 1.0,
        "vs_baseline_note": "no published attention A/B baseline",
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", platform),
        "ab_mode": "timed" if on_tpu else "decisions",
        "geometries": len(per_geometry),
        "fused_interpret_parity_max_abs_err": parity,
        "per_geometry": per_geometry,
    }


def run_serving_benchmark(steps: int, runs: int | None,
                          force_cpu: bool) -> dict:
    """Serving front door A/B (ISSUE 9, docs/serving.md): the same R
    requests executed (a) sequentially as R solo programs and (b) as one
    microbatched program (``generate_microbatch``), both warm — the
    speedup is the dispatch/scheduling overhead cross-user batching
    amortizes. Then an in-process front door is driven at fixed offered
    load (tiny preset, real controller + HTTP route) to measure p50/p99
    submit→terminal latency and achieved microbatch occupancy.

    On accel the program A/B uses the SDXL-base architecture at 1024²
    (the headline geometry); on CPU the tiny stack — flagged as usual so
    a toy line can't be mistaken for hardware numbers."""
    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.pipeline import (
        GenerationSpec, Txt2ImgPipeline)
    from comfyui_distributed_tpu.models.text import (TextEncoder,
                                                     TextEncoderConfig)
    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh

    if on_accel:
        unet_cfg, vae_cfg = UNetConfig.sdxl(), VAEConfig.sdxl()
        text_cfg = TextEncoderConfig()
        spec = GenerationSpec(height=1024, width=1024, steps=steps,
                              guidance_scale=5.0)
        lat_hw = (128, 128)
        batch_r = 4
    else:
        unet_cfg, vae_cfg = UNetConfig.tiny(), VAEConfig.tiny()
        text_cfg = TextEncoderConfig.tiny()
        spec = GenerationSpec(height=32, width=32, steps=min(steps, 4),
                              guidance_scale=5.0)
        lat_hw = (16, 16)
        batch_r = 4

    model, params = init_unet(
        unet_cfg, jax.random.key(0),
        sample_shape=(*lat_hw, unet_cfg.in_channels),
        context_len=text_cfg.max_len,
        param_dtype=jnp.bfloat16 if on_accel else None)
    vae = AutoencoderKL(vae_cfg).init(
        jax.random.key(1),
        image_hw=(lat_hw[0] * vae_cfg.downscale,
                  lat_hw[1] * vae_cfg.downscale))
    enc = TextEncoder(text_cfg).init(jax.random.key(2))
    pipe = Txt2ImgPipeline(model, params, vae)
    contexts, unconds = [], []
    for i in range(batch_r):
        c, _ = enc.encode([f"serving bench {i}"])
        u, _ = enc.encode([""])
        contexts.append(c)
        unconds.append(u)
    mesh = build_mesh({"dp": len(jax.devices())})
    seeds = list(range(100, 100 + batch_r))

    y = uy = None
    if unet_cfg.adm_in_channels:
        y = jnp.zeros((1, unet_cfg.adm_in_channels))
        uy = jnp.zeros_like(y)
    ys = None if y is None else [y] * batch_r
    uys = None if uy is None else [uy] * batch_r

    # warm both program shapes (solo + R-bucket), then time
    jax.block_until_ready(pipe.generate(mesh, spec, seeds[0], contexts[0],
                                        unconds[0], y, uy))
    jax.block_until_ready(pipe.generate_microbatch(
        mesh, spec, seeds, contexts, unconds, ys, uys)[0])

    reps = runs or (3 if on_accel else 2)
    seq_times, seq_median = _timed_runs(
        lambda i: [jax.block_until_ready(pipe.generate(
            mesh, spec, seeds[r], contexts[r], unconds[r], y, uy))
            for r in range(batch_r)], reps)
    mb_times, mb_median = _timed_runs(
        lambda i: jax.block_until_ready(pipe.generate_microbatch(
            mesh, spec, seeds, contexts, unconds, ys, uys)[-1]), reps)
    speedup = seq_median / mb_median if mb_median else None

    # fixed offered load against the real front door (tiny preset; the
    # controller path is identical on accel, only the model differs)
    serving = _serving_offered_load()

    return {
        "metric": ("serving_microbatch_speedup" if on_accel
                   else "serving_microbatch_speedup_cpu"),
        "value": round(speedup, 4) if speedup else None,
        "unit": "x (sequential wall / microbatched wall, same R requests)",
        "vs_baseline": 1.0,
        "vs_baseline_note": "no published serving baseline",
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", platform),
        "devices": len(jax.devices()),
        "steps": spec.steps,
        "microbatch_r": batch_r,
        "sequential_wall_s": round(seq_median, 3),
        "microbatch_wall_s": round(mb_median, 3),
        "sequential_times_s": [round(t, 3) for t in seq_times],
        "microbatch_times_s": [round(t, 3) for t in mb_times],
        "offered_load": serving,
    }


def _serving_offered_load(n: int = 16, concurrency: int = 16) -> dict:
    """Drive the real in-process controller (front door enabled) at a
    fixed offered load of same-and-mixed-shape tiny requests; report
    submit→terminal p50/p99 and the achieved mean microbatch size."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
    try:
        import load_smoke
    except ImportError as e:
        return {"error": f"load_smoke unavailable: {e}"}

    # window sized for CPU program times so coalescing actually happens
    # at this offered load; knobs are instance attrs, set post-build
    os.environ.setdefault("CDT_CONFIG_PATH",
                          os.path.join(tempfile.mkdtemp(prefix="cdt_bench_"),
                                       "config.json"))
    reqs = load_smoke.build_workload(7, n, shapes=((32, 2), (48, 2)))
    try:
        stats = asyncio.run(load_smoke._run_in_process(
            reqs, concurrency, wait=True, timeout_s=1800.0))
    except Exception as e:  # noqa: BLE001 — offered-load leg is evidence
        return {"error": str(e)[:300]}
    return {
        "requests": n,
        "concurrency": concurrency,
        "admitted": stats.get("admitted", 0) + stats.get("queued", 0),
        "shed": stats.get("shed"),
        "completed": stats.get("completed"),
        "errors": stats.get("errors"),
        "latency_p50_s": stats.get("latency_p50_s"),
        "latency_p99_s": stats.get("latency_p99_s"),
        "mean_batch_size": (stats.get("metrics") or {}).get(
            "mean_batch_size"),
        "by_tenant": stats.get("by_tenant"),
    }


def run_elastic_benchmark(steps: int, runs: int | None,
                          force_cpu: bool) -> dict:
    """Elastic scale event A/B (ISSUE 10, docs/elasticity.md): a mixed
    two-job tile load driven over the real HTTP control plane — real
    pull/submit wire traffic, real drain route — run (a) with a static
    2-worker fleet and (b) with a fleet that scales up one worker mid-run
    (the steal scheduler hands it pending tiles; its arrival→first-result
    latency is the ``steal_pickup_s`` number) and gracefully drains
    another mid-run. Per-tile compute is one jitted matmul chain keyed on
    the GLOBAL tile index, so both runs must be bit-identical — the
    zero-loss check is part of the bench, not a separate test."""
    import asyncio

    import jax
    import jax.numpy as jnp
    import numpy as np

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api.app import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller
    from comfyui_distributed_tpu.cluster.job_store import JobStore
    from comfyui_distributed_tpu.cluster.tile_farm import (TileFarm,
                                                           assemble_tiles)

    os.environ.setdefault("CDT_CONFIG_PATH",
                          os.path.join(tempfile.mkdtemp(prefix="cdt_bench_"),
                                       "config.json"))
    inner_steps = max(2, min(int(steps), 8))

    @jax.jit
    def _tile_program(x):
        for _ in range(inner_steps):
            x = jnp.tanh(x @ x) + 0.1
        return x

    dim = 128 if on_accel else 32

    def make_proc(marker: float):
        def proc(start, end):
            out = []
            for i in range(start, end):
                x = jnp.full((dim, dim), 0.01 * (i + 1) + marker,
                             jnp.float32)
                out.append(np.asarray(jax.block_until_ready(
                    _tile_program(x))))
            return np.stack(out)
        return proc

    totals = {"sdxl": 24, "usdu": 16}
    procs = {"sdxl": make_proc(0.0), "usdu": make_proc(0.5)}
    # warm the program once so neither leg pays the compile
    jax.block_until_ready(_tile_program(jnp.zeros((dim, dim))))
    # pace each tile so the run is long enough for mid-run events to
    # land while work is pending (a real tile is a multi-second SPMD
    # program; this bench measures the CONTROL PLANE around it)
    pace_s = 0.05

    def paced(fn):
        def proc(start, end):
            time.sleep(pace_s * (end - start))
            return fn(start, end)
        return proc

    paced_procs = {jid: paced(fn) for jid, fn in procs.items()}

    def resolver_for(tag: str):
        """Steal grants carry the full job id ("{tag}-{kind}"); map it
        back to the kind's process_fn."""
        def resolve(job_id: str):
            prefix = f"{tag}-"
            if not job_id.startswith(prefix):
                return None
            return paced_procs.get(job_id[len(prefix):])
        return resolve

    async def drive(elastic: bool, tag: str) -> dict:
        # the lifecycle registry is process-global (like the breakers):
        # a drain from the previous leg must not carry into this one
        from comfyui_distributed_tpu.cluster.elastic.states import DRAIN

        DRAIN.reset()
        controller = Controller()
        client = TestClient(TestServer(create_app(controller)))
        await client.start_server()
        t0 = time.monotonic()
        pickup = {}
        try:
            base = f"http://127.0.0.1:{client.port}"
            loop = asyncio.get_running_loop()

            def steal_worker(wid, resolve=None):
                farm = TileFarm(JobStore(), loop)
                return farm.worker_steal_run_async(
                    wid, base, resolve or resolver_for(tag),
                    idle_polls=3, idle_interval=0.1)

            masters = [asyncio.create_task(
                controller.tile_farm.master_run_async(
                    f"{tag}-{jid}", total=total,
                    process_fn=paced_procs[jid], chunk=1,
                    heartbeat_interval=0.5, worker_timeout=30.0))
                for jid, total in totals.items()]
            await asyncio.sleep(0.05)
            workers = {w: asyncio.create_task(steal_worker(w))
                       for w in ("w0", "w1")}
            if elastic:
                await asyncio.sleep(0.3)
                # mid-run arrival: w2 steals from the open jobs; pickup
                # latency = arrival → its FIRST processed grant
                arrived = time.monotonic()
                first_grant: dict = {}

                base_resolve = resolver_for(tag)

                def recording_resolve(jid):
                    fn = base_resolve(jid)
                    if fn is None:
                        return None

                    def wrapped(start, end):
                        first_grant.setdefault("t", time.monotonic())
                        return fn(start, end)
                    return wrapped

                workers["w2"] = asyncio.create_task(
                    steal_worker("w2", recording_resolve))
                # mid-run graceful departure: drain w1
                async with client.session.post(
                        f"{base}/distributed/worker/w1/drain",
                        json={"deadline_s": 0.5,
                              "stop_process": False}) as r:
                    assert r.status == 200, await r.text()
            results = await asyncio.gather(*masters)
            done = await asyncio.gather(*workers.values())
            if elastic:
                done_by = dict(zip(workers, done))
                if first_grant.get("t"):
                    pickup["steal_pickup_s"] = round(
                        first_grant["t"] - arrived, 3)
                pickup["scaleup_tasks"] = sum(done_by["w2"].values())
            out = {}
            for (jid, total), res in zip(totals.items(), results):
                out[jid] = assemble_tiles(res, total, 1)
            status = {jid: await controller.store.job_status(f"{tag}-{jid}")
                      for jid in totals}
            dead = sum(len(s.get("dead_letter") or [])
                       for s in status.values())
            return {"wall_s": time.monotonic() - t0, "outputs": out,
                    "dead_letters": dead, **pickup}
        finally:
            await client.close()

    def one_rep(i: int) -> dict:
        async def body():
            static = await drive(elastic=False, tag=f"st{i}")
            elastic = await drive(elastic=True, tag=f"el{i}")
            identical = all(
                np.array_equal(static["outputs"][j], elastic["outputs"][j])
                for j in totals)
            return {
                "static_wall_s": round(static["wall_s"], 3),
                "elastic_wall_s": round(elastic["wall_s"], 3),
                "bit_identical": identical,
                "dead_letters": static["dead_letters"]
                + elastic["dead_letters"],
                "steal_pickup_s": elastic.get("steal_pickup_s"),
                "scaleup_tasks": elastic.get("scaleup_tasks", 0),
            }
        return asyncio.run(body())

    reps = runs or 2
    rep_results = [one_rep(i) for i in range(reps)]
    overheads = sorted(r["elastic_wall_s"] / r["static_wall_s"]
                       for r in rep_results)
    median = overheads[len(overheads) // 2]
    pickups = [r["steal_pickup_s"] for r in rep_results
               if r.get("steal_pickup_s") is not None]

    return {
        "metric": ("elastic_scale_event_overhead" if on_accel
                   else "elastic_scale_event_overhead_cpu"),
        "value": round(median, 4),
        "unit": "x (scale-event wall / static-fleet wall, same work)",
        "vs_baseline": 1.0,
        "vs_baseline_note": "no published elastic baseline",
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", platform),
        "devices": len(jax.devices()),
        "steps": inner_steps,
        "jobs": totals,
        "reps": rep_results,
        "steal_pickup_s_best": min(pickups) if pickups else None,
        "all_bit_identical": all(r["bit_identical"] for r in rep_results),
        "total_dead_letters": sum(r["dead_letters"] for r in rep_results),
    }


def _caching_collect_outputs(history: dict, pids: list) -> list:
    """Per-request list of terminal output arrays (sorted by node id) —
    the bit-identity evidence for the caching A/B."""
    import numpy as np

    out = []
    for pid in pids:
        entry = history.get(pid) or {}
        arrays = []
        for nid in sorted((entry.get("outputs") or {})):
            for v in entry["outputs"][nid]:
                if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 3:
                    arrays.append(np.asarray(v))
        out.append(arrays)
    return out


async def _caching_drive(requests: list, cache_on: bool,
                         timeout_s: float) -> dict:
    """Drive one leg of the caching A/B: a REAL in-process controller +
    HTTP route, every request submitted concurrently, waited to terminal.
    Returns wall-clock, completion counts, per-request outputs, and the
    leg's cache stats."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    os.environ["CDT_CACHE"] = "1" if cache_on else "0"
    # fresh persisted tier per leg: the A/B measures THIS leg's cache,
    # not a previous run's leftovers
    os.environ["CDT_CACHE_DIR"] = tempfile.mkdtemp(prefix="cdt_bench_cc_")
    controller = Controller()
    client = TestClient(TestServer(create_app(controller)))
    await client.start_server()
    try:
        async def submit(payload):
            resp = await client.post("/distributed/queue", json=payload)
            body = await resp.json()
            return resp.status, body

        async def wait_done(pid):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                entry = controller.queue.history.get(pid)
                if entry is not None:
                    return entry
                await asyncio.sleep(0.02)
            return {"status": "timeout"}

        # untimed warmup: build the model bundle + compile the program
        # OUTSIDE the measured window (both legs pay it identically; the
        # A/B measures serving throughput, not controller boot)
        warm = dict(requests[0])
        warm["prompt"] = json.loads(json.dumps(warm["prompt"]))
        sampler = next(v for v in warm["prompt"].values()
                       if v["class_type"] == "TPUTxt2Img")
        sampler["inputs"]["seed"] = 999983     # distinct fingerprint
        warm["cache"] = "bypass"
        _, wb = await submit(warm)
        if wb.get("prompt_id"):
            await wait_done(wb["prompt_id"])

        # two waves: wave-1 duplicates land while their twin is in
        # flight (coalescer traffic); wave-2 duplicates of completed
        # wave-1 requests exercise the completed-result tier. Identical
        # structure in both legs, so the A/B stays fair.
        split = max(1, (2 * len(requests)) // 3)
        t0 = time.perf_counter()
        pids: list = []
        entries: list = []
        for wave in (requests[:split], requests[split:]):
            if not wave:
                continue
            results = await asyncio.gather(*(submit(dict(p))
                                             for p in wave))
            wave_pids = [body.get("prompt_id", "") for _, body in results]
            pids.extend(wave_pids)
            entries.extend(await asyncio.gather(
                *(wait_done(p) for p in wave_pids if p)))
        wall = time.perf_counter() - t0
        coalesced = sum(1 for e in entries if e.get("coalesced_with"))
        completed = sum(1 for e in entries if e.get("status") == "success")
        cache_stats = (controller.cache.stats()
                       if controller.cache is not None else None)
        return {
            "wall_s": wall,
            "submitted": len(requests),
            "completed": completed,
            "statuses": sorted({e.get("status") for e in entries}),
            "coalesced": coalesced,
            "result_hits": ((cache_stats or {}).get("result") or {}).get(
                "hit", 0) + ((cache_stats or {}).get("result") or {}).get(
                "disk_hit", 0),
            "hit_rate": (cache_stats or {}).get("hit_rate"),
            "outputs": _caching_collect_outputs(controller.queue.history,
                                                pids),
        }
    finally:
        await client.close()


async def _caching_fleet_drive(waves: list, fleet_on: bool,
                               timeout_s: float) -> dict:
    """One leg of the fleet A/B (ISSUE 17, docs/caching.md): TWO real
    controllers over HTTP, each with its OWN disk tier. ``waves`` is a
    list of submission waves, each a list of ``(worker_idx, payload)``
    — a wave is submitted concurrently and fully drained before the
    next starts, so duplicate placement is CONTROLLED: a dup routed to
    the worker that computed the original is a per-host hit either
    way; a cross-routed dup is a recompute per-host but a ring serve
    with ``fleet_on``. Same waves, same routing — the A/B isolates the
    fleet tier."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    os.environ["CDT_CACHE"] = "1"
    os.environ["CDT_FLEET_CACHE"] = "1" if fleet_on else "0"
    names = ("wA", "wB")
    ctls, clients = [], []
    try:
        for name in names:
            os.environ["CDT_CACHE_DIR"] = tempfile.mkdtemp(
                prefix=f"cdt_bench_fleet_{name}_")
            ctl = Controller()
            client = TestClient(TestServer(create_app(ctl)))
            await client.start_server()
            ctls.append(ctl)
            clients.append(client)
        if fleet_on:
            urls = [str(c.make_url("")).rstrip("/") for c in clients]
            for i, ctl in enumerate(ctls):
                fleet = ctl.cache.fleet
                me, peer, peer_url = (names[i], names[1 - i],
                                      urls[1 - i])
                fleet.self_id = me
                fleet._membership = (lambda me=me, peer=peer, u=peer_url:
                                     {me: None, peer: u})
                with fleet._lock:
                    fleet._ring_cache = None

        async def submit(idx, payload):
            resp = await clients[idx % 2].post("/distributed/queue",
                                               json=payload)
            return idx % 2, await resp.json()

        n_requests = sum(len(w) for w in waves)
        template = waves[0][0][1]

        async def wait_done(idx, pid):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                entry = ctls[idx].queue.history.get(pid)
                if entry is not None:
                    return entry
                await asyncio.sleep(0.02)
            return {"status": "timeout"}

        # untimed warmup on each controller (bundle build + compile)
        for i in range(2):
            warm = dict(template)
            warm["prompt"] = json.loads(json.dumps(warm["prompt"]))
            sampler = next(v for v in warm["prompt"].values()
                           if v["class_type"] == "TPUTxt2Img")
            sampler["inputs"]["seed"] = 999700 + i
            warm["cache"] = "bypass"
            _, wb = await submit(i, warm)
            if wb.get("prompt_id"):
                await wait_done(i, wb["prompt_id"])

        # each wave drains fully before the next submits (a dup wave
        # must see the originals' fills, and intra-wave keys are all
        # distinct so the coalescer can't mask the cache under test);
        # the fleet leg keeps its fire-and-forget fill drain INSIDE
        # the timed window (propagation is part of the serving
        # pipeline, not free)
        t0 = time.perf_counter()
        located: list = []
        entries: list = []
        for wave in waves:
            results = await asyncio.gather(
                *(submit(widx, dict(p)) for widx, p in wave))
            pairs = [(idx, body.get("prompt_id", ""))
                     for idx, body in results]
            located.extend(pairs)
            entries.extend(await asyncio.gather(
                *(wait_done(idx, pid) for idx, pid in pairs if pid)))
            if fleet_on:
                deadline = time.monotonic() + 10
                while (any(c.cache.fleet._pending for c in ctls)
                       and time.monotonic() < deadline):
                    await asyncio.sleep(0.02)
        wall = time.perf_counter() - t0
        outputs = []
        for idx, pid in located:
            outputs.extend(_caching_collect_outputs(
                ctls[idx].queue.history, [pid]))
        out = {
            "wall_s": wall,
            "submitted": n_requests,
            "completed": sum(1 for e in entries
                             if e.get("status") == "success"),
            "served": sum(1 for e in entries
                          if e.get("cache") == "hit"),
            "coalesced": sum(1 for e in entries
                             if e.get("coalesced_with")),
            "outputs": outputs,
        }
        if fleet_on:
            out["remote"] = {name: dict(ctl.cache.fleet.counts)
                             for name, ctl in zip(names, ctls)}
        return out
    finally:
        for client in clients:
            await client.close()


async def _caching_near_leg(steps: int, timeout_s: float) -> dict:
    """Near-tier evidence (ISSUE 17): a ``cache:"near"`` donor parks its
    midpoint; a seed re-roll of the same prompt resumes it for half the
    steps. Reports steps saved and the output delta vs the re-roll's
    OWN exact computation — the delta is nonzero BY DESIGN (the near
    serve re-noises the donor carry under the request's own seed;
    docs/caching.md documents the bound), which is why the tier is
    opt-in per request."""
    import asyncio

    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    os.environ["CDT_CACHE"] = "1"
    os.environ["CDT_FLEET_CACHE"] = "1"
    os.environ["CDT_CACHE_DIR"] = tempfile.mkdtemp(prefix="cdt_bench_near_")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
    import load_smoke

    controller = Controller()
    client = TestClient(TestServer(create_app(controller)))
    await client.start_server()
    try:
        async def run_one(payload):
            resp = await client.post("/distributed/queue", json=payload)
            body = await resp.json()
            pid = body.get("prompt_id")
            deadline = time.monotonic() + timeout_s
            while pid and time.monotonic() < deadline:
                entry = controller.queue.history.get(pid)
                if entry is not None:
                    return entry
                await asyncio.sleep(0.02)
            return {"status": "timeout"}

        prompt = load_smoke.prompt_for(seed=51, text="near bench",
                                       wh=16, steps=steps)
        reroll = json.loads(json.dumps(prompt))
        next(v for v in reroll.values()
             if v["class_type"] == "TPUTxt2Img")["inputs"]["seed"] = 151

        donor = await run_one({"prompt": prompt, "client_id": "bench",
                               "cache": "near"})
        near = await run_one({"prompt": reroll, "client_id": "bench",
                              "cache": "near"})
        exact = await run_one({"prompt": reroll, "client_id": "bench",
                               "cache": "bypass"})
        tier = controller.cache.fleet.near.stats()

        def imgs(entry):
            return _caching_collect_outputs(
                {"x": entry}, ["x"])[0]

        delta = None
        if near.get("cache") == "near":
            pairs = list(zip(imgs(near), imgs(exact)))
            if pairs:
                delta = max(float(np.max(np.abs(
                    a.astype(np.float64) - b.astype(np.float64))))
                    for a, b in pairs)
        return {
            "donor_status": donor.get("status"),
            "near_served": near.get("cache") == "near",
            "reuse": tier.get("reuse", 0),
            "steps_saved": tier.get("steps_saved", 0),
            "total_steps": steps,
            # max|near - exact| over the re-roll's own from-scratch run,
            # in image units (0..1): bounded, never bit-identical
            "max_abs_delta_vs_exact": delta,
        }
    finally:
        await client.close()


def _caching_autoscaler_leg(hit_rate: float) -> dict:
    """Deterministic evidence that cache-hit pressure lowers the
    autoscaler's desired fleet size: the same deep queue evaluated cold
    (hit rate 0) vs hot (the measured rate). Fake clock + fake provider —
    the policy arithmetic is the thing under test."""
    import math

    from comfyui_distributed_tpu.cluster.elastic.autoscaler import (
        AutoscalePolicy, Autoscaler, FleetSignals)

    policy = AutoscalePolicy(min_workers=0, max_workers=8,
                             scale_up_depth=4.0, scale_down_depth=0.5,
                             up_streak=2, down_streak=4)

    class _Provider:
        def __init__(self):
            self.n = 0

        def list_workers(self):
            return {}

        def scale_up(self):
            self.n += 1
            return f"w{self.n}"

        def scale_down(self, wid):
            pass

    def leg(rate: float) -> dict:
        depth = 20
        sig = FleetSignals(queue_depth=depth, tile_depth=0,
                           active_workers=2, cache_hit_rate=rate)
        clock = {"t": 0.0}
        scaler = Autoscaler(lambda: sig, _Provider(), policy,
                            clock=lambda: clock["t"])
        decision = None
        # exactly up_streak ticks: the last one is the acting tick
        for _ in range(policy.up_streak):
            clock["t"] += 60.0
            decision = scaler.evaluate()
        pressure = sig.effective_work / (sig.active_workers + 1)
        return {
            "cache_hit_rate": round(rate, 4),
            "effective_work": round(sig.effective_work, 2),
            "pressure": round(pressure, 3),
            "decision": decision.direction,
            # capacity units needed to bring pressure under the scale-up
            # threshold — the policy's implied fleet size for this load
            "desired_workers": max(policy.min_workers, math.ceil(
                sig.effective_work / policy.scale_up_depth) - 1),
        }

    return {"cold": leg(0.0), "hot": leg(hit_rate)}


def run_caching_benchmark(steps: int, runs: int | None,
                          force_cpu: bool) -> dict:
    """Content-cache offered-load A/B (ISSUE 11, docs/caching.md): the
    SAME seeded dup-rate-0.75 workload (the acceptance floor is ≥0.5)
    driven through the real controller + HTTP route with the cache
    subsystem off, then on. The metric is completed-requests/sec;
    acceptance is ≥2× with every served image bit-identical to the
    uncached run, plus the autoscaler leg showing cache-hit pressure
    lowering the desired fleet size.

    ``CDT_FD_MAX_BATCH=1`` pins microbatching out of both legs so the
    A/B isolates the caching lever (the serving workload already covers
    batching); tiny preset on CPU, same controller path on accel."""
    import asyncio

    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
    import load_smoke

    os.environ.setdefault(
        "CDT_CONFIG_PATH",
        os.path.join(tempfile.mkdtemp(prefix="cdt_bench_"), "config.json"))
    os.environ["CDT_FD_MAX_BATCH"] = "1"
    # n is floored at 16 even under the CPU-fallback runs cap: the tiny
    # programs are cheap warm, and a 16-request mix is the smallest
    # workload where the seeded dup structure is meaningful
    n = max(16, runs or 16)
    # dup-rate 0.75 ≥ the 0.5 acceptance floor; 15% of dups are
    # seed-rerolled near-duplicates (conditioning-tier traffic), the
    # rest byte-identical (coalescer + result-tier traffic)
    dup_rate, near_fraction = 0.75, 0.15
    wh, leg_steps = 24, min(steps, 4)
    requests = load_smoke.build_workload(1, n, shapes=((wh, leg_steps),),
                                         dup_rate=dup_rate,
                                         near_fraction=near_fraction)
    unique_prints = len({json.dumps(r["prompt"], sort_keys=True)
                         for r in requests})

    import numpy as np

    # each leg warms its own controller (bundle build + compile) outside
    # the timed window; the persistent XLA cache makes the second leg's
    # warmup a cache load
    off = asyncio.run(_caching_drive(requests, cache_on=False,
                                     timeout_s=1800.0))
    on = asyncio.run(_caching_drive(requests, cache_on=True,
                                    timeout_s=1800.0))

    # fleet leg (ISSUE 17): dup-rate-0.75 at a HEAVIER shape than the
    # main leg — the harness costs ~0.5s/request regardless of outcome,
    # so the program must dominate for the wall ratio to measure the
    # cache (at production scale the sampler program IS the cost).
    # Duplicate PLACEMENT is controlled: wave 0 computes 6 uniques
    # round-robin, then three dup waves re-request every unique with
    # routing alternated cross/same/cross. Per-host, the first
    # cross-routed dup of each unique RECOMPUTES on the other worker
    # (and refills its local cache, serving the later waves) — the
    # per-host floor is every unique computed once PER WORKER it lands
    # on; the ring computes each unique once for the fleet.
    # Byte-identical dups only: near-dups are the near leg's job below.
    n_uniq, n_dup_waves = 6, 3
    fleet_wh, fleet_steps = 48, 8
    uniq = [load_smoke.prompt_for(seed=4200 + u, text=f"fleet bench {u}",
                                  wh=fleet_wh, steps=fleet_steps)
            for u in range(n_uniq)]
    fleet_waves = [[(u % 2, {"prompt": uniq[u], "client_id": "bench"})
                    for u in range(n_uniq)]]
    for w in range(1, n_dup_waves + 1):
        fleet_waves.append(
            [((u + w) % 2, {"prompt": uniq[u], "client_id": "bench"})
             for u in range(n_uniq)])
    n_fleet = sum(len(wv) for wv in fleet_waves)
    fleet_dup_rate = (n_fleet - n_uniq) / n_fleet
    cross_dups = sum(1 for w in range(1, n_dup_waves + 1)
                     for u in range(n_uniq) if (u + w) % 2 != u % 2)
    def _best_of_two(fleet_on: bool) -> dict:
        # this box shows multi-second scheduling stalls run-to-run;
        # min-wall of two fully independent reps (fresh controllers,
        # fresh cache dirs) keeps the A/B about the cache, not the box
        a = asyncio.run(_caching_fleet_drive(fleet_waves, fleet_on,
                                             timeout_s=1800.0))
        b = asyncio.run(_caching_fleet_drive(fleet_waves, fleet_on,
                                             timeout_s=1800.0))
        return a if a["wall_s"] <= b["wall_s"] else b

    per_host = _best_of_two(fleet_on=False)
    fleet_on_leg = _best_of_two(fleet_on=True)
    fleet_mismatch = 0
    fleet_compared = 0
    for a_arrays, b_arrays in zip(per_host["outputs"],
                                  fleet_on_leg["outputs"]):
        for a, b in zip(a_arrays, b_arrays):
            fleet_compared += 1
            if a.shape != b.shape or not np.array_equal(a, b):
                fleet_mismatch += 1
    ph_rps = (per_host["completed"] / per_host["wall_s"]
              if per_host["wall_s"] else None)
    fl_rps = (fleet_on_leg["completed"] / fleet_on_leg["wall_s"]
              if fleet_on_leg["wall_s"] else None)
    per_host.pop("outputs", None)
    fleet_on_leg.pop("outputs", None)
    fleet_leg = {
        "requests": n_fleet,
        "dup_rate": fleet_dup_rate,
        "cross_worker_dups": cross_dups,
        "shape": [fleet_wh, fleet_steps],
        "reps": 2,
        "per_host": per_host,
        "fleet": fleet_on_leg,
        "completed_rps_per_host": round(ph_rps, 4) if ph_rps else None,
        "completed_rps_fleet": round(fl_rps, 4) if fl_rps else None,
        "speedup": (round(fl_rps / ph_rps, 4)
                    if ph_rps and fl_rps else None),
        # every fleet-served image equals the per-host (recomputed)
        # leg's bytes — remote serves are EXACT-tier serves
        "bit_identical": fleet_mismatch == 0 and fleet_compared > 0,
        "outputs_compared": fleet_compared,
        "output_mismatches": fleet_mismatch,
    }

    near = asyncio.run(_caching_near_leg(steps=4, timeout_s=1800.0))

    # bit-identity: every request's served arrays in the cached leg must
    # equal the uncached leg's, byte for byte
    mismatches = 0
    compared = 0
    for a_arrays, b_arrays in zip(off["outputs"], on["outputs"]):
        for a, b in zip(a_arrays, b_arrays):
            compared += 1
            if a.shape != b.shape or not np.array_equal(a, b):
                mismatches += 1
    off_rps = off["completed"] / off["wall_s"] if off["wall_s"] else None
    on_rps = on["completed"] / on["wall_s"] if on["wall_s"] else None
    speedup = (on_rps / off_rps) if off_rps and on_rps else None

    autoscaler = _caching_autoscaler_leg(on.get("hit_rate") or dup_rate)

    off.pop("outputs", None)
    on.pop("outputs", None)
    return {
        "metric": ("caching_offered_load_speedup" if platform != "cpu"
                   else "caching_offered_load_speedup_cpu"),
        "value": round(speedup, 4) if speedup else None,
        "unit": "x (completed-requests/sec, cache+coalescing vs cache-off, "
                f"same dup-rate-{dup_rate} workload)",
        "vs_baseline": 1.0,
        "vs_baseline_note": "no published caching baseline",
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", platform),
        "devices": len(jax.devices()),
        "requests": n,
        "dup_rate": dup_rate,
        "unique_fingerprints": unique_prints,
        "shape": [wh, leg_steps],
        "fd_max_batch": 1,
        "cache_off": off,
        "cache_on": on,
        "completed_rps_off": round(off_rps, 4) if off_rps else None,
        "completed_rps_on": round(on_rps, 4) if on_rps else None,
        "bit_identical": mismatches == 0 and compared > 0,
        "outputs_compared": compared,
        "output_mismatches": mismatches,
        "autoscaler": autoscaler,
        "fleet": fleet_leg,
        "near": near,
    }


# denoise-program labels (bind_weights): what counts as "the mesh was
# denoising" in the stages A/B — fused programs (decode folded in,
# conservative for the staged claim) and the latent-only stage programs
_DENOISE_LABELS = frozenset({"txt2img", "txt2img_mb", "txt2img_mb_tp",
                             "txt2img_seg", "txt2img_lat",
                             "txt2img_lat_tp"})


def _denoise_program_seconds() -> float:
    """Cumulative wall-clock inside denoise programs (execute + compile)
    from the telemetry registry — callers take deltas around a leg."""
    from comfyui_distributed_tpu.telemetry.registry import REGISTRY

    snap = REGISTRY.snapshot()
    total = 0.0
    for fam_name in ("cdt_pipeline_execute_seconds",
                     "cdt_pipeline_compile_seconds"):
        for s in (snap.get(fam_name) or {}).get("series", []):
            if (s.get("labels") or {}).get("pipeline") in _DENOISE_LABELS:
                total += float(s.get("sum", 0.0))
    return total


async def _stages_drive(requests: list, staged: bool,
                        timeout_s: float) -> dict:
    """One leg of the stages A/B: the same seeded offered load through a
    REAL in-process controller + HTTP route, fused (CDT_STAGES=0) or
    disaggregated. Returns wall, latencies, per-request outputs, the
    denoise-program seconds spent, and the mesh-lane busy seconds the
    occupancy divides by (fused: the one graph-exec consumer; staged:
    the denoise pool)."""
    import asyncio
    import math

    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    os.environ["CDT_STAGES"] = "1" if staged else "0"
    controller = Controller()
    client = TestClient(TestServer(create_app(controller)))
    await client.start_server()
    try:
        async def submit(payload):
            resp = await client.post("/distributed/queue", json=payload)
            return resp.status, await resp.json()

        async def wait_done(pid):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                entry = controller.queue.history.get(pid)
                if entry is not None:
                    return entry
                await asyncio.sleep(0.02)
            return {"status": "timeout"}

        async def drive_wave(wave):
            t_sub = {}

            async def one(payload):
                t0 = time.perf_counter()
                status, body = await submit(dict(payload))
                pid = body.get("prompt_id")
                if status != 200 or not pid:
                    return None, None, None
                entry = await wait_done(pid)
                return pid, entry, time.perf_counter() - t0

            return await asyncio.gather(*(one(p) for p in wave))

        # untimed warmup wave: the SAME shape/group structure with
        # re-rolled seeds, so every bucket program (latent, decode,
        # fused microbatch) compiles OFF the measured clock in both legs
        warm = []
        for r in requests:
            w = json.loads(json.dumps(r))
            sampler = next(v for v in w["prompt"].values()
                           if v["class_type"] == "TPUTxt2Img")
            sampler["inputs"]["seed"] += 100000
            warm.append(w)
        await drive_wave(warm)

        busy0 = (controller.stages.denoise.busy_seconds if staged
                 else controller.queue.busy_seconds)
        den0 = _denoise_program_seconds()
        t0 = time.perf_counter()
        results = await drive_wave(requests)
        wall = time.perf_counter() - t0
        den = _denoise_program_seconds() - den0
        busy = ((controller.stages.denoise.busy_seconds if staged
                 else controller.queue.busy_seconds) - busy0)

        outputs, lat, completed, errors = [], [], 0, 0
        for pid, entry, dt in results:
            entry = entry or {}
            if entry.get("status") == "success":
                completed += 1
                lat.append(dt)
            else:
                errors += 1
            arrays = []
            for nid in sorted(entry.get("outputs") or {}):
                for v in entry["outputs"][nid]:
                    if hasattr(v, "shape"):
                        arrays.append(np.asarray(v))
            outputs.append(arrays)
        lat.sort()

        def pct(q):
            return (round(lat[min(len(lat) - 1,
                                  max(0, math.ceil(q * len(lat)) - 1))], 4)
                    if lat else None)

        leg = {
            "staged": staged,
            "wall_s": round(wall, 3),
            "completed": completed,
            "errors": errors,
            "completed_rps": round(completed / wall, 4) if wall else None,
            "latency_p50_s": pct(0.50),
            "latency_p99_s": pct(0.99),
            "denoise_program_s": round(den, 4),
            "mesh_lane_busy_s": round(busy, 4),
            # THE acceptance number: the share of the mesh-owning
            # lane's busy time spent inside denoise programs. Fused,
            # the lane also encodes and decodes; staged, those moved to
            # their own pools (docs/stages.md)
            "denoise_occupancy": (round(den / busy, 4) if busy else None),
            "denoise_duty_of_wall": (round(den / wall, 4) if wall
                                     else None),
            "outputs": outputs,
        }
        if staged:
            stats = controller.stages.stats()
            leg["pools"] = stats["pools"]
            leg["redispatched"] = stats["redispatched"]
            sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                            "scripts"))
            import load_smoke

            from comfyui_distributed_tpu.telemetry.export import \
                render_json
            from comfyui_distributed_tpu.telemetry.registry import REGISTRY

            occ = load_smoke._occupancy_from_snapshot(
                render_json(REGISTRY.snapshot()))
            # the fused leg never observes cdt_decode_batch_size, so
            # the cumulative histogram is this leg's alone
            leg["mean_decode_batch"] = occ.get("mean_decode_batch")
            leg["mean_batch_size"] = occ.get("mean_batch_size")
        return leg
    finally:
        await client.close()


def run_stages_benchmark(steps: int, runs: int | None,
                         force_cpu: bool) -> dict:
    """Stage-split serving A/B (ISSUE 15, docs/stages.md): the SAME
    seeded mixed-shape offered load through the real controller + HTTP
    route with the fused path (CDT_STAGES=0), then disaggregated.
    Reported per leg: req/s, submit→terminal p50/p99, and the
    denoise-pool occupancy (share of the mesh lane's busy time spent in
    denoise programs — the number the stage split exists to raise);
    plus the decode batch-size histogram mean for the staged leg.
    Acceptance: staged occupancy strictly higher at the same offered
    load, mean decode batch > 1, outputs bit-identical across legs.

    CDT_CACHE=0 pins the content cache out of both legs so the A/B
    isolates the stage-split lever (the caching workload owns that
    one); tiny preset on CPU, same controller path on accel."""
    import asyncio

    import jax
    import numpy as np

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    platform = jax.devices()[0].platform

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "scripts"))
    import load_smoke

    os.environ.setdefault(
        "CDT_CONFIG_PATH",
        os.path.join(tempfile.mkdtemp(prefix="cdt_bench_"), "config.json"))
    os.environ["CDT_CACHE"] = "0"
    n = max(16, runs or 16)
    requests = load_smoke.build_workload(7, n, shapes=((16, 2), (24, 2)))

    fused = asyncio.run(_stages_drive(requests, staged=False,
                                      timeout_s=1800.0))
    staged = asyncio.run(_stages_drive(requests, staged=True,
                                       timeout_s=1800.0))

    mismatches = compared = 0
    for a_arrays, b_arrays in zip(fused["outputs"], staged["outputs"]):
        for a, b in zip(a_arrays, b_arrays):
            compared += 1
            if a.shape != b.shape or not np.array_equal(a, b):
                mismatches += 1
    fused.pop("outputs", None)
    staged.pop("outputs", None)

    occ_f, occ_s = fused["denoise_occupancy"], staged["denoise_occupancy"]
    gain = (round(occ_s / occ_f, 4)
            if occ_f and occ_s else None)
    return {
        "metric": ("stages_denoise_occupancy_gain" if platform != "cpu"
                   else "stages_denoise_occupancy_gain_cpu"),
        "value": gain,
        "unit": "x (denoise-pool occupancy, disaggregated vs fused, "
                "same offered load)",
        "vs_baseline": 1.0,
        "vs_baseline_note": "no published stage-split baseline",
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", platform),
        "devices": len(jax.devices()),
        "requests": n,
        "shapes": [[16, 2], [24, 2]],
        "fused": fused,
        "staged": staged,
        "occupancy_fused": occ_f,
        "occupancy_staged": occ_s,
        "occupancy_strictly_higher": (occ_f is not None
                                      and occ_s is not None
                                      and occ_s > occ_f),
        "mean_decode_batch": staged.get("mean_decode_batch"),
        "bit_identical": mismatches == 0 and compared > 0,
        "outputs_compared": compared,
        "output_mismatches": mismatches,
    }


_WORKLOADS = {
    "txt2img": run_benchmark,
    "usdu": run_usdu_benchmark,
    "flux": run_flux_benchmark,
    "wan": run_wan_benchmark,
    "wan14b": run_wan14b_benchmark,
    "wan22": run_wan22_benchmark,
    "attn": run_attn_benchmark,
    "serving": run_serving_benchmark,
    "elastic": run_elastic_benchmark,
    "caching": run_caching_benchmark,
    "stages": run_stages_benchmark,
}


def _workload_fn(workload: str):
    return _WORKLOADS.get(workload, run_benchmark)


def _inner_main(cli) -> None:
    force_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    result = _workload_fn(cli.workload)(cli.steps, cli.runs, force_cpu)
    _emit(result, cli.out)


def _is_terminal_failure(errors: list[str]) -> bool:
    """True when the last two attempts died with the IDENTICAL error tail:
    a deterministic backend-init failure, not tunnel flake. Retrying it
    burns the whole budget re-running the same crash (BENCH_r05 rc=124
    root cause) — two matching attempts are terminal. Timeout kills are
    exempt: their message is constant by construction (derived from the
    timeout value, not the failure), and a hung tunnel is exactly the
    transient class the retry loop exists to survive."""
    if len(errors) < 2 or not errors[-1] or errors[-1] != errors[-2]:
        return False
    return not errors[-1].startswith("attempt timed out")


def _cap_cpu_fallback(steps: int, runs: "int | None") -> tuple[int, int]:
    """The CPU fallback exists to prove the harness end-to-end, not to
    benchmark a laptop: cap it at tiny-preset scale (≤4 steps, ≤2 runs)
    so it can never eat the remaining wall-clock."""
    return min(int(steps), 4), min(int(runs) if runs else 2, 2)


def _install_partial_result_handler(cli, partial: dict) -> None:
    """An external overall-timeout (``timeout -k`` → SIGTERM) must not
    leave an EMPTY results file: VERDICT r05 found BENCH_r05.json empty
    after rc=124, breaking the perf evidence chain. The handler emits the
    evidence accumulated so far (attempt count, per-attempt error tails)
    as the result JSON before exiting nonzero — a dead backend now leaves
    a diagnosable artifact instead of nothing."""

    def _on_term(signum, frame):
        if partial.get("_final_result_emitted"):
            # a real result already reached cli.out (e.g. `timeout -k`
            # fires during teardown just after success) — exiting without
            # rewriting keeps the good JSON instead of a zeroed partial
            os._exit(128 + int(signum))
        out = dict(partial)
        out.setdefault("metric", "benchmark_partial")
        out.setdefault("value", 0.0)
        out.setdefault("unit", "n/a")
        out.setdefault("vs_baseline", 0.0)
        out["tpu_attempted"] = True
        out["interrupted_by"] = f"signal {signum} (overall timeout?)"
        try:
            _emit(out, cli.out)
        finally:
            # 128+signum mirrors the shell convention; the outer `timeout`
            # reports 124 for its own kills either way
            os._exit(128 + int(signum))

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_term)
        except (ValueError, OSError):   # non-main thread / platform quirk
            pass


def _tpu_preflight(timeout_s: float) -> dict:
    """Probe backend init in a SHORT-LIVED subprocess with its own
    timeout BEFORE committing the full watchdog budget. r06–r09 all
    burned their entire budget hanging inside ``jax.devices()`` in the
    full workload subprocess and then fell back to CPU anyway — this
    answers "is there an accelerator at all?" in ``timeout_s`` seconds,
    and the verdict is recorded in the artifact as ``tpu_preflight``."""
    code = ("import jax; ds = jax.devices(); "
            "print(ds[0].platform, len(ds))")
    t0 = time.monotonic()
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              timeout=timeout_s, capture_output=True,
                              text=True, env=dict(os.environ))
        out = (proc.stdout or "").strip().split()
        ok = proc.returncode == 0 and bool(out)
        err = None
        if not ok:
            tail = (proc.stderr or "").strip().splitlines()
            err = tail[-1] if tail else f"exit code {proc.returncode}"
        return {"attempted": True, "ok": ok,
                "platform": out[0] if ok else None,
                "devices": int(out[1]) if ok and len(out) > 1 else None,
                "seconds": round(time.monotonic() - t0, 2),
                "error": err}
    except subprocess.TimeoutExpired:
        return {"attempted": True, "ok": False, "platform": None,
                "devices": None,
                "seconds": round(time.monotonic() - t0, 2),
                "error": f"backend init hung past {timeout_s:.0f}s "
                         "preflight timeout"}


def _watchdog_main(cli) -> None:
    """Run the accelerator attempt in a subprocess so a hung tunnel (even
    inside ``jax.devices()``) can never prevent a result line; retry
    within the budget — but a repeated IDENTICAL failure is terminal
    after 2 attempts (fail fast with evidence instead of a silent rc=124)
    — then fall back to a tiny-capped CPU run, loudly and explicitly.
    A short preflight probe runs FIRST: a backend that cannot even
    enumerate devices skips the full-budget attempts entirely."""
    from comfyui_distributed_tpu.utils import constants

    budget = constants.BENCH_BUDGET_S.get()
    attempt_timeout = constants.BENCH_ATTEMPT_TIMEOUT_S.get()
    preflight_timeout = constants.BENCH_PREFLIGHT_TIMEOUT_S.get()
    start = time.monotonic()
    attempt = 0
    last_err = None
    errors: list[str] = []
    partial: dict = {"workload": cli.workload, "tpu_attempts": 0,
                     "tpu_errors": errors}
    _install_partial_result_handler(cli, partial)

    preflight = _tpu_preflight(preflight_timeout)
    partial["tpu_preflight"] = preflight
    print(f"[bench] tpu_preflight: {preflight}", file=sys.stderr)

    def emit_final(result: dict) -> None:
        # flag first: once set, a late SIGTERM exits without clobbering
        # the result JSON written below
        partial["_final_result_emitted"] = True
        result.setdefault("tpu_preflight", preflight)
        _emit(result, cli.out)

    def launch(extra_env: dict, timeout: float, steps: "int | None" = None,
               runs: "int | None" = None) -> tuple[int, str]:
        tmp = tempfile.NamedTemporaryFile(
            mode="r", suffix=".json", delete=False)
        env = dict(os.environ, **extra_env)
        cmd = [sys.executable, os.path.abspath(__file__), "--inner",
               "--out", tmp.name,
               "--steps", str(cli.steps if steps is None else steps),
               "--workload", cli.workload]
        runs = cli.runs if runs is None else runs
        if runs:
            cmd += ["--runs", str(runs)]
        try:
            # env must actually reach the child: the CPU fallback's
            # JAX_PLATFORMS=cpu is what stops it hanging in accelerator
            # discovery (r07: without it the fallback timed out exactly
            # like the accelerator attempts it was the fallback FOR)
            proc = subprocess.run(cmd, timeout=timeout, env=env,
                                  capture_output=True, text=True)
            err = (proc.stderr or "").strip().splitlines()
            return proc.returncode, "\n".join(err[-5:])
        except subprocess.TimeoutExpired:
            return -1, f"attempt timed out after {timeout:.0f}s"
        finally:
            tmp_path = tmp.name
            tmp.close()
            # stash for the reader below
            launch.last_tmp = tmp_path  # type: ignore[attr-defined]

    def read_result() -> dict | None:
        path = launch.last_tmp  # type: ignore[attr-defined]
        try:
            with open(path) as f:
                return json.loads(f.read())
        except (OSError, json.JSONDecodeError):
            return None
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass

    accel_possible = (preflight["ok"]
                      and preflight.get("platform") not in (None, "cpu"))
    if not accel_possible:
        # no accelerator behind the backend: spending the watchdog budget
        # re-discovering that (the r06–r09 failure mode) is pure waste —
        # go straight to the capped CPU fallback with the evidence
        last_err = "preflight: " + (preflight.get("error")
                                    or f"platform={preflight.get('platform')}")
        print(f"[bench] skipping accelerator attempts — {last_err}",
              file=sys.stderr)

    while accel_possible and time.monotonic() - start < budget:
        attempt += 1
        remaining = budget - (time.monotonic() - start)
        rc, err_tail = launch({}, min(attempt_timeout, max(60.0, remaining)))
        result = read_result()          # also unlinks the temp file
        if rc != 0:
            result = None
        if result and result.get("platform") not in (None, "cpu"):
            result["tpu_attempted"] = True
            result["tpu_error"] = None
            emit_final(result)
            return
        if result:
            # a machine with no accelerator at all resolves CPU instantly
            # and deterministically — emit the CPU result we already hold
            # instead of burning the budget re-running identical attempts
            last_err = ("inner process silently fell back to CPU "
                        f"(platform={result.get('platform')})")
            print(f"[bench] WARNING: no accelerator available — "
                  f"CPU toy result. {last_err}", file=sys.stderr)
            result["tpu_attempted"] = True
            result["tpu_error"] = last_err
            emit_final(result)
            return
        last_err = err_tail or f"exit code {rc}"
        errors.append(last_err)
        partial["tpu_attempts"] = attempt
        partial["tpu_error"] = last_err
        print(f"[bench] accelerator attempt {attempt} failed: {last_err}",
              file=sys.stderr)
        if _is_terminal_failure(errors):
            # same crash twice = deterministic backend-init failure;
            # emit evidence NOW instead of re-running it for 40 minutes
            print(f"[bench] identical failure on {len(errors)} consecutive "
                  "attempts — terminal; skipping further accelerator "
                  "retries", file=sys.stderr)
            break
        time.sleep(15)

    print(f"[bench] WARNING: no accelerator result after {attempt} attempts "
          f"— tiny CPU fallback. Last error: {last_err}",
          file=sys.stderr)
    partial["phase"] = "cpu_fallback"
    partial["tpu_error"] = last_err
    cpu_steps, cpu_runs = _cap_cpu_fallback(cli.steps, cli.runs)
    rc, err_tail = launch({"JAX_PLATFORMS": "cpu"},
                          min(attempt_timeout, 300.0),
                          steps=cpu_steps, runs=cpu_runs)
    result = read_result()
    if rc != 0:
        result = None
    if result is None:
        emit_final({"metric": "benchmark_failed", "value": 0.0, "unit": "n/a",
                    "vs_baseline": 0.0, "tpu_attempted": True,
                    "tpu_error": last_err, "tpu_attempts": attempt,
                    "cpu_error": err_tail})
        return
    result["tpu_attempted"] = True
    result["tpu_error"] = last_err
    result["tpu_attempts"] = attempt
    emit_final(result)


def _emit(result: dict, out: str | None) -> None:
    line = json.dumps(result)
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")
    print(line)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None,
                        help="also write the JSON result to this path")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--runs", type=int, default=None)
    parser.add_argument("--workload",
                        choices=["txt2img", "usdu", "flux", "wan",
                                 "wan14b", "wan22", "attn", "serving",
                                 "elastic", "caching", "stages"],
                        default="txt2img",
                        help="txt2img (SDXL images/sec), usdu (4K upscale "
                             "wall-clock), flux (flow images/sec), wan "
                             "(t2v wall-clock), wan14b (14B t2v via the "
                             "quantized offload executor), wan22 "
                             "(dual-expert MoE t2v, same geometry as "
                             "wan), attn (per-geometry attention A/B "
                             "from the tuning table), serving (front-door "
                             "microbatch vs sequential + offered-load "
                             "latency, docs/serving.md), elastic "
                             "(scale-event overhead + steal pickup "
                             "latency, docs/elasticity.md), caching "
                             "(content-cache offered-load A/B at "
                             "dup-rate 0.75 + autoscaler pressure leg, "
                             "docs/caching.md)")
    parser.add_argument("--inner", action="store_true",
                        help="(internal) run the measurement in-process")
    cli = parser.parse_args()

    if cli.inner or os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # explicit CPU (test harness) skips the watchdog
        if os.environ.get("JAX_PLATFORMS", "") == "cpu" and not cli.inner:
            result = _workload_fn(cli.workload)(cli.steps, cli.runs,
                                                force_cpu=True)
            result["tpu_attempted"] = False
            result["tpu_error"] = "JAX_PLATFORMS=cpu requested explicitly"
            _emit(result, cli.out)
            return
        _inner_main(cli)
        return
    _watchdog_main(cli)


if __name__ == "__main__":
    main()
