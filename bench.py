#!/usr/bin/env python
"""Benchmark driver: SDXL-class txt2img throughput on the available device.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric matches BASELINE.md: images/sec for SDXL 1024², 30 steps (per chip;
pod scaling multiplies by data-parallel width). The reference publishes no
numbers (BASELINE.json "published": {}), so ``vs_baseline`` is the ratio
against the implied reference performance model: one denoise step per UNet
call, plus the reference's per-result PNG/base64/HTTP overhead which this
framework eliminates on-pod — baselined as 1.0 at parity.

Robustness: if the TPU backend is unreachable (tunnel down), falls back to
CPU with a scaled-down config so the driver always gets a result line;
the JSON then carries "platform": "cpu" for honest bookkeeping.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _try_tpu() -> str:
    """Pick the best available platform; returns its name."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # a pre-registered accelerator platform may have overridden the env
        # var programmatically; honor the explicit request
        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    try:
        devs = jax.devices()
        return devs[0].platform
    except RuntimeError:
        pass
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def main() -> None:
    os.environ.setdefault("XLA_FLAGS", "")
    import jax
    import jax.numpy as jnp

    platform = _try_tpu()
    on_accel = platform not in ("cpu",)

    from comfyui_distributed_tpu.diffusion.pipeline import (
        GenerationSpec, Txt2ImgPipeline)
    from comfyui_distributed_tpu.models.text import TextEncoder, TextEncoderConfig
    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet
    from comfyui_distributed_tpu.models.vae import AutoencoderKL, VAEConfig
    from comfyui_distributed_tpu.parallel import build_mesh

    if on_accel:
        # SDXL-base architecture, 1024² (latent 128²), 30 steps
        unet_cfg = UNetConfig.sdxl()
        vae_cfg = VAEConfig.sdxl()
        text_cfg = TextEncoderConfig()
        spec = GenerationSpec(height=1024, width=1024, steps=30,
                              guidance_scale=5.0, per_device_batch=1)
        lat_hw = (128, 128)
    else:
        unet_cfg = UNetConfig.tiny()
        vae_cfg = VAEConfig.tiny()
        text_cfg = TextEncoderConfig.tiny()
        spec = GenerationSpec(height=32, width=32, steps=30,
                              guidance_scale=5.0, per_device_batch=1)
        lat_hw = (16, 16)

    key = jax.random.key(0)
    model, params = init_unet(
        unet_cfg, key, sample_shape=(*lat_hw, unet_cfg.in_channels),
        context_len=text_cfg.max_len)
    vae = AutoencoderKL(vae_cfg).init(
        jax.random.key(1),
        image_hw=(lat_hw[0] * vae_cfg.downscale, lat_hw[1] * vae_cfg.downscale))
    enc = TextEncoder(text_cfg).init(jax.random.key(2))
    pipe = Txt2ImgPipeline(model, params, vae)
    ctx, pooled = enc.encode(["benchmark prompt"])
    unc, upooled = enc.encode([""])

    n_dev = len(jax.devices())
    mesh = build_mesh({"dp": n_dev})

    import numpy as np

    from comfyui_distributed_tpu.diffusion.pipeline import sdxl_adm

    y = uy = None
    if unet_cfg.adm_in_channels:
        if unet_cfg.adm_in_channels == 2816:
            y = sdxl_adm(pooled, (spec.height, spec.width))
            uy = sdxl_adm(upooled, (spec.height, spec.width))
        else:
            y = jnp.zeros((1, unet_cfg.adm_in_channels))
            uy = jnp.zeros_like(y)

    fn = pipe.generate_fn(mesh, spec)
    args = (jax.random.key(42), ctx, unc,
            y if y is not None else jnp.zeros((1, 1)),
            uy if uy is not None else jnp.zeros((1, 1)))

    # compile + warmup
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0

    # timed runs (median of 5 per protocol in BASELINE.md; 3 on cpu)
    runs = 5 if on_accel else 3
    times = []
    for i in range(runs):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(jax.random.key(i), *args[1:]))
        times.append(time.perf_counter() - t0)
    times.sort()
    median = times[len(times) // 2]
    images = n_dev * spec.per_device_batch
    ips = images / median

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get("images_per_sec")
    except (OSError, json.JSONDecodeError):
        pass
    vs = (ips / baseline) if baseline else 1.0

    print(json.dumps({
        "metric": "sdxl_1024_30step_images_per_sec" if on_accel
                  else "tiny_32_30step_images_per_sec_cpu",
        "value": round(ips, 4),
        "unit": "images/sec",
        "vs_baseline": round(vs, 4),
        "platform": platform,
        "devices": n_dev,
        "median_step_time_s": round(median, 3),
        "compile_s": round(compile_s, 1),
    }))


if __name__ == "__main__":
    main()
