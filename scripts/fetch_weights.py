#!/usr/bin/env python
"""Turnkey weights fetcher: resumable downloads + checksums + convert.

The reference documents an LLM-prompted shell-script recipe for pulling
checkpoint URLs (``/root/reference/docs/model-download-script.md:1``);
this is the first-class equivalent: a registry of the checkpoints each
supported model family needs (HF ``resolve/main`` URLs), a resumable
chunked downloader (HTTP Range + ``.part`` files, atomic rename), sha256
verification, and an optional handoff to the converter
(``python -m comfyui_distributed_tpu convert``) so one command goes from
nothing to TPU-loadable flax stacks:

    python scripts/fetch_weights.py --list
    python scripts/fetch_weights.py sd15 --out weights/
    python scripts/fetch_weights.py flux --out weights/ --convert ckpts/flux
    python scripts/fetch_weights.py --url https://... --dest weights/x.safetensors

Stdlib-only (urllib): runs on a bare TPU-VM image. Where a token is
required (FLUX.1-dev gating), pass ``--hf-token`` or set ``HF_TOKEN``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
import urllib.error
import urllib.request

HF = "https://huggingface.co"

# Checkpoints per model family. ``convert`` is the converter argv suffix
# (docs/weights.md); paths are relative to --out. sha256 is pinned only
# where upstream publishes a stable single revision — HF files can be
# re-uploaded, so most entries verify size>0 + safetensors magic instead.
REGISTRY: dict[str, dict] = {
    "sd15": {
        "about": "Stable Diffusion 1.5 single-file (UNet+VAE+CLIP-L)",
        "files": [
            {"url": f"{HF}/stable-diffusion-v1-5/stable-diffusion-v1-5/"
                    "resolve/main/v1-5-pruned-emaonly.safetensors",
             "dest": "v1-5-pruned-emaonly.safetensors"},
        ],
        "convert": ["--checkpoint", "v1-5-pruned-emaonly.safetensors",
                    "--preset", "sd15"],
    },
    "sdxl": {
        "about": "SDXL base 1.0 single-file (UNet+VAE+CLIP-L+CLIP-G)",
        "files": [
            {"url": f"{HF}/stabilityai/stable-diffusion-xl-base-1.0/"
                    "resolve/main/sd_xl_base_1.0.safetensors",
             "dest": "sd_xl_base_1.0.safetensors"},
        ],
        "convert": ["--checkpoint", "sd_xl_base_1.0.safetensors",
                    "--preset", "sdxl"],
    },
    "flux-schnell": {
        "about": "FLUX.1-schnell (MMDiT + ae + t5xxl + clip-l)",
        "files": [
            {"url": f"{HF}/black-forest-labs/FLUX.1-schnell/resolve/main/"
                    "flux1-schnell.safetensors",
             "dest": "flux1-schnell.safetensors"},
            {"url": f"{HF}/black-forest-labs/FLUX.1-schnell/resolve/main/"
                    "ae.safetensors", "dest": "ae.safetensors"},
            {"url": f"{HF}/comfyanonymous/flux_text_encoders/resolve/main/"
                    "t5xxl_fp16.safetensors", "dest": "t5xxl_fp16.safetensors"},
            {"url": f"{HF}/comfyanonymous/flux_text_encoders/resolve/main/"
                    "clip_l.safetensors", "dest": "clip_l.safetensors"},
        ],
        "convert": ["--checkpoint", "flux1-schnell.safetensors",
                    "--preset", "flux", "--t5", "t5xxl_fp16.safetensors",
                    "--clip-l", "clip_l.safetensors", "--vae", "ae.safetensors"],
    },
    "wan-1.3b": {
        "about": "WAN 2.1 t2v 1.3B (DiT + wan-vae + umt5-xxl)",
        "files": [
            {"url": f"{HF}/Comfy-Org/Wan_2.1_ComfyUI_repackaged/resolve/main/"
                    "split_files/diffusion_models/"
                    "wan2.1_t2v_1.3B_fp16.safetensors",
             "dest": "wan2.1_t2v_1.3B_fp16.safetensors"},
            {"url": f"{HF}/Comfy-Org/Wan_2.1_ComfyUI_repackaged/resolve/main/"
                    "split_files/vae/wan_2.1_vae.safetensors",
             "dest": "wan_2.1_vae.safetensors"},
            {"url": f"{HF}/Comfy-Org/Wan_2.1_ComfyUI_repackaged/resolve/main/"
                    "split_files/text_encoders/"
                    "umt5_xxl_fp8_e4m3fn_scaled.safetensors",
             "dest": "umt5_xxl.safetensors"},
        ],
        "convert": ["--checkpoint", "wan2.1_t2v_1.3B_fp16.safetensors",
                    "--preset", "wan", "--t5", "umt5_xxl.safetensors",
                    "--vae", "wan_2.1_vae.safetensors"],
    },
}

CHUNK = 8 * 1024 * 1024
SAFETENSORS_MAGIC_MAX_HEADER = 100 * 1024 * 1024


def _request(url: str, start: int = 0, token: str | None = None):
    req = urllib.request.Request(url)
    req.add_header("User-Agent", "cdt-fetch/1.0")
    if start:
        req.add_header("Range", f"bytes={start}-")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=60)


def download(url: str, dest: str, sha256: str | None = None,
             token: str | None = None, retries: int = 5,
             progress: bool = True) -> str:
    """Resumable download to ``dest`` (``dest.part`` + atomic rename).
    Returns the file's sha256 hex. Raises on exhausted retries or
    checksum mismatch (the .part is kept for resume; a bad final hash
    deletes it)."""
    if os.path.exists(dest):
        if progress:
            print(f"  [skip] {dest} exists")
        return _sha256_file(dest) if sha256 else ""
    part = dest + ".part"
    os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
    attempt = 0
    while True:
        start = os.path.getsize(part) if os.path.exists(part) else 0
        try:
            with _request(url, start=start, token=token) as resp:
                # a server that ignores Range restarts from zero
                if start and resp.status != 206:
                    start = 0
                total = resp.headers.get("Content-Length")
                total = (int(total) + start) if total else None
                mode = "ab" if start else "wb"
                done = start
                t0 = time.time()
                with open(part, mode) as f:
                    while True:
                        chunk = resp.read(CHUNK)
                        if not chunk:
                            break
                        f.write(chunk)
                        done += len(chunk)
                        if progress and total:
                            pct = 100.0 * done / total
                            mbs = (done - start) / 1e6 / max(
                                time.time() - t0, 1e-9)
                            print(f"\r  {os.path.basename(dest)}: "
                                  f"{pct:5.1f}% ({done / 1e9:.2f} GB, "
                                  f"{mbs:.0f} MB/s)", end="", flush=True)
            if progress:
                print()
            break
        except urllib.error.HTTPError as e:
            if e.code == 416 and start:
                # Range past EOF: the .part is already the complete file
                # (a crash between the loop and the rename) — fall
                # through to checksum + rename
                break
            if e.code in (401, 403, 404):
                raise RuntimeError(
                    f"HTTP {e.code} for {url} — gated repo? pass "
                    "--hf-token / set HF_TOKEN") from e
            attempt += 1
            if attempt > retries:
                raise RuntimeError(
                    f"download failed after {retries} retries: {url} ({e})")
            wait = min(2 ** attempt, 60)
            if progress:
                print(f"\n  [retry {attempt}/{retries} in {wait}s] {e}")
            time.sleep(wait)
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            attempt += 1
            if attempt > retries:
                raise RuntimeError(
                    f"download failed after {retries} retries: {url} ({e})")
            wait = min(2 ** attempt, 60)
            if progress:
                print(f"\n  [retry {attempt}/{retries} in {wait}s] {e}")
            time.sleep(wait)
    digest = _sha256_file(part)
    if sha256 and digest != sha256:
        os.remove(part)
        raise RuntimeError(
            f"sha256 mismatch for {dest}: got {digest}, want {sha256}")
    os.replace(part, dest)
    return digest


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_safetensors(path: str) -> bool:
    """Cheap validity check: 8-byte little-endian header length followed
    by a JSON header (the safetensors container format) — catches HTML
    error pages saved as .safetensors (the classic gated-repo failure)."""
    try:
        with open(path, "rb") as f:
            n = int.from_bytes(f.read(8), "little")
            if not 0 < n < SAFETENSORS_MAGIC_MAX_HEADER:
                return False
            head = f.read(min(n, 1024))
        return head.lstrip()[:1] == b"{"
    except OSError:
        return False


def fetch_model(name: str, out: str, token: str | None = None,
                convert_out: str | None = None, progress: bool = True) -> int:
    entry = REGISTRY[name]
    print(f"[{name}] {entry['about']}")
    manifest = {}
    for spec in entry["files"]:
        dest = os.path.join(out, spec["dest"])
        digest = download(spec["url"], dest, sha256=spec.get("sha256"),
                          token=token, progress=progress)
        if dest.endswith(".safetensors") and not verify_safetensors(dest):
            print(f"  [warn] {dest} does not look like safetensors "
                  "(gated repo HTML error page? pass --hf-token)")
            return 1
        manifest[spec["dest"]] = {"sha256": digest or _sha256_file(dest),
                                  "bytes": os.path.getsize(dest),
                                  "url": spec["url"]}
    with open(os.path.join(out, f"{name}.manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    if convert_out:
        argv = [sys.executable, "-m", "comfyui_distributed_tpu", "convert"]
        for a in entry["convert"]:
            argv.append(os.path.join(out, a)
                        if a.endswith(".safetensors") else a)
        argv += ["--out", convert_out]
        print("  converting:", " ".join(argv))
        import subprocess

        return subprocess.call(argv)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("model", nargs="?", choices=sorted(REGISTRY),
                    help="model family to fetch")
    ap.add_argument("--out", default="weights", help="download directory")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--url", help="ad-hoc: fetch one URL instead")
    ap.add_argument("--dest", help="ad-hoc: destination path for --url")
    ap.add_argument("--sha256", help="ad-hoc: expected digest for --url")
    ap.add_argument("--convert", metavar="CKPT_DIR",
                    help="run the converter into this directory afterwards")
    ap.add_argument("--hf-token", default=os.environ.get("HF_TOKEN"))
    ap.add_argument("--quiet", action="store_true")
    cli = ap.parse_args(argv)

    if cli.list or (not cli.model and not cli.url):
        for name, entry in sorted(REGISTRY.items()):
            total = len(entry["files"])
            print(f"{name:14s} {entry['about']} ({total} files)")
        return 0
    if cli.url:
        dest = cli.dest or os.path.join(
            cli.out, os.path.basename(cli.url.split("?")[0]))
        download(cli.url, dest, sha256=cli.sha256, token=cli.hf_token,
                 progress=not cli.quiet)
        return 0
    return fetch_model(cli.model, cli.out, token=cli.hf_token,
                       convert_out=cli.convert, progress=not cli.quiet)


if __name__ == "__main__":
    sys.exit(main())
