#!/bin/sh
# Waits for the tunneled TPU to come back, then runs the MFU probe
# experiments in sequence, capturing JSON lines to /tmp/probe_*.log.
# (Same pattern as tpu_bench_watcher.py: the tunnel dies for hours at a
# time; measurements must start the moment it returns.)
set -u
cd "$(dirname "$0")/.."
export PYTHONPATH="/root/.axon_site:$(pwd)"
export CDT_PROBE_RUNS="${CDT_PROBE_RUNS:-5}"

while :; do
    if timeout 120 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
        echo "[probe-watcher] TPU reachable — running experiments"
        for exp in batch forward attn; do
            echo "[probe-watcher] $exp"
            timeout 3000 python scripts/mfu_probe.py "$exp" \
                > "/tmp/probe_${exp}.log" 2>&1 || \
                echo "[probe-watcher] $exp failed/timed out"
        done
        exit 0
    fi
    echo "[probe-watcher] TPU unreachable; sleeping"
    sleep 120
done
