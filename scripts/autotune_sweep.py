#!/usr/bin/env python
"""(Re)bake the attention tuning table (``ops/autotune.py``).

    python scripts/autotune_sweep.py --dry-run            # CPU: policy bake
    python scripts/autotune_sweep.py                      # TPU: timed sweep
    python scripts/autotune_sweep.py --bake               # write the
                                                          # in-repo shipped
                                                          # table
    python scripts/autotune_sweep.py --geometry h12.d128.q16384.kv16384.bf16

Default geometry set is the known model zoo
(``autotune.model_zoo_geometries``: SDXL self/cross, FLUX joint, WAN
self/cross). ``--dry-run`` resolves the deterministic legality-ranked
policy and works anywhere (interpret-mode legality only — no timing);
without it the sweep times every candidate on the live backend and
belongs on the TPU host. Every resolved entry is validated
(``autotune.validate_entry``) before writing; exit 1 on any error so a
bad bake can't land in a fleet image.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dry-run", action="store_true",
                    help="deterministic policy resolution (CPU-safe); no "
                         "on-device timing")
    ap.add_argument("--bake", action="store_true",
                    help="write the in-repo shipped table "
                         "(ops/attn_table_default.json) instead of the "
                         "local overlay")
    ap.add_argument("--out", default=None,
                    help="explicit output path (overrides --bake/local)")
    ap.add_argument("--geometry", action="append", default=[],
                    help="geometry key string (h<H>.d<D>.q<Q>.kv<KV>."
                         "<dtype>); repeatable; default: the model zoo")
    ap.add_argument("--mesh", default=None,
                    help="mesh shape ('dp4xtp2', 'tp=2', 'dp=2,tp=4'): "
                         "sweep the PER-SHARD geometries a tp-sharded "
                         "site executes (heads divided by the tp degree) "
                         "instead of the full-H ones")
    ap.add_argument("--runs", type=int, default=3,
                    help="timed-mode runs per candidate")
    cli = ap.parse_args()

    from comfyui_distributed_tpu.ops import autotune

    tp = 1
    if cli.mesh:
        try:
            axes = autotune.parse_mesh_spec(cli.mesh)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        tp = axes.get("tp", 1)

    if not cli.dry_run:
        import jax

        try:
            platform = jax.devices()[0].platform
        except RuntimeError:
            platform = "none"
        if platform != "tpu":
            # a timed sweep off-TPU would "measure" every pallas
            # candidate as a lowering failure and bake an all-xla table
            # that silently loses the flash/fused wins fleet-wide
            print(f"error: timed sweep needs a TPU (platform={platform}); "
                  "use --dry-run for the deterministic policy bake",
                  file=sys.stderr)
            return 1

    if cli.geometry:
        try:
            geometries = [autotune.GeometryKey.from_key_str(g)
                          for g in cli.geometry]
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    else:
        geometries = sorted(autotune.model_zoo_geometries().values())
    if tp > 1:
        sharded = sorted({g.shard(tp) for g in geometries})
        skipped = len(geometries) - len(
            [g for g in geometries if g.num_heads % tp == 0])
        if skipped:
            print(f"note: {skipped} geometry(ies) have head counts not "
                  f"divisible by tp={tp}; swept unsharded", file=sys.stderr)
        geometries = sharded

    mode = "dry" if cli.dry_run else "timed"
    errors = 0
    entries: dict[str, dict] = {}
    for key in geometries:
        entry = autotune.sweep_geometry(key, mode=mode, runs=cli.runs)
        rec = entry.to_dict()
        if entry.choice is None:
            errors += 1
            print(json.dumps({"geometry": key.key_str(), "error":
                              entry.detail or "sweep failed"}), flush=True)
            continue
        problems = autotune.validate_entry(key, entry.choice)
        if problems:
            errors += 1
            rec["legality_errors"] = problems
        print(json.dumps(rec), flush=True)
        if not problems:
            entries[key.key_str()] = entry.choice.to_dict()

    if cli.out:
        out_path = Path(cli.out)
    elif cli.bake:
        out_path = Path(autotune.__file__).parent / "attn_table_default.json"
    else:
        out_path = autotune.table_path()

    if cli.bake or cli.out:
        # full rewrite of a standalone artifact
        payload = {"version": autotune.TABLE_VERSION,
                   "mode": mode, "entries": entries}
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(json.dumps(payload, indent=1) + "\n")
    else:
        # merge into the live local overlay the serving dispatcher reads
        table = autotune.TuningTable(path=out_path, shipped=False)
        for ks, d in entries.items():
            table.record(autotune.GeometryKey.from_key_str(ks),
                         autotune.KernelChoice.from_dict(d, source="sweep"),
                         save=False)
        table.save()
    print(json.dumps({"written": str(out_path), "entries": len(entries),
                      "errors": errors, "mode": mode,
                      "mesh": cli.mesh or None, "tp_shards": tp}),
          flush=True)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
