#!/usr/bin/env bash
# Chaos tier: run every fault-injection test (pytest -m chaos) with a FIXED
# seed so a failure replays exactly (docs/resilience.md).
#
# The fast chaos cases already ride tier-1 (`-m 'not slow'` picks them up);
# this script is the dedicated lane: chaos tests ONLY, slow ones included,
# with the seed pinned and printed so CI logs carry the repro line.
#
# Usage: scripts/chaos_suite.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${CDT_CHAOS_SEED:-42}"
echo "[chaos] fixed seed: ${SEED} (override with CDT_CHAOS_SEED)"
echo "[chaos] repro: CDT_CHAOS_SEED=${SEED} scripts/chaos_suite.sh $*"

exec env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" \
    python -m pytest tests/ -q -m chaos \
    -p no:cacheprovider --continue-on-collection-errors "$@"
