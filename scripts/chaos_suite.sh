#!/usr/bin/env bash
# Chaos tier: run every fault-injection test (pytest -m chaos) with a FIXED
# seed so a failure replays exactly (docs/resilience.md).
#
# The fast chaos cases already ride tier-1 (`-m 'not slow'` picks them up);
# this script is the dedicated lane: chaos tests ONLY, slow ones included,
# with the seed pinned and printed so CI logs carry the repro line.
#
# Usage: scripts/chaos_suite.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

SEED="${CDT_CHAOS_SEED:-42}"
echo "[chaos] fixed seed: ${SEED} (override with CDT_CHAOS_SEED)"
echo "[chaos] repro: CDT_CHAOS_SEED=${SEED} scripts/chaos_suite.sh $*"

# Stage 0 — machine-checked invariants (ISSUE 12 + 20, docs/lint.md):
# cdtlint v2 over the package against the committed baseline — the
# per-function rules (L001/A001/D001/K001/J001) plus the project-wide
# flow rules on the call graph + taint engine (A002 transitive
# async-blocking, L002 lock-held-across-await, D002 interprocedural
# nondeterminism taint, W001 wire/route<->docs/api.md contract). Fails
# on any non-baselined finding AND on a stale or unjustified baseline
# entry (the baseline only shrinks). Then re-run the stage-1 chaos
# event under the runtime lock-order detector (CDT_LOCK_ORDER=1):
# every lock the event path takes records its acquisition order, and an
# inversion fails the test loudly instead of deadlocking a future run.
echo "[chaos] stage 0: cdtlint v2 (call-graph + taint invariants) + lock-order detector"
python -m comfyui_distributed_tpu.lint
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" CDT_LOCK_ORDER=1 \
    python -m pytest tests/ -q -m chaos -k "warm_restarted or lock_order" \
    -p no:cacheprovider --continue-on-collection-errors "$@"

# Stage 1 — seeded rolling-restart event (ISSUE 6): a worker dies
# mid-job holding work; its warm restart (shared compile cache + shape
# catalog) must rejoin with a pure cache-hit warmup pass and the job
# must complete with nothing dropped or dead-lettered.
echo "[chaos] stage 1: rolling-restart event (warm worker rejoin)"
# (filter matches test_warm_restarted_worker_rejoins_without_dropping_jobs;
# the old "rolling_restart" pattern matched nothing and rc=5 aborted the
# whole suite under set -e)
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" \
    python -m pytest tests/ -q -m chaos -k "warm_restarted" \
    -p no:cacheprovider --continue-on-collection-errors "$@"

# Stage 2 — seeded front-door overload event (ISSUE 9, docs/serving.md):
# 4× capacity of seeded mixed-tenant load against a pinned-low shed
# threshold. Asserted: surplus requests get deterministic 429s with
# Retry-After (never hangs), queue depth stays bounded under the
# threshold, zero admitted-job loss, and both tenants complete work.
echo "[chaos] stage 2: front-door overload (shed 429s, zero admitted loss)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" \
    python -m pytest tests/ -q -m chaos -k "overload" \
    -p no:cacheprovider --continue-on-collection-errors "$@"

# Stage 3 — the rest of the chaos tier
echo "[chaos] stage 3: full chaos tier"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" \
    python -m pytest tests/ -q -m chaos \
    -k "not warm_restarted and not overload and not scale_event and not cache_corrupt and not mesh_drain and not preempt and not decode_worker and not fleet_shard" \
    -p no:cacheprovider --continue-on-collection-errors "$@"

# Stage 4 — seeded scale events under live load (ISSUE 10,
# docs/elasticity.md): (a) the chaos-marked acceptance test — a mixed
# two-job run that scales up mid-job (steal pickup), drains one worker
# (deadline handback), and rolling-restarts another (drain → undrain),
# asserting bit-identical outputs vs the static fleet, zero dead-letters,
# and no breaker opening for any intentional departure; (b) load_smoke
# --churn — seeded drain/kill/restart events interleaved with the
# mixed-tenant serving load, exiting 1 on any admitted-job loss or
# unbounded queue depth.
echo "[chaos] stage 4: elastic scale events (scale-up / drain / rolling restart)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" CDT_STEAL_SEED="${SEED}" \
    python -m pytest tests/ -q -m chaos -k "scale_event" \
    -p no:cacheprovider --continue-on-collection-errors "$@"
echo "[chaos] stage 4b: churn load smoke (zero admitted-job loss)"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    python scripts/load_smoke.py --in-process --churn --n 12 \
    --concurrency 8 --seed "${SEED}"

# Stage 5 — persisted-cache corruption under live load (ISSUE 11,
# docs/caching.md): a persisted result-cache entry is byte-flipped while
# a duplicate-heavy load runs. Asserted: the checksum rejects the entry
# LOUDLY (cdt_cache_corrupt_total), the request recomputes, every served
# image is bit-identical to the uncorrupted reference, and zero admitted
# jobs are lost. Then the dup-rate smoke: a seeded duplicate/near-dup
# mix through the real front door, exit 1 on any admitted-job loss.
echo "[chaos] stage 5: cache corruption under load (zero wrong-byte serves)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" \
    python -m pytest tests/ -q -m chaos -k "cache_corrupt" \
    -p no:cacheprovider --continue-on-collection-errors "$@"
echo "[chaos] stage 5b: duplicate-mix load smoke (dup-rate 0.5)"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    CDT_CACHE_DIR="$(mktemp -d)" \
    python scripts/load_smoke.py --in-process --n 12 --dup-rate 0.5 \
    --concurrency 8 --seed "${SEED}"

# Stage 6 — executed mesh tier under drain (ISSUE 13,
# docs/parallelism.md): a worker drains MID mesh-tier batched job (each
# tile runs the dp×tp microbatched program) under the runtime
# lock-order detector. Asserted: bit-identical completion vs the
# uninterrupted reference, zero dead-letters, no breaker opens. The
# excluded-strategy filter note: "mesh_drain" selects the chaos-marked
# TestChaosMeshDrain case in tests/test_mesh_serving.py (stage 3's
# blanket run excludes it via the filter below staying in sync).
echo "[chaos] stage 6: mesh-tier drain (bit-identical, lock-order armed)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" CDT_LOCK_ORDER=1 \
    python -m pytest tests/ -q -m chaos -k "mesh_drain" \
    -p no:cacheprovider --continue-on-collection-errors "$@"

# Stage 7 — step-granular preemption (ISSUE 14, docs/preemption.md):
# (a) the chaos-marked acceptance tests under the runtime lock-order
# detector — a job preempted mid-denoise and resumed locally AND on a
# different worker is bit-identical to an uninterrupted run (zero
# dead-letters, no breaker opens), a preemption landing mid mesh-tier
# batch traffic records zero lock inversions, and a checkpoint that
# cannot restore dead-letters after its bounded retries then completes
# from scratch; (b) load_smoke --preempt — a long video-class job
# churns under a seeded interactive workload, exit 1 unless the long
# job completes, at least one preemption fired, and interactive p99
# stays bounded (the full-residual failure mode this subsystem
# removes). The compile cache dir keeps re-runs warm so one-time
# compiles don't pollute the latency signal.
echo "[chaos] stage 7: preemption (bit-identical resume, bounded interactive p99)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" CDT_LOCK_ORDER=1 \
    python -m pytest tests/ -q -m chaos -k "preempt" \
    -p no:cacheprovider --continue-on-collection-errors "$@"
echo "[chaos] stage 7b: preempt load smoke (interactive p99 under a long job)"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    CDT_COMPILE_CACHE_DIR="${CDT_COMPILE_CACHE_DIR:-/tmp/cdt_xla_cache_chaos}" \
    python scripts/load_smoke.py --in-process --preempt --n 6 \
    --concurrency 4 --seed "${SEED}"

# Stage 8 — stage-split serving under decode-worker death (ISSUE 15,
# docs/stages.md): (a) the chaos-marked acceptance under the runtime
# lock-order detector — a decode-pool worker is killed while holding a
# BATCH of transferred latents; the latents re-dispatch to a surviving
# decoder, every member completes BIT-identically to the fused path,
# zero dead-letters, no breaker opens, zero lock inversions; (b)
# load_smoke --stages — the mixed-tenant load through the three pools,
# exit 1 on any admitted-job loss or a stage backlog past its shed
# threshold. The compile cache dir keeps the latent/decode programs
# warm across re-runs.
echo "[chaos] stage 8: stage-split serving (decode-worker death, bounded backlogs)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" CDT_LOCK_ORDER=1 \
    python -m pytest tests/ -q -m chaos -k "decode_worker" \
    -p no:cacheprovider --continue-on-collection-errors "$@"
echo "[chaos] stage 8b: stages load smoke (three pools, bounded backlogs)"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    CDT_COMPILE_CACHE_DIR="${CDT_COMPILE_CACHE_DIR:-/tmp/cdt_xla_cache_chaos}" \
    python scripts/load_smoke.py --in-process --stages --n 12 \
    --concurrency 8 --seed "${SEED}"

# Stage 9 — fleet cache under shard-owner death (ISSUE 17,
# docs/caching.md): (a) the chaos-marked acceptance under the runtime
# lock-order detector — two real controllers on one consistent-hash
# ring; a duplicate is served REMOTELY from the shard owner's tier,
# then the owner is killed mid dup-heavy load. The survivor recomputes
# BIT-identically (the fallback ladder's last rung), zero admitted-job
# loss, and the dead owner's breaker holds no cache-probe evidence
# (probes are scavenging, not health checks); (b) load_smoke --fleet —
# duplicates routed to the worker that did NOT compute the original,
# exit 1 unless the cross-worker hit rate beats the per-host
# (CDT_FLEET_CACHE=0) baseline.
echo "[chaos] stage 9: fleet cache (shard-owner death, cross-worker serves)"
env JAX_PLATFORMS=cpu CDT_CHAOS_SEED="${SEED}" CDT_LOCK_ORDER=1 \
    python -m pytest tests/ -q -m chaos -k "fleet_shard" \
    -p no:cacheprovider --continue-on-collection-errors "$@"
echo "[chaos] stage 9b: fleet load smoke (cross-worker hit rate beats per-host)"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    CDT_COMPILE_CACHE_DIR="${CDT_COMPILE_CACHE_DIR:-/tmp/cdt_xla_cache_chaos}" \
    python scripts/load_smoke.py --fleet --fleet-n 4 \
    --concurrency 8 --seed "${SEED}"

# Stage 10 — event-loop stall sanitizer (ISSUE 20, docs/lint.md): re-run
# the stage-split and fleet-cache smokes with CDT_LOOP_STALL=1 — every
# asyncio callback is timed (lint/loopstall.py patches Handle._run at
# import) and a sampler thread captures the live stack of any callback
# blocking the loop past CDT_LOOP_STALL_MS. load_smoke exits 1 on ANY
# recorded stall, so the executor discipline the static rules (A001/
# A002) prove on the AST is also proven at runtime under real serving
# load — including blocking work static analysis can't see (C
# extensions, pathological codec inputs). The threshold is held above
# the default: on CI-shared CPU the first-compile XLA callbacks and the
# GIL under 8-way concurrency make sub-100ms guarantees unmeasurable.
echo "[chaos] stage 10: loop-stall sanitizer (stage-split + fleet smokes armed)"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    CDT_COMPILE_CACHE_DIR="${CDT_COMPILE_CACHE_DIR:-/tmp/cdt_xla_cache_chaos}" \
    CDT_LOOP_STALL=1 CDT_LOOP_STALL_MS="${CDT_LOOP_STALL_MS:-250}" \
    python scripts/load_smoke.py --in-process --stages --n 12 \
    --concurrency 8 --seed "${SEED}"
env JAX_PLATFORMS=cpu PYTHONPATH="$(pwd)" \
    CDT_CONFIG_PATH="$(mktemp -d)/config.json" \
    CDT_COMPILE_CACHE_DIR="${CDT_COMPILE_CACHE_DIR:-/tmp/cdt_xla_cache_chaos}" \
    CDT_LOOP_STALL=1 CDT_LOOP_STALL_MS="${CDT_LOOP_STALL_MS:-250}" \
    python scripts/load_smoke.py --fleet --fleet-n 4 \
    --concurrency 8 --seed "${SEED}"
