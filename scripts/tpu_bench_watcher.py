#!/usr/bin/env python
"""Persistent TPU benchmark watcher.

The accelerator tunnel in this environment is flaky: it can refuse
connections, hang ``jax.devices()``, or die mid-compile. This watcher
loops forever (until both artifacts are captured or ``--budget-s`` runs
out): cheap probe first, then the real benchmark runs, each in watchdog
subprocesses so a hung tunnel never wedges the loop.

Artifacts (committed so the numbers survive tunnel outages):
- ``benchmarks/r{N}_tpu.json``        — txt2img images/sec + MFU
- ``benchmarks/r{N}_tpu_usdu.json``   — 4K USDU wall-clock

Usage: ``nohup python scripts/tpu_bench_watcher.py --round 2 &``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_SRC = "import jax; print(jax.devices()[0].platform)"


def probe(timeout_s: float) -> bool:
    """True iff jax.devices() resolves to a non-CPU backend in time."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True, cwd=ROOT,
        )
    except subprocess.TimeoutExpired:
        return False
    last = (out.stdout or "").strip().splitlines()
    return out.returncode == 0 and bool(last) and last[-1] != "cpu"


def captured(path: str) -> bool:
    """True iff the artifact holds a real accelerator result."""
    try:
        with open(path) as f:
            data = json.loads(f.read())
        return data.get("platform") not in (None, "cpu") and data.get("value", 0) > 0
    except (OSError, json.JSONDecodeError):
        return False


def run_bench(workload: str, out_path: str, timeout_s: float) -> bool:
    cmd = [sys.executable, os.path.join(ROOT, "bench.py"), "--inner",
           "--workload", workload, "--out", out_path]
    print(f"[watcher] running {workload} bench (timeout {timeout_s:.0f}s)",
          flush=True)
    try:
        proc = subprocess.run(cmd, timeout=timeout_s, capture_output=True,
                              text=True, cwd=ROOT)
    except subprocess.TimeoutExpired:
        print(f"[watcher] {workload} timed out", flush=True)
        return False
    if proc.returncode != 0:
        tail = "\n".join((proc.stderr or "").strip().splitlines()[-4:])
        print(f"[watcher] {workload} failed:\n{tail}", flush=True)
        return False
    ok = captured(out_path)
    print(f"[watcher] {workload} -> {'CAPTURED' if ok else 'cpu/invalid'}",
          flush=True)
    return ok


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=2)
    ap.add_argument("--budget-s", type=float, default=10 * 3600)
    ap.add_argument("--probe-timeout-s", type=float, default=180)
    ap.add_argument("--bench-timeout-s", type=float, default=3600)
    ap.add_argument("--poll-s", type=float, default=120)
    cli = ap.parse_args()

    bdir = os.path.join(ROOT, "benchmarks")
    os.makedirs(bdir, exist_ok=True)
    targets = [
        ("txt2img", os.path.join(bdir, f"r{cli.round:02d}_tpu.json")),
        ("usdu", os.path.join(bdir, f"r{cli.round:02d}_tpu_usdu.json")),
        ("flux", os.path.join(bdir, f"r{cli.round:02d}_tpu_flux.json")),
        ("wan", os.path.join(bdir, f"r{cli.round:02d}_tpu_wan.json")),
        ("wan14b",
         os.path.join(bdir, f"r{cli.round:02d}_tpu_wan14b.json")),
    ]
    start = time.monotonic()
    while time.monotonic() - start < cli.budget_s:
        todo = [(w, p) for w, p in targets if not captured(p)]
        if not todo:
            print("[watcher] all artifacts captured — done", flush=True)
            return
        if probe(cli.probe_timeout_s):
            print("[watcher] TPU reachable", flush=True)
            for workload, path in todo:
                run_bench(workload, path, cli.bench_timeout_s)
        else:
            print(f"[watcher] TPU unreachable "
                  f"({(time.monotonic() - start) / 60:.0f}m elapsed)",
                  flush=True)
        time.sleep(cli.poll_s)
    print("[watcher] budget exhausted", flush=True)


if __name__ == "__main__":
    main()
