#!/usr/bin/env python
"""Regenerate README.md / BASELINE.md perf tables from benchmarks/*.json.

VERDICT r3 weak #7: the README's perf table and BASELINE's "Achieved"
section drifted from the committed artifacts for two rounds. This makes
them *generated*: the newest round's artifact per workload renders into
the blocks between ``<!-- PERF_TABLE_START/END -->`` markers, and
``tests/test_bench_docs.py`` fails when the committed text differs from
what the artifacts produce.

    python scripts/gen_perf_table.py            # rewrite in place
    python scripts/gen_perf_table.py --check    # exit 1 on drift
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
START, END = "<!-- PERF_TABLE_START -->", "<!-- PERF_TABLE_END -->"

# benchmark file suffix → stable row order
WORKLOADS = ["tpu", "tpu_usdu", "tpu_wan", "tpu_flux", "tpu_wan14b",
             "tpu_wan22"]
# wan14b is an extra capability artifact — its absence is not an error
OPTIONAL_WORKLOADS = {"tpu_wan14b", "tpu_wan22"}


def newest_artifacts() -> dict[str, tuple[int, dict]]:
    """suffix → (round, artifact) for the newest captured round of each
    workload (an outage round may capture a subset; each row shows its
    own provenance)."""
    out: dict[str, tuple[int, dict]] = {}
    for p in sorted((ROOT / "benchmarks").glob("r*_*.json")):
        m = re.match(r"r(\d+)_(.+)\.json$", p.name)
        if not m or m.group(2) not in WORKLOADS:
            continue
        rnd, suffix = int(m.group(1)), m.group(2)
        try:
            data = json.loads(p.read_text())
        except json.JSONDecodeError:
            continue
        if data.get("platform") in (None, "cpu") or not data.get("value"):
            continue
        if suffix not in out or rnd > out[suffix][0]:
            out[suffix] = (rnd, data)
    return out


def _row_txt2img(rnd: int, a: dict) -> str:
    step_ms = a["median_step_time_s"] * 1000
    return (f"| SDXL 1024², {a['steps']} steps, CFG | "
            f"**{a['value']:.3f} images/s** ({step_ms:.0f} ms/step) | "
            f"**{a['mfu'] * 100:.1f}% MFU** "
            f"({a['model_flops_per_image'] / 1e12:.0f} analytic "
            f"TFLOPs/image vs {a['peak_flops_per_chip_bf16'] / 1e12:.0f} "
            f"TFLOP/s bf16 peak) — r{rnd:02d} |")


def _mfu_note(a: dict) -> str:
    """r05+: every workload artifact carries mfu (VERDICT r04 weak #1);
    older artifacts render without it."""
    return f"**{a['mfu'] * 100:.1f}% MFU**, " if a.get("mfu") else ""


def _row_usdu(rnd: int, a: dict) -> str:
    hw = a.get("output_hw", [4096, 4096])
    tps = (f"{a['tiles_per_sec']:.1f} tiles/s, "
           if a.get("tiles_per_sec") else "")
    return (f"| 4K Ultimate SD Upscale (1024²→{hw[0]}², "
            f"{a['tiles']} tiles × {a['steps']} steps) | "
            f"**{a['value']:.1f} s** | {_mfu_note(a)}{tps}chunked "
            f"tile-farm path; a pod shards the tile axis — r{rnd:02d} |")


def _row_wan(rnd: int, a: dict) -> str:
    return (f"| WAN-1.3B t2v, {a['frames']} frames 480×832, "
            f"{a['steps']} steps, CFG | **{a['value']:.1f} s** | "
            f"{_mfu_note(a)}exact WAN "
            f"stack + 3D causal VAE, spatially-tiled decode — r{rnd:02d} |")


def _row_flux(rnd: int, a: dict) -> str:
    if a["metric"].startswith("flux_full_depth_offload"):
        if a.get("fully_resident"):
            step = a.get("per_step_s", 0)
            return (f"| FLUX.1 FULL depth (12B) 1024², single chip, fp8 "
                    f"weight residency | **{a['value']:.4f} images/s** "
                    f"({a['median_image_latency_s']:.0f} s/image, "
                    f"{step:.2f} s/step) | whole quantized block set "
                    f"({a['resident_bytes'] / 1e9:.1f} GB e4m3, "
                    f"per-channel scales) HBM-resident; "
                    f"{_mfu_note(a)}zero bytes "
                    f"streamed per step, one scanned program per forward "
                    f"— r{rnd:02d} |")
        streamed_gb = a.get("streamed_bytes_per_step", 0) / 1e9
        gbps = a.get("host_to_device_gbps", 0)
        return (f"| FLUX.1 FULL depth (12B bf16) 1024², host-offload "
                f"streaming | **{a['value']:.4f} images/s** "
                f"({a['median_image_latency_s']:.0f} s/image) | one chip "
                f"streams {streamed_gb:.1f} GB/step over a measured "
                f"{gbps:.2f} GB/s link (tunneled; real v5e host DMA is "
                f"~10-40× faster, pods run dp×tp) — r{rnd:02d} |")
    return (f"| FLUX-architecture 1024² (half depth, bf16-resident) | "
            f"{a['value']:.3f} images/s | full 12B exceeds one chip's HBM "
            f"— pods run it dp×tp — r{rnd:02d} |")


def _row_wan14b(rnd: int, a: dict) -> str:
    res = a.get("resident_bytes", 0) / 1e9
    streamed = a.get("streamed_bytes_per_step", 0) / 1e9
    return (f"| WAN-2.1 **14B** t2v, 33 frames 480×832, "
            f"{a['steps']} steps, single chip | **{a['value']:.0f} s** "
            f"({a.get('per_step_s', 0):.1f} s/step) | 28 GB bf16 expert "
            f"on one 16 GB chip: {res:.1f} GB fp8-resident, "
            f"{streamed:.1f} GB/step streamed — r{rnd:02d} |")


def _row_wan22(rnd: int, a: dict) -> str:
    return (f"| WAN-2.2-style dual-expert (MoE) t2v, {a['frames']} frames "
            f"480×832, {a['steps']} steps, CFG | **{a['value']:.1f} s** | "
            f"{_mfu_note(a)}two 1.3B-class experts bf16-resident, sigma-boundary "
            f"switch at {a.get('expert_boundary', 0.875)} inside one "
            f"compiled program — measured within noise of the "
            f"single-expert run (the switch is free) — r{rnd:02d} |")


ROWS = {"tpu": _row_txt2img, "tpu_usdu": _row_usdu, "tpu_wan": _row_wan,
        "tpu_flux": _row_flux, "tpu_wan14b": _row_wan14b,
        "tpu_wan22": _row_wan22}


def render_table() -> str:
    arts = newest_artifacts()
    lines = ["| Workload | Result | Notes |", "|---|---|---|"]
    for suffix in WORKLOADS:
        if suffix in arts:
            rnd, a = arts[suffix]
            lines.append(ROWS[suffix](rnd, a))
    return "\n".join(lines)


def splice(path: Path, table: str) -> tuple[str, str]:
    """Return (old_block, new_text) for the marker block in ``path``."""
    text = path.read_text()
    if START not in text or END not in text:
        raise SystemExit(f"{path} is missing {START}/{END} markers")
    pre, rest = text.split(START, 1)
    old, post = rest.split(END, 1)
    new = f"{pre}{START}\n{table}\n{END}{post}"
    return old.strip(), new


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any target is out of date")
    cli = ap.parse_args(argv)
    table = render_table()
    drift = False
    for name in ("README.md", "BASELINE.md"):
        path = ROOT / name
        old, new = splice(path, table)
        if old != table:
            drift = True
            if cli.check:
                print(f"[drift] {name} perf table != benchmarks/ artifacts "
                      "(run scripts/gen_perf_table.py)")
            else:
                path.write_text(new)
                print(f"[updated] {name}")
        elif not cli.check:
            print(f"[ok] {name}")
    return 1 if (drift and cli.check) else 0


if __name__ == "__main__":
    sys.exit(main())
