#!/usr/bin/env python
"""Pre-bake a compile cache + shape catalog for a fleet image.

Runs the same AOT warmup pass a worker runs at boot
(``diffusion/warmup.py``), but as a build step: point it at the cache
directory that ships in the image and every worker booted from that
image starts with ``cache_hit`` for the whole catalog — time-to-ready
drops from full-compile cost to cache-load cost.

    # bake the shipped-workflow catalog for the tiny smoke models
    CDT_COMPILE_CACHE_DIR=/image/xla python scripts/warmup_catalog.py \
        --models tiny,flux-tiny

    # add explicit shapes beyond the workflow catalog
    python scripts/warmup_catalog.py --models sdxl \
        --shape txt2img:sdxl:1024x1024:30 --shape txt2img:sdxl:768x768:25

    # inspect what would warm, without compiling
    python scripts/warmup_catalog.py --dry-run

Exit status: 0 when every non-skipped program warmed (compiled or cache
hit), 1 when any errored — CI can gate an image build on it.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def parse_shape(spec: str):
    """``pipeline:model:WxH:steps[:frames]`` → ProgramKey."""
    from comfyui_distributed_tpu.cluster.shape_catalog import ProgramKey

    parts = spec.split(":")
    if len(parts) not in (4, 5):
        raise argparse.ArgumentTypeError(
            f"bad --shape {spec!r} (want pipeline:model:WxH:steps"
            "[:frames])")
    pipeline, model, wh, steps = parts[:4]
    try:
        w, h = (int(x) for x in wh.lower().split("x"))
        return ProgramKey(pipeline=pipeline, model=model, height=h,
                          width=w, steps=int(steps),
                          frames=int(parts[4]) if len(parts) == 5 else 0)
    except ValueError as e:
        raise argparse.ArgumentTypeError(f"bad --shape {spec!r}: {e}")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="AOT-compile the shape catalog into the persistent "
                    "XLA cache (fleet-image pre-bake)")
    ap.add_argument("--models", default=None,
                    help="csv of model presets eligible to warm "
                         "(default: CDT_WARMUP_MODELS, else everything)")
    ap.add_argument("--workflows-dir", default=None,
                    help="seed the catalog from this directory "
                         "(default: the shipped workflows/)")
    ap.add_argument("--shape", action="append", type=parse_shape,
                    default=[], metavar="P:M:WxH:S[:F]",
                    help="extra program key, e.g. txt2img:sdxl:1024x1024:30")
    ap.add_argument("--catalog", default=None,
                    help="catalog path (default: CDT_SHAPE_CATALOG or "
                         "next to the XLA cache)")
    ap.add_argument("--mesh-devices", type=int, default=None,
                    help="dp width to warm for (default: all devices)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the catalog and exit without compiling")
    cli = ap.parse_args()

    from comfyui_distributed_tpu.cluster.shape_catalog import ShapeCatalog

    catalog = ShapeCatalog(cli.catalog) if cli.catalog else ShapeCatalog()
    catalog.seed_from_workflows(cli.workflows_dir)
    catalog.update(cli.shape)

    if cli.dry_run:
        print(json.dumps({"catalog": str(catalog.path),
                          "entries": [k.to_dict()
                                      for k in catalog.entries()]},
                         indent=1))
        return 0

    import jax

    from comfyui_distributed_tpu.diffusion.warmup import run_warmup
    from comfyui_distributed_tpu.models.registry import ModelRegistry
    from comfyui_distributed_tpu.parallel import build_mesh
    from comfyui_distributed_tpu.utils.compile_cache import (
        active_cache_dir, enable_compile_cache)

    enable_compile_cache(min_compile_secs=0.0)
    n = cli.mesh_devices or len(jax.devices())
    mesh = build_mesh({"dp": n}, jax.devices()[:n])
    models = ([m.strip() for m in cli.models.split(",") if m.strip()]
              if cli.models is not None else None)

    def progress(entry):
        print(f"[warmup] {entry.key.pipeline}:{entry.key.model} "
              f"{entry.key.width}x{entry.key.height} "
              f"steps={entry.key.steps} → {entry.outcome} "
              f"({entry.seconds:.1f}s)"
              + (f" — {entry.detail}" if entry.detail else ""),
              file=sys.stderr, flush=True)

    report = run_warmup(ModelRegistry(), mesh, catalog.entries(),
                        models=models, on_entry=progress)
    catalog.save()
    summary = {
        "cache_dir": active_cache_dir(),
        "catalog": str(catalog.path),
        "programs": len(report),
        "outcomes": {o: sum(e.outcome == o for e in report)
                     for o in ("cache_hit", "compiled", "error",
                               "skipped")},
        "report": [e.to_dict() for e in report],
    }
    print(json.dumps(summary, indent=1))
    return 1 if summary["outcomes"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
