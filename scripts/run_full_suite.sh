#!/bin/sh
# Full-suite runner with per-file timings (VERDICT r3 next #5: one green
# end-to-end run, logged and committed). Runs every test file serially —
# the two-process fault-injection tests must not overlap with compile-
# heavy SPMD files on a small host — and records wall-clock per file plus
# the final tally in full_suite.log (or $1).
#
# Warnings policy: RuntimeWarning-clean. -W error::RuntimeWarning turns
# any RuntimeWarning (e.g. a progress-sink steal) into a failure.
set -u
cd "$(dirname "$0")/.."
LOG="${1:-full_suite.log}"
: > "$LOG"

note() { printf '%s\n' "$*" | tee -a "$LOG"; }

note "# full suite run: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
note "# python: $(python --version 2>&1); host: $(uname -sr)"
suite_start=$(date +%s)
fail=0

for f in tests/test_*.py; do
    t0=$(date +%s)
    if python -m pytest "$f" -q -W error::RuntimeWarning \
        >/tmp/suite_file.log 2>&1; then
        status=ok
    else
        status=FAIL
        fail=1
    fi
    t1=$(date +%s)
    tally=$(tail -n 3 /tmp/suite_file.log | grep -Eo \
        '[0-9]+ (passed|failed|error|skipped)[^,]*' | tr '\n' ' ')
    note "$(printf '%-42s %5ss  %-4s %s' "$f" "$((t1 - t0))" "$status" "$tally")"
    if [ "$status" = FAIL ]; then
        note "---- $f failure tail ----"
        tail -n 40 /tmp/suite_file.log | tee -a "$LOG"
        note "-------------------------"
    fi
done

suite_end=$(date +%s)
note "# total: $(((suite_end - suite_start) / 60))m $(((suite_end - suite_start) % 60))s, exit=$fail"
exit "$fail"
