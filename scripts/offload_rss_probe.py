#!/usr/bin/env python
"""Diagnose the flux-offload host OOM (r04: bench pid killed at 130 GB
RSS during the warmup image).

Streams a block-sized buffer to the device N times with the same
backpressure discipline as ``diffusion/offload.py`` (block on a consumer,
delete the device array) and prints host RSS growth per variant:

    variant none     — stream + delete, no extra hygiene (offload.py today)
    variant gc       — + gc.collect() every K transfers
    variant refresh  — + drop python refs immediately

If RSS grows linearly under 'none' but not 'gc', the tunnel client frees
its host mirror only at gc time → offload.py needs periodic collection.
"""

from __future__ import annotations

import argparse
import gc
import os
import resource
import sys
import time


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def cur_rss_gb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1e6
    return 0.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", choices=["none", "gc", "refresh"],
                    default="none")
    ap.add_argument("--mb", type=int, default=512, help="buffer size")
    ap.add_argument("--n", type=int, default=40, help="transfers")
    ap.add_argument("--gc-every", type=int, default=4)
    cli = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    dev = jax.devices()[0]
    print(f"platform={dev.platform} kind={dev.device_kind}", flush=True)
    host = np.random.default_rng(0).standard_normal(
        (cli.mb, 1024, 256), dtype=np.float32)          # mb MB
    consume = jax.jit(lambda a: jnp.sum(a))

    base = cur_rss_gb()
    print(f"baseline rss={base:.2f} GB", flush=True)
    t0 = time.time()
    for i in range(cli.n):
        arr = jax.device_put(host, dev)
        out = consume(arr)
        jax.block_until_ready(out)                       # backpressure
        arr.delete()
        if cli.variant == "refresh":
            del arr, out
        if cli.variant == "gc" and (i + 1) % cli.gc_every == 0:
            gc.collect()
        if (i + 1) % 5 == 0:
            print(f"i={i+1:3d} rss={cur_rss_gb():.2f} GB "
                  f"(+{cur_rss_gb() - base:.2f}) "
                  f"{(i+1) * cli.mb / 1024 / (time.time() - t0):.2f} GB/s",
                  flush=True)
    gc.collect()
    print(f"final rss={cur_rss_gb():.2f} GB (peak {rss_gb():.2f}) "
          f"streamed {cli.n * cli.mb / 1024:.1f} GB", flush=True)


if __name__ == "__main__":
    main()
