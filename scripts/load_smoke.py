#!/usr/bin/env python
"""Synthetic-load generator for the serving front door (docs/serving.md).

Builds a SEEDED mixed workload — mixed shapes (so several coalescing
groups exist), mixed tenants, mixed priority classes — and drives it at
bounded concurrency against either:

- a live controller (``--url http://host:8288``), or
- an in-process controller (``--in-process``; real tiny-preset compiles
  on CPU — slow the first time, cache-served after).

Prints admission outcomes, per-tenant completion, microbatch occupancy
(from ``/distributed/metrics.json``), and submit→terminal latency
percentiles. The tier-1 test (``tests/test_frontdoor_load.py``) imports
``build_workload``/``run_load`` and drives them against a stubbed
sampler, so the scheduler logic is exercised on every CI run without a
single compile.

Exit status: 0 on a clean run (every admitted request reached a terminal
status), 1 otherwise — the zero-loss guarantee is the smoke check.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import random
import sys
import time
from typing import Any, Callable, Optional


def prompt_for(seed: int, text: str, wh: int, steps: int,
               model: str = "tiny", cfg: float = 2.0,
               sampler: str | None = None) -> dict:
    """A minimal batchable txt2img graph (classifier allowlist only).
    ``sampler`` picks a non-default sampler — a stochastic one (e.g.
    ``dpmpp_2m_sde``) makes the prompt NON-batchable, modeling the solo
    video-class lane the preempt leg exercises."""
    inputs = {
        "model": ["1", 0], "positive": ["2", 0], "negative": ["3", 0],
        "seed": seed, "steps": steps, "cfg": cfg,
        "width": wh, "height": wh}
    if sampler is not None:
        inputs["sampler_name"] = sampler
    return {
        "1": {"class_type": "CheckpointLoader",
              "inputs": {"ckpt_name": model}},
        "2": {"class_type": "CLIPTextEncode",
              "inputs": {"text": text, "clip": ["1", 1]}},
        "3": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "", "clip": ["1", 1]}},
        "4": {"class_type": "TPUTxt2Img", "inputs": inputs},
    }


def build_workload(seed: int, n: int, *,
                   shapes: tuple = ((16, 2), (24, 2)),
                   tenants: tuple = ("tenant-a", "tenant-b"),
                   priorities: tuple = ("interactive", "batch"),
                   model: str = "tiny",
                   dup_rate: float = 0.0,
                   near_fraction: float = 0.5) -> list[dict]:
    """N deterministic ``POST /distributed/queue`` payloads. Same seed →
    same workload, byte for byte — chaos runs replay exactly.

    ``dup_rate`` (0..1) makes that fraction of requests duplicates of an
    earlier one — the production redundancy the content cache exists for
    (docs/caching.md). ``near_fraction`` of the duplicates are
    *near*-duplicates that re-roll only the seed (conditioning-cache
    traffic: same text, new sampling); the rest repeat the earlier
    prompt BYTE-IDENTICALLY (coalescer/result-cache traffic).
    client_id/tenant stay the dup's own — duplicates come from
    *different* users."""
    rng = random.Random(seed)
    out = []
    uniques: list[dict] = []
    for i in range(n):
        tenant = tenants[rng.randrange(len(tenants))]
        priority = priorities[rng.randrange(len(priorities))]
        if uniques and rng.random() < dup_rate:
            base = uniques[rng.randrange(len(uniques))]
            prompt = json.loads(json.dumps(base))   # deep copy
            if rng.random() < near_fraction:
                # near-duplicate: same prompt text/shape, fresh seed
                sampler = next(v for v in prompt.values()
                               if v["class_type"] == "TPUTxt2Img")
                sampler["inputs"]["seed"] = 5000 + i
        else:
            wh, steps = shapes[rng.randrange(len(shapes))]
            prompt = prompt_for(seed=1000 + i, text=f"load {i}",
                                wh=wh, steps=steps, model=model)
            uniques.append(prompt)
        out.append({
            "prompt": prompt,
            "tenant": tenant,
            "priority": priority,
            "client_id": f"load_smoke_{i}",
        })
    return out


def percentile(values: list, q: float) -> float:
    if not values:
        return float("nan")
    s = sorted(values)
    idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
    return s[idx]


async def run_load(submit: Callable[[dict], Any],
                   requests: list[dict], *,
                   concurrency: int = 16,
                   wait_done: Optional[Callable[[str], Any]] = None
                   ) -> dict:
    """Drive ``requests`` through async ``submit(payload) -> (status,
    body)`` at bounded concurrency; optionally await per-id completion
    via ``wait_done(prompt_id) -> terminal history entry``. Returns the
    stats dict the CLI prints."""
    sem = asyncio.Semaphore(concurrency)
    stats: dict = {
        "submitted": 0, "admitted": 0, "queued": 0, "shed": 0,
        "rejected": 0, "completed": 0, "errors": 0, "expired": 0,
        "by_tenant": {}, "latency_s": [],
        "shed_retry_after": [],
    }

    async def one(payload: dict) -> None:
        async with sem:
            t0 = time.monotonic()
            status, body = await submit(payload)
            stats["submitted"] += 1
            tenant = payload.get("tenant", "default")
            per = stats["by_tenant"].setdefault(
                tenant, {"admitted": 0, "shed": 0, "completed": 0})
            if status == 429:
                stats["shed"] += 1
                per["shed"] += 1
                ra = body.get("retry_after_s")
                if ra is not None:
                    stats["shed_retry_after"].append(ra)
                return
            if status != 200 or not body.get("prompt_id"):
                stats["rejected"] += 1
                return
            outcome = body.get("outcome", "admitted")
            stats["admitted" if outcome != "queued" else "queued"] += 1
            per["admitted"] += 1
            if wait_done is None:
                return
            entry = await wait_done(body["prompt_id"])
            stats["latency_s"].append(time.monotonic() - t0)
            final = (entry or {}).get("status")
            if final == "success":
                stats["completed"] += 1
                per["completed"] += 1
            elif final == "expired":
                stats["expired"] += 1
            else:
                stats["errors"] += 1

    await asyncio.gather(*(one(p) for p in requests))
    lat = stats.pop("latency_s")
    stats["latency_p50_s"] = round(percentile(lat, 0.50), 4) if lat else None
    stats["latency_p99_s"] = round(percentile(lat, 0.99), 4) if lat else None
    return stats


# --- seeded churn (ISSUE 10: scale events under live load) ------------------


def build_churn_plan(seed: int, workers: tuple, n_events: int) -> list:
    """Deterministic (worker, event) sequence: each tick flips one
    worker's presence — out via ``drain`` or ``kill`` (seeded pick), back
    via the matching ``undrain`` / ``restart``. Ends with everyone back
    in, so the run's terminal fleet state is clean. Same seed → same
    event schedule, byte for byte."""
    rng = random.Random(seed * 7919 + 13)
    state = {w: "in" for w in workers}
    plan = []
    for _ in range(n_events):
        w = workers[rng.randrange(len(workers))]
        if state[w] == "in":
            kind = ("drain", "kill")[rng.randrange(2)]
            state[w] = kind
        else:
            kind = "undrain" if state[w] == "drain" else "restart"
            state[w] = "in"
        plan.append((w, kind))
    for w, st in state.items():
        if st != "in":
            plan.append((w, "undrain" if st == "drain" else "restart"))
    return plan


async def run_churn(plan: list, act, interval_s: float,
                    depth_probe) -> dict:
    """Apply the churn plan at a fixed cadence while the load runs;
    ``act(worker, kind) -> outcome str`` performs one event,
    ``depth_probe()`` samples the admission depth signal. Returns the
    event log + the max depth observed (the bounded-queue assertion)."""
    log = {"events": [], "max_depth": 0}
    for w, kind in plan:
        await asyncio.sleep(interval_s)
        try:
            outcome = await act(w, kind)
        except Exception as e:  # noqa: BLE001 — churn must not sink the
            # load run; a failed event is itself a reportable outcome
            outcome = f"error: {e}"
        log["events"].append({"worker": w, "event": kind,
                              "outcome": outcome})
        try:
            log["max_depth"] = max(log["max_depth"],
                                   int(await depth_probe()))
        except Exception:  # noqa: BLE001 — depth is decoration
            pass
    return log


# --- preemption leg (ISSUE 14: interactive p99 under a long job) ------------


async def run_preempt_leg(submit, wait_done, preempt_stats, *,
                          seed: int, n: int, long_steps: int,
                          concurrency: int) -> dict:
    """One long video-class job (batch priority, stochastic sampler —
    deliberately NON-batchable, the solo lane video jobs take) churns
    underneath a seeded interactive workload. Asserted by the caller:
    the long job completes, at least one preemption happened, and the
    interactive p99 is a fraction of the long job's wall — i.e. the
    interactive class did NOT eat the long job's residual
    (docs/preemption.md)."""
    # untimed warmup: compile the interactive program off the clock
    warm = {"prompt": prompt_for(1, "warm", 16, 2),
            "priority": "interactive", "client_id": "preempt_warm"}
    _, body = await submit(warm)
    if body.get("prompt_id"):
        await wait_done(body["prompt_id"])

    long_payload = {
        "prompt": prompt_for(seed, "long video-class", 16, long_steps,
                             sampler="dpmpp_2m_sde"),
        "priority": "batch", "tenant": "tenant-video",
        "client_id": "preempt_long"}
    t_long = time.monotonic()
    _, lbody = await submit(long_payload)
    long_id = lbody.get("prompt_id")
    if not long_id:
        return {"error": f"long job rejected: {lbody}"}
    await asyncio.sleep(0.3)      # let it take the slot

    requests = [{"prompt": prompt_for(1000 + i, f"interactive {i}",
                                      16, 2),
                 "priority": "interactive", "tenant": "tenant-int",
                 "client_id": f"preempt_{i}"} for i in range(n)]
    stats = await run_load(submit, requests, concurrency=concurrency,
                           wait_done=wait_done)
    long_entry = await wait_done(long_id) or {}
    stats["long_job"] = {
        "status": long_entry.get("status"),
        "wall_s": round(time.monotonic() - t_long, 3),
        "preemptions": long_entry.get("preemptions", 0),
    }
    try:
        stats["preemption"] = await preempt_stats()
    except Exception:  # noqa: BLE001 — stats are decoration; the
        # long_job entry carries the assertion signal
        stats["preemption"] = {}
    return stats


# --- fleet cache leg (ISSUE 17: cross-worker serves, docs/caching.md) -------


async def _run_fleet_leg(seed: int, n: int, concurrency: int,
                         timeout_s: float) -> dict:
    """Two in-process controllers, each with its OWN disk tier, joined
    into one consistent-hash ring. Wave 1 (originals) lands on worker A;
    wave 2 (byte-identical duplicates) lands on worker B — the routing
    split that per-host caching cannot serve. Run twice: per-host
    baseline (``CDT_FLEET_CACHE=0``) and fleet. The caller exits 1
    unless the fleet leg's cross-worker hit rate beats the baseline."""
    wave = [{"prompt": prompt_for(seed=2000 + i, text=f"fleet {i}",
                                  wh=16, steps=2),
             "client_id": f"fleet_{i}"} for i in range(n)]

    async def leg(fleet_on: bool) -> dict:
        import os
        import tempfile

        from aiohttp.test_utils import TestClient, TestServer

        from comfyui_distributed_tpu.api import create_app
        from comfyui_distributed_tpu.cluster.controller import Controller

        saved = {k: os.environ.get(k)
                 for k in ("CDT_FLEET_CACHE", "CDT_CACHE_DIR")}
        os.environ["CDT_FLEET_CACHE"] = "1" if fleet_on else "0"
        ctls, clients = [], []
        try:
            for name in ("wA", "wB"):
                os.environ["CDT_CACHE_DIR"] = tempfile.mkdtemp(
                    prefix=f"fleet_smoke_{name}_")
                ctl = Controller()
                client = TestClient(TestServer(create_app(ctl)))
                await client.start_server()
                ctls.append(ctl)
                clients.append(client)
            urls = [str(c.make_url("")).rstrip("/") for c in clients]
            if fleet_on:
                names = ("wA", "wB")
                for i, ctl in enumerate(ctls):
                    fleet = ctl.cache.fleet
                    me, peer, peer_url = names[i], names[1 - i], urls[1 - i]
                    fleet.self_id = me
                    fleet._membership = (
                        lambda me=me, peer=peer, u=peer_url:
                        {me: None, peer: u})
                    with fleet._lock:
                        fleet._ring_cache = None

            async def drive(client, ctl, payloads):
                sem = asyncio.Semaphore(concurrency)
                entries: list = []

                async def one(p):
                    async with sem:
                        resp = await client.post("/distributed/queue",
                                                 json=p)
                        body = await resp.json()
                        pid = body.get("prompt_id")
                        if resp.status != 200 or not pid:
                            entries.append({"status": f"rejected "
                                            f"({resp.status})"})
                            return
                        deadline = time.monotonic() + timeout_s
                        while time.monotonic() < deadline:
                            entry = ctl.queue.history.get(pid)
                            if entry is not None and entry.get(
                                    "status") in ("success", "error",
                                                  "interrupted",
                                                  "expired"):
                                entries.append(entry)
                                return
                            await asyncio.sleep(0.05)
                        entries.append({"status": "timeout"})

                await asyncio.gather(*(one(p) for p in payloads))
                return entries

            originals = await drive(clients[0], ctls[0], wave)
            if fleet_on:
                # let fire-and-forget fills land on their ring owners
                deadline = time.monotonic() + 10
                while (ctls[0].cache.fleet._pending
                       and time.monotonic() < deadline):
                    await asyncio.sleep(0.05)
            dups = await drive(clients[1], ctls[1],
                               [dict(p) for p in wave])
            served = sum(1 for e in dups if e.get("cache") == "hit")
            out = {
                "requests": len(wave) * 2,
                "completed": sum(1 for e in originals + dups
                                 if e.get("status") == "success"),
                "dup_cache_hits": served,
                "cross_worker_hit_rate": round(served / len(wave), 3),
            }
            if fleet_on:
                out["fleet"] = {
                    name: dict(ctl.cache.fleet.counts)
                    for name, ctl in zip(("wA", "wB"), ctls)}
            return out
        finally:
            for client in clients:
                await client.close()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    baseline = await leg(fleet_on=False)
    fleet = await leg(fleet_on=True)
    return {"baseline": baseline, "fleet": fleet}


# --- transports -------------------------------------------------------------


async def _run_http(url: str, requests: list[dict], concurrency: int,
                    wait: bool, timeout_s: float,
                    churn: Optional[dict] = None,
                    preempt: Optional[dict] = None,
                    stages: bool = False) -> dict:
    import aiohttp

    async with aiohttp.ClientSession() as session:
        stage_sampler = None
        stage_probe = {"stop": False, "max_depths": {}}
        if stages:
            async def get_depths():
                async with session.get(f"{url}/distributed/stages") as r:
                    body = await r.json()
                return {name: p.get("depth", 0)
                        for name, p in (body.get("pools") or {}).items()}

            stage_sampler = asyncio.ensure_future(
                _sample_stage_depths(get_depths, stage_probe))

        async def submit(payload):
            async with session.post(f"{url}/distributed/queue",
                                    json=payload) as resp:
                try:
                    body = await resp.json()
                except Exception:  # noqa: BLE001 — non-JSON error body
                    body = {}
                return resp.status, body

        async def wait_done(prompt_id):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                async with session.get(
                        f"{url}/distributed/history/{prompt_id}") as resp:
                    if resp.status == 200:
                        body = await resp.json()
                        if body.get("status") in ("success", "error",
                                                  "interrupted", "expired"):
                            return body
                await asyncio.sleep(0.2)
            return {"status": "timeout"}

        churn_task = None
        if churn:
            _ROUTES = {
                "drain": ("/distributed/worker/{w}/drain",
                          {"deadline_s": 2.0, "stop_process": False}),
                "undrain": ("/distributed/worker/{w}/undrain", {}),
                "kill": ("/distributed/stop_worker", None),
                "restart": ("/distributed/launch_worker", None),
            }

            async def act(w, kind):
                path, body = _ROUTES[kind]
                payload = body if body is not None else {"worker_id": w}
                async with session.post(
                        url + path.format(w=w), json=payload) as resp:
                    return f"http {resp.status}"

            async def depth_probe():
                async with session.get(f"{url}/distributed/frontdoor") as r:
                    return (await r.json()).get("depth", 0)

            churn_task = asyncio.ensure_future(run_churn(
                churn["plan"], act, churn["interval_s"], depth_probe))
        if preempt is not None:
            async def preempt_stats():
                async with session.get(
                        f"{url}/distributed/preemption") as r:
                    return await r.json() if r.status == 200 else {}

            stats = await run_preempt_leg(
                submit, wait_done, preempt_stats, seed=preempt["seed"],
                n=preempt["n"], long_steps=preempt["long_steps"],
                concurrency=concurrency)
        else:
            stats = await run_load(submit, requests,
                                   concurrency=concurrency,
                                   wait_done=wait_done if wait else None)
        if churn_task is not None:
            stats["churn"] = await churn_task
        stats["metrics"] = await _fetch_occupancy(session, url)
        if stage_sampler is not None:
            stage_probe["stop"] = True
            await stage_sampler
            try:
                async with session.get(f"{url}/distributed/stages") as r:
                    stats["stages"] = {
                        "max_depths": stage_probe["max_depths"],
                        **(await r.json()),
                    }
            except Exception:  # noqa: BLE001 — stats are decoration
                stats["stages"] = {
                    "max_depths": stage_probe["max_depths"]}
        return stats


def _occupancy_from_snapshot(snap: dict) -> dict:
    """``{batch_programs, mean_batch_size, cache_hits, coalesce_width,
    mean_decode_batch}`` from a metrics.json-shaped snapshot — shared by
    the HTTP and in-process modes (and consumed by bench.py's
    serving/caching/stages workloads) so the definitions can't drift."""
    metrics = snap.get("metrics") or {}
    fam = metrics.get("cdt_batch_size") or {}
    series = fam.get("series") or []
    total = sum(s.get("count", 0) for s in series)
    ssum = sum(s.get("sum", 0) for s in series)
    out = {"batch_programs": total,
           "mean_batch_size": round(ssum / total, 3) if total else None}
    hits = (metrics.get("cdt_cache_hits_total") or {}).get("series") or []
    out["cache_hits"] = {
        (s.get("labels") or {}).get("tier", ""): s.get("value", 0)
        for s in hits} or None
    cw = (metrics.get("cdt_coalesce_width") or {}).get("series") or []
    n = sum(s.get("count", 0) for s in cw)
    w = sum(s.get("sum", 0) for s in cw)
    out["coalesce_width"] = round(w / n, 3) if n else None
    db = (metrics.get("cdt_decode_batch_size") or {}).get("series") or []
    dn = sum(s.get("count", 0) for s in db)
    dw = sum(s.get("sum", 0) for s in db)
    out["mean_decode_batch"] = round(dw / dn, 3) if dn else None
    return out


async def _sample_stage_depths(get_depths, out: dict,
                               interval_s: float = 0.1) -> None:
    """Background sampler for the ``--stages`` leg: track the max
    backlog each stage pool ever showed — the bounded-queue assertion
    (any stage past CDT_STAGE_SHED_DEPTH is overload the admission
    layer failed to shed)."""
    while not out.get("stop"):
        try:
            depths = await get_depths()
            for k, v in (depths or {}).items():
                out["max_depths"][k] = max(out["max_depths"].get(k, 0),
                                           int(v))
        except Exception:  # noqa: BLE001 — sampling is decoration
            pass
        await asyncio.sleep(interval_s)


async def _fetch_occupancy(session, url: str) -> dict:
    try:
        async with session.get(f"{url}/distributed/metrics.json") as resp:
            snap = await resp.json()
    except Exception:  # noqa: BLE001 — metrics are optional decoration
        return {}
    return _occupancy_from_snapshot(snap)


async def _run_in_process(requests: list[dict], concurrency: int,
                          wait: bool, timeout_s: float,
                          churn: Optional[dict] = None,
                          preempt: Optional[dict] = None,
                          stages: bool = False) -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    from comfyui_distributed_tpu.api import create_app
    from comfyui_distributed_tpu.cluster.controller import Controller

    controller = Controller()
    client = TestClient(TestServer(create_app(controller)))
    await client.start_server()
    stage_sampler = None
    stage_probe = {"stop": False, "max_depths": {}}
    try:
        if stages and controller.stages is not None:
            async def get_depths():
                return controller.stages.depths()

            stage_sampler = asyncio.ensure_future(
                _sample_stage_depths(get_depths, stage_probe))

        async def submit(payload):
            resp = await client.post("/distributed/queue", json=payload)
            try:
                body = await resp.json()
            except Exception:  # noqa: BLE001 — non-JSON error body
                body = {}
            return resp.status, body

        async def wait_done(prompt_id):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                entry = controller.queue.history.get(prompt_id)
                # a "preempted"/"resume_*" row is non-terminal (docs/
                # preemption.md): the job is parked and will resume —
                # keep waiting exactly like the HTTP poller does
                if entry is not None and entry.get("status") in (
                        "success", "error", "interrupted", "expired"):
                    return entry
                await asyncio.sleep(0.05)
            return {"status": "timeout"}

        churn_task = None
        if churn:
            # no real worker processes in-process: drain/undrain drive
            # the REAL elastic registry (admission's healthy-fraction
            # sees them), kill/restart the REAL breaker registry — the
            # master-side state machines the scale events exercise
            from comfyui_distributed_tpu.cluster.elastic.states import DRAIN
            from comfyui_distributed_tpu.cluster.resilience import BREAKERS

            async def act(w, kind):
                if kind == "drain":
                    controller.elastic.coordinator.begin(
                        w, deadline_s=2.0, stop_process=False)
                elif kind == "undrain":
                    controller.elastic.coordinator.undrain(w)
                elif kind == "kill":
                    BREAKERS.trip(w)
                else:   # restart
                    BREAKERS.record(w, True)
                    DRAIN.reactivate(w)
                return "ok"

            async def depth_probe():
                fd = controller.frontdoor
                return (fd.depth() if fd is not None
                        else controller.queue.queue_remaining)

            churn_task = asyncio.ensure_future(run_churn(
                churn["plan"], act, churn["interval_s"], depth_probe))
        if preempt is not None:
            async def preempt_stats():
                pre = controller.preemption
                return pre.stats() if pre is not None else {}

            stats = await run_preempt_leg(
                submit, wait_done, preempt_stats, seed=preempt["seed"],
                n=preempt["n"], long_steps=preempt["long_steps"],
                concurrency=concurrency)
        else:
            stats = await run_load(submit, requests,
                                   concurrency=concurrency,
                                   wait_done=wait_done if wait else None)
        if churn_task is not None:
            stats["churn"] = await churn_task
        from comfyui_distributed_tpu import telemetry

        if telemetry.enabled():
            from comfyui_distributed_tpu.telemetry.export import render_json
            from comfyui_distributed_tpu.telemetry.registry import REGISTRY

            stats["metrics"] = _occupancy_from_snapshot(
                render_json(REGISTRY.snapshot()))
        # mesh-lane accounting the stages A/B divides by (bench.py)
        stats["queue_busy_seconds"] = round(
            controller.queue.busy_seconds, 4)
        if stage_sampler is not None:
            stage_probe["stop"] = True
            await stage_sampler
            stats["stages"] = {
                "max_depths": stage_probe["max_depths"],
                **controller.stages.stats(),
            }
        return stats
    finally:
        await client.close()


def _check_loop_stalls() -> int:
    """When ``CDT_LOOP_STALL=1`` armed the event-loop stall sanitizer
    (lint/loopstall.py latches it at import, patching every loop
    callback), any recorded stall fails the smoke with the offending
    stack — the chaos suite re-runs the stage-split and fleet legs
    under it."""
    from comfyui_distributed_tpu.lint import loopstall

    if not loopstall.enabled():
        return 0
    stalls = loopstall.snapshot()["stalls"]
    if not stalls:
        print(f"[loopstall] armed (threshold "
              f"{loopstall.threshold_ms():.0f} ms): zero stalls recorded",
              file=sys.stderr)
        return 0
    worst = max(stalls, key=lambda s: s["duration_ms"])
    print(f"EVENT-LOOP STALLS: {len(stalls)} callback(s) blocked the "
          f"loop past {loopstall.threshold_ms():.0f} ms; worst "
          f"{worst['duration_ms']:.0f} ms in {worst['callback']}\n"
          f"{worst['stack']}", file=sys.stderr)
    return 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--url", default=None,
                    help="target controller base URL (default: in-process)")
    ap.add_argument("--in-process", action="store_true",
                    help="spin a controller in this process (tiny preset)")
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--dup-rate", type=float, default=0.0,
                    help="fraction of requests that duplicate an earlier "
                         "one (alternating byte-identical and "
                         "seed-rerolled near-duplicates) — the content "
                         "cache's traffic shape (docs/caching.md)")
    ap.add_argument("--no-wait", action="store_true",
                    help="submit only; skip waiting for completion")
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--churn", action="store_true",
                    help="interleave seeded worker drain/kill/restart "
                         "events with the load (ISSUE 10 scale events); "
                         "exit 1 on any admitted-job loss or unbounded "
                         "queue depth")
    ap.add_argument("--churn-workers", default="w1,w2",
                    help="comma-separated worker ids the churn events hit")
    ap.add_argument("--churn-events", type=int, default=6)
    ap.add_argument("--churn-interval-s", type=float, default=0.3)
    ap.add_argument("--preempt", action="store_true",
                    help="preemption leg (ISSUE 14): a long video-class "
                         "job churns under --n interactive requests; "
                         "exit 1 unless the long job completes, at "
                         "least one preemption fired, and interactive "
                         "p99 stays under the budget")
    ap.add_argument("--stages", action="store_true",
                    help="stage-split leg (ISSUE 15, docs/stages.md): "
                         "drive the mixed-tenant load through the "
                         "encode/denoise/decode pools, sampling each "
                         "pool's backlog; exit 1 on admitted-job loss "
                         "or any stage queue exceeding its shed "
                         "threshold (CDT_STAGE_SHED_DEPTH)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet cache leg (ISSUE 17, docs/caching.md): "
                         "two in-process controllers with separate disk "
                         "tiers on one consistent-hash ring; duplicates "
                         "are routed to the worker that did NOT compute "
                         "the original. Exit 1 on admitted-job loss or "
                         "unless the cross-worker hit rate beats the "
                         "per-host (CDT_FLEET_CACHE=0) baseline")
    ap.add_argument("--fleet-n", type=int, default=6,
                    help="originals per wave in the --fleet leg (each "
                         "is a real tiny-preset generation)")
    ap.add_argument("--preempt-long-steps", type=int, default=48)
    ap.add_argument("--preempt-p99-budget-s", type=float, default=None,
                    help="interactive p99 ceiling (default: "
                         "max(10s, 0.6x the long job's wall) — failing "
                         "means interactive requests ate the long "
                         "job's residual)")
    cli = ap.parse_args()

    if not 0.0 <= cli.dup_rate <= 1.0:
        print("--dup-rate must be in [0, 1]", file=sys.stderr)
        return 2
    if cli.fleet:
        stats = asyncio.run(_run_fleet_leg(cli.seed, cli.fleet_n,
                                           cli.concurrency,
                                           cli.timeout_s))
        print(json.dumps(stats, indent=2, default=str))
        for name in ("baseline", "fleet"):
            leg = stats[name]
            if leg["completed"] != leg["requests"]:
                print(f"LOSS ({name}): {leg['requests']} accepted but "
                      f"only {leg['completed']} completed",
                      file=sys.stderr)
                return 1
        base_rate = stats["baseline"]["cross_worker_hit_rate"]
        fleet_rate = stats["fleet"]["cross_worker_hit_rate"]
        if fleet_rate <= base_rate:
            print(f"NO FLEET WIN: cross-worker hit rate {fleet_rate} "
                  f"does not beat per-host baseline {base_rate}",
                  file=sys.stderr)
            return 1
        return _check_loop_stalls()
    requests = build_workload(cli.seed, cli.n, dup_rate=cli.dup_rate)
    wait = not cli.no_wait
    churn = None
    if cli.churn:
        workers = tuple(w for w in cli.churn_workers.split(",") if w)
        churn = {"plan": build_churn_plan(cli.seed, workers,
                                          cli.churn_events),
                 "interval_s": cli.churn_interval_s}
    preempt = None
    if cli.preempt:
        import os

        # the leg wants tight segments so a preemption fires within a
        # couple of steps; an operator-provided value wins
        os.environ.setdefault("CDT_PREEMPT_SEGMENT_STEPS", "2")
        preempt = {"seed": cli.seed, "n": cli.n,
                   "long_steps": cli.preempt_long_steps}
    if cli.url:
        stats = asyncio.run(_run_http(cli.url, requests, cli.concurrency,
                                      wait, cli.timeout_s, churn=churn,
                                      preempt=preempt, stages=cli.stages))
    else:
        stats = asyncio.run(_run_in_process(requests, cli.concurrency,
                                            wait, cli.timeout_s,
                                            churn=churn, preempt=preempt,
                                            stages=cli.stages))
    print(json.dumps(stats, indent=2, default=str))
    accepted = stats["admitted"] + stats["queued"]
    accounted = (stats["completed"] + stats["errors"] + stats["expired"])
    if wait and accounted != accepted:
        print(f"LOSS: {accepted} accepted but only {accounted} reached a "
              f"terminal status", file=sys.stderr)
        return 1
    if wait and stats["errors"]:
        print(f"{stats['errors']} request(s) errored", file=sys.stderr)
        return 1
    if cli.churn:
        from comfyui_distributed_tpu.utils import constants

        max_depth = (stats.get("churn") or {}).get("max_depth", 0)
        if max_depth > constants.FD_SHED_DEPTH:
            print(f"UNBOUNDED DEPTH: observed {max_depth} > shed "
                  f"threshold {constants.FD_SHED_DEPTH}", file=sys.stderr)
            return 1
    if cli.stages:
        from comfyui_distributed_tpu.utils import constants

        shed = constants.STAGE_SHED_DEPTH.get()
        stage_stats = stats.get("stages") or {}
        # HTTP mode answers {"enabled": false} when the server runs
        # CDT_STAGES=0 — a truthy dict, so the presence check alone
        # would pass vacuously without ever exercising the pools
        if not stage_stats or stage_stats.get("enabled") is False:
            print("NO STAGE STATS: --stages leg ran without the stage "
                  "pools (CDT_STAGES=0?)", file=sys.stderr)
            return 1
        max_depths = stage_stats.get("max_depths") or {}
        over = {k: v for k, v in max_depths.items() if v > shed}
        if over:
            print(f"STAGE BACKLOG PAST SHED THRESHOLD ({shed}): {over}",
                  file=sys.stderr)
            return 1
    if cli.preempt:
        lj = stats.get("long_job") or {}
        if lj.get("status") != "success":
            print(f"LONG JOB DID NOT COMPLETE: {lj}", file=sys.stderr)
            return 1
        preempted = (stats.get("preemption") or {}).get(
            "preempted", lj.get("preemptions", 0))
        if not preempted:
            print("NO PREEMPTION OBSERVED: the long job held its slot "
                  "end-to-end", file=sys.stderr)
            return 1
        p99 = stats.get("latency_p99_s")
        budget = cli.preempt_p99_budget_s
        if budget is None:
            # default: a fraction of the long job's wall, floored so
            # one-time compiles on a cold XLA cache can't false-fail a
            # CI-sized run (a NO-preemption run puts the full residual
            # PLUS the interactive's own work in p99, which clears both
            # bounds)
            budget = max(10.0, 0.6 * lj.get("wall_s", 0.0))
        if p99 is None or p99 > budget:
            print(f"INTERACTIVE P99 VIOLATION: p99={p99}s > budget="
                  f"{budget:.2f}s while the long job churned "
                  f"(wall {lj.get('wall_s')}s)", file=sys.stderr)
            return 1
    return _check_loop_stalls()


if __name__ == "__main__":
    sys.exit(main())
