#!/bin/sh
# JS test runner (reference parity: scripts/test-web.sh → vitest; here
# node's built-in test runner — zero dependencies, no build system).
# Usage: scripts/test-web.sh
set -e
cd "$(dirname "$0")/.."
if ! command -v node >/dev/null 2>&1; then
    echo "node not found — JS tests skipped (the Python suite's" \
         "tests/test_web.py contract checks still guard the web layer)"
    exit 0
fi
exec node --test comfyui_distributed_tpu/web/tests/
