#!/usr/bin/env python
"""SDXL MFU investigation harness (VERDICT r3 next #2).

Focused A/B experiments on the UNet denoiser forward — the 97%+ of the
txt2img step — instead of the whole pipeline, so one variant compiles in
seconds and the numbers isolate one question each:

    python scripts/mfu_probe.py forward          # flash on vs off
    python scripts/mfu_probe.py batch            # B=2 (CFG pair) vs B=4
    python scripts/mfu_probe.py attn             # attention microbench
    python scripts/mfu_probe.py trace            # profiler trace + op table

Run with PYTHONPATH=/root/.axon_site:/root/repo on the tunneled chip.
Results print as JSON lines for easy capture into docs/roofline.md.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cdt_xla_cache_probe")


def _enable_cache() -> None:
    import jax

    d = os.environ["JAX_COMPILATION_CACHE_DIR"]
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _median_time(fn, *args, runs: int = 10) -> float:
    """Times ``fn(seed_scalar, *args)`` — the varying scalar defeats any
    result caching in the tunneled backend (identical repeated calls
    measured 1000x too fast), and ``float()`` forces execution +
    device→host fetch of a scalar."""
    import jax.numpy as jnp

    float(fn(jnp.float32(0.0), *args))        # warmup (compile + alloc)
    times = []
    from comfyui_distributed_tpu.utils import constants

    runs = constants.PROBE_RUNS.get() or runs
    for i in range(runs):
        t0 = time.perf_counter()
        float(fn(jnp.float32(i + 1), *args))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _build_unet():
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.models.unet import UNetConfig, init_unet

    _enable_cache()

    cfg = UNetConfig.sdxl()
    model, params = init_unet(cfg, jax.random.key(0),
                              sample_shape=(128, 128, cfg.in_channels),
                              context_len=77, param_dtype=jnp.bfloat16)
    return cfg, model, params


def _unet_inputs(batch: int, cfg):
    import jax
    import jax.numpy as jnp

    x = jax.random.normal(jax.random.key(1), (batch, 128, 128,
                                              cfg.in_channels), jnp.bfloat16)
    t = jnp.full((batch,), 500, jnp.int32)
    ctx = jax.random.normal(jax.random.key(2), (batch, 77, cfg.context_dim),
                            jnp.bfloat16)
    y = (jax.random.normal(jax.random.key(3), (batch, cfg.adm_in_channels),
                           jnp.bfloat16)
         if cfg.adm_in_channels else None)
    return x, t, ctx, y


SCAN_LEN = 8     # forwards chained on-device per timed call: one tunnel
                 # RTT (~70 ms here) amortizes over 8 UNet forwards, like
                 # the pipeline's 30-step compiled scan


def _forward_fn(model):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fwd(seed, params, x, t, ctx, y):
        def body(carry, _):
            out = model.apply(params, carry, t, ctx, y)
            return carry * 0.5 + out.astype(carry.dtype) * 0.5, None

        # cast the seed perturbation to x's dtype: a strong f32 scalar
        # would promote the whole benchmarked stack out of bf16
        final, _ = jax.lax.scan(body, x + (seed * 1e-6).astype(x.dtype),
                                None, length=SCAN_LEN)
        return jnp.sum(final.astype(jnp.float32))

    return fwd


def _flops_of(fn, *args) -> float:
    try:
        import jax.numpy as jnp

        from comfyui_distributed_tpu.utils.flops import estimate_flops

        return float(estimate_flops(fn, jnp.float32(0.0), *args))
    except Exception as e:  # noqa: BLE001
        print(f"[probe] flops estimate failed: {e}", file=sys.stderr)
        return 0.0


def _peak() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    return 394e12 if "v5p" in kind else 197e12      # bf16 peak


def exp_forward(flash: str | None = None) -> None:
    """UNet forward, CFG-shaped batch (B=2): pallas flash vs XLA
    dot_product_attention. CDT_FLASH_ATTENTION is read at trace time, so
    each variant jits fresh."""
    results = []
    for mode in ([flash] if flash else ["1", "0"]):
        os.environ["CDT_FLASH_ATTENTION"] = mode
        import jax

        cfg, model, params = _build_unet()
        fwd = _forward_fn(model)
        args = _unet_inputs(2, cfg)
        t = _median_time(fwd, params, *args)
        flops = _flops_of(fwd, params, *args)
        rec = {"exp": "forward", "flash": mode,
               "s_per_forward": round(t / SCAN_LEN, 5),
               "flops": flops, "mfu": round(flops / t / _peak(), 4)
               if flops else None}
        print(json.dumps(rec), flush=True)
        results.append(rec)
        # new trace next loop: clear the jit cache so _flash_enabled
        # re-evaluates
        fwd._clear_cache()


def exp_batch() -> None:
    """Per-device batch 1 vs 2 (UNet sees 2 vs 4 with CFG concat): where
    does the r02 batch-2 throughput regression come from?"""
    os.environ.setdefault("CDT_FLASH_ATTENTION", "1")
    cfg, model, params = _build_unet()
    fwd = _forward_fn(model)
    for b in (2, 4):
        args = _unet_inputs(b, cfg)
        t = _median_time(fwd, params, *args)
        flops = _flops_of(fwd, params, *args)
        print(json.dumps({
            "exp": "batch", "unet_batch": b,
            "s_per_forward": round(t / SCAN_LEN, 5),
            "s_per_cfg_image_step": round(t / SCAN_LEN / (b // 2), 5),
            "mfu": round(flops / t / _peak(), 4) if flops else None,
        }), flush=True)


def exp_attn() -> None:
    """Attention microbench: flash (auto layout) vs XLA, plus the fused
    QKV+attention tier at self-attention shapes where C == H·D. Shapes
    cover SDXL, the FLUX H·D=3072 width (shrunk-packed since ISSUE 8) and
    WAN's ~14k-token geometry."""
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops.flash_attention import (
        flash_attention, fused_qkv_attention)

    shapes = [
        ("self64", 2, 4096, 10, 64, 4096),
        ("self32", 2, 1024, 20, 64, 1024),
        ("cross32", 2, 1024, 20, 64, 77),
        ("self64_b4", 4, 4096, 10, 64, 4096),
        ("self32_b4", 4, 1024, 20, 64, 1024),
        ("flux3072", 1, 4608, 24, 128, 4608),
        ("wan14k", 1, 14040, 12, 128, 14040),
    ]
    ATTN_SCAN = 64   # attention ops chained on-device per timed call —
                     # a single op is ~µs while the tunnel RTT is ~66 ms,
                     # so unamortized timings only measure the tunnel

    def timed_attn(f):
        @jax.jit
        def run(seed, q, k, v):
            def body(carry, _):
                out = f(carry, k, v)
                return (q + out * (seed * 1e-6).astype(q.dtype)), None

            final, _ = jax.lax.scan(body, q, None, length=ATTN_SCAN)
            return jnp.sum(final.astype(jnp.float32))

        return run

    def timed_fused(h, ws):
        @jax.jit
        def run(seed, x):
            def body(carry, _):
                out = fused_qkv_attention(carry, *ws, h, interpret=False)
                out = out.reshape(carry.shape)
                return (x + out * (seed * 1e-6).astype(x.dtype)), None

            final, _ = jax.lax.scan(body, x, None, length=ATTN_SCAN)
            return jnp.sum(final.astype(jnp.float32))

        return run

    for name, b, nq, h, d, nk in shapes:
        # works for nq != nk too: attention output is q-shaped, so the
        # scan carry stays [B, Nq, H, D] while k/v stay fixed
        q = jax.random.normal(jax.random.key(0), (b, nq, h, d), jnp.bfloat16)
        k = jax.random.normal(jax.random.key(1), (b, nk, h, d), jnp.bfloat16)
        v = jax.random.normal(jax.random.key(2), (b, nk, h, d), jnp.bfloat16)
        t_flash = _median_time(
            timed_attn(functools.partial(flash_attention, interpret=False)),
            q, k, v) / ATTN_SCAN
        t_xla = _median_time(timed_attn(jax.nn.dot_product_attention),
                             q, k, v) / ATTN_SCAN
        flops = 4.0 * b * h * nq * nk * d          # fwd: QK^T + PV
        rec = {
            "exp": "attn", "shape": name,
            "flash_us": round(t_flash * 1e6, 1),
            "xla_us": round(t_xla * 1e6, 1),
            "flash_tflops": round(flops / t_flash / 1e12, 1),
            "xla_tflops": round(flops / t_xla / 1e12, 1),
        }
        if nq == nk:   # self-attention: fused tier (C == H·D) if feasible
            from comfyui_distributed_tpu.ops.flash_attention import (
                _fused_feasible)

            C = h * d
            if _fused_feasible(C, h, d) is not None:
                x = jax.random.normal(jax.random.key(3), (b, nq, C),
                                      jnp.bfloat16)
                ws = [jax.random.normal(jax.random.key(4 + i), (C, C),
                                        jnp.bfloat16) / (C ** 0.5)
                      for i in range(3)]
                t_fused = _median_time(timed_fused(h, ws), x) / ATTN_SCAN
                # the fused op also does the QKV projection; its FLOPs
                # column includes that so tiers stay comparable per op
                rec["fused_us"] = round(t_fused * 1e6, 1)
        print(json.dumps(rec), flush=True)


def exp_trace(out_dir: str = "/tmp/mfu_trace") -> None:
    """Profiler trace of 4 UNet forwards + a best-effort op-level table
    via tensorboard_plugin_profile."""
    import glob

    import jax

    import jax.numpy as jnp

    os.environ.setdefault("CDT_FLASH_ATTENTION", "1")
    cfg, model, params = _build_unet()
    fwd = _forward_fn(model)
    args = _unet_inputs(2, cfg)
    float(fwd(jnp.float32(0.0), params, *args))     # warmup/compile
    jax.profiler.start_trace(out_dir)
    for i in range(4):
        float(fwd(jnp.float32(i + 1.0), params, *args))
    jax.profiler.stop_trace()
    print(json.dumps({"exp": "trace", "dir": out_dir}), flush=True)

    xplanes = sorted(glob.glob(f"{out_dir}/**/*.xplane.pb", recursive=True))
    if not xplanes:
        print(json.dumps({"exp": "trace", "error": "no xplane captured"}))
        return
    try:
        from tensorboard_plugin_profile.convert import raw_to_tool_data

        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [xplanes[-1]], "framework_op_stats", {})
        print(data[:8000] if isinstance(data, str) else str(data)[:8000])
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"exp": "trace",
                          "parse_error": f"{type(e).__name__}: {e}"}))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("experiment",
                    choices=["forward", "batch", "attn", "trace"])
    ap.add_argument("--flash", choices=["0", "1"])
    cli = ap.parse_args()
    {"forward": lambda: exp_forward(cli.flash),
     "batch": exp_batch,
     "attn": exp_attn,
     "trace": exp_trace}[cli.experiment]()


if __name__ == "__main__":
    main()
