#!/usr/bin/env python
"""cdtlint entry point (docs/lint.md).

Thin wrapper over ``python -m comfyui_distributed_tpu.lint`` so CI images
and pre-commit hooks can call a stable script path regardless of cwd.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from comfyui_distributed_tpu.lint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
