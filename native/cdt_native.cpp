// Native data-plane library for the TPU-distributed framework.
//
// The reference's data plane is pure Python: every tensor hop pays
// PIL PNG encode/decode + base64 (+33%) + JSON (SURVEY §6 — its "single
// biggest overhead"). On-pod this framework moves tensors as device
// arrays over ICI; this library serves the remaining *cross-host* hops
// (DCN/WAN collector envelopes, tile submissions, media hashing) and the
// master's host-side compositing:
//
//   - frame codec: length-prefixed tensor framing with crc32 integrity
//     and optional zlib compression — binary multipart replaces
//     base64-PNG JSON envelopes
//   - feathered tile blend: the master-side compositing hot loop when
//     combining tiles returned by remote hosts
//   - fnv1a64 content hash: media-sync dedup cheaper than md5 for
//     multi-GB video files
//
// C ABI only (consumed via ctypes); no exceptions across the boundary.
// Build: `make` (g++ -O3 -shared -fPIC, links zlib).

#include <cstdint>
#include <cstring>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// content hashing
// ---------------------------------------------------------------------------

uint64_t cdt_hash64(const uint8_t* data, int64_t n) {
    // FNV-1a 64-bit
    uint64_t h = 14695981039346656037ULL;
    for (int64_t i = 0; i < n; ++i) {
        h ^= data[i];
        h *= 1099511628211ULL;
    }
    return h;
}

uint32_t cdt_crc32(const uint8_t* data, int64_t n) {
    return (uint32_t)crc32(0L, data, (uInt)n);
}

// ---------------------------------------------------------------------------
// tensor frame codec
//
// layout (little-endian):
//   u32 magic 'CDTF'   u8 version   u8 dtype   u8 ndim   u8 flags(bit0=zlib)
//   i64 dims[ndim]
//   u32 crc32(raw payload)   u64 payload_bytes(stored)   u64 raw_bytes
//   payload
// ---------------------------------------------------------------------------

static const uint32_t kMagic = 0x46544443u;  // "CDTF"
static const uint8_t kVersion = 1;

static int64_t header_size(int32_t ndim) {
    return 8 + 8 * (int64_t)ndim + 4 + 8 + 8;
}

int64_t cdt_frame_bound(int64_t nbytes, int32_t ndim) {
    // worst case: zlib expansion bound + header
    return header_size(ndim) + (int64_t)compressBound((uLong)nbytes);
}

// returns bytes written, or <0 on error (-1 args, -2 capacity, -3 zlib)
int64_t cdt_pack_frame(const uint8_t* src, int64_t nbytes,
                       int32_t dtype, const int64_t* dims, int32_t ndim,
                       int32_t level, uint8_t* dst, int64_t dst_cap) {
    if (!src || !dst || ndim < 0 || ndim > 8 || nbytes < 0) return -1;
    const int64_t hsize = header_size(ndim);
    if (dst_cap < hsize) return -2;

    uint8_t flags = 0;
    uint64_t stored = (uint64_t)nbytes;
    if (level > 0) {
        uLongf cap = (uLongf)(dst_cap - hsize);
        int rc = compress2(dst + hsize, &cap, src, (uLong)nbytes, level);
        if (rc != Z_OK) return -3;
        if ((int64_t)cap < nbytes) {        // only keep if it actually shrank
            flags = 1;
            stored = (uint64_t)cap;
        }
    }
    if (!flags) {
        if (dst_cap < hsize + nbytes) return -2;
        std::memcpy(dst + hsize, src, (size_t)nbytes);
        stored = (uint64_t)nbytes;
    }

    uint8_t* p = dst;
    std::memcpy(p, &kMagic, 4); p += 4;
    *p++ = kVersion;
    *p++ = (uint8_t)dtype;
    *p++ = (uint8_t)ndim;
    *p++ = flags;
    std::memcpy(p, dims, 8 * (size_t)ndim); p += 8 * ndim;
    uint32_t crc = (uint32_t)crc32(0L, src, (uInt)nbytes);
    std::memcpy(p, &crc, 4); p += 4;
    std::memcpy(p, &stored, 8); p += 8;
    uint64_t raw = (uint64_t)nbytes;
    std::memcpy(p, &raw, 8); p += 8;
    return hsize + (int64_t)stored;
}

// peek: fills dtype/ndim/dims/raw_bytes; returns 0 or <0 on error
int64_t cdt_frame_info(const uint8_t* src, int64_t nbytes,
                       int32_t* dtype, int32_t* ndim, int64_t* dims /*>=8*/,
                       int64_t* raw_bytes) {
    if (!src || nbytes < 8) return -1;
    uint32_t magic;
    std::memcpy(&magic, src, 4);
    if (magic != kMagic || src[4] != kVersion) return -4;
    int32_t nd = src[6];
    if (nd < 0 || nd > 8) return -4;
    const int64_t hsize = header_size(nd);
    if (nbytes < hsize) return -1;
    *dtype = src[5];
    *ndim = nd;
    std::memcpy(dims, src + 8, 8 * (size_t)nd);
    uint64_t raw;
    std::memcpy(&raw, src + 8 + 8 * nd + 4 + 8, 8);
    *raw_bytes = (int64_t)raw;
    return 0;
}

// returns raw payload bytes written, or <0 (-1 args, -2 cap, -3 zlib,
// -4 bad magic/version, -5 crc mismatch)
int64_t cdt_unpack_frame(const uint8_t* src, int64_t nbytes,
                         uint8_t* dst, int64_t dst_cap) {
    int32_t dtype, ndim;
    int64_t dims[8], raw;
    int64_t rc = cdt_frame_info(src, nbytes, &dtype, &ndim, dims, &raw);
    if (rc < 0) return rc;
    const int64_t hsize = header_size(ndim);
    uint8_t flags = src[7];
    uint32_t crc_expected;
    std::memcpy(&crc_expected, src + 8 + 8 * ndim, 4);
    uint64_t stored;
    std::memcpy(&stored, src + 8 + 8 * ndim + 4, 8);
    if (nbytes < hsize + (int64_t)stored) return -1;
    if (dst_cap < raw) return -2;

    if (flags & 1) {
        uLongf out = (uLongf)dst_cap;
        int zrc = uncompress(dst, &out, src + hsize, (uLong)stored);
        if (zrc != Z_OK || (int64_t)out != raw) return -3;
    } else {
        std::memcpy(dst, src + hsize, (size_t)raw);
    }
    if ((uint32_t)crc32(0L, dst, (uInt)raw) != crc_expected) return -5;
    return raw;
}

// ---------------------------------------------------------------------------
// feathered tile compositing (master-side, float32 HWC)
// ---------------------------------------------------------------------------

// canvas[y:y+th, x:x+tw] = canvas*(1-mask) + tile*mask, clipped to bounds.
void cdt_blend_tile(float* canvas, int64_t H, int64_t W, int64_t C,
                    const float* tile, const float* mask,
                    int64_t th, int64_t tw, int64_t y, int64_t x) {
    for (int64_t r = 0; r < th; ++r) {
        const int64_t cy = y + r;
        if (cy < 0 || cy >= H) continue;
        for (int64_t c = 0; c < tw; ++c) {
            const int64_t cx = x + c;
            if (cx < 0 || cx >= W) continue;
            const float m = mask[r * tw + c];
            const float inv = 1.0f - m;
            float* dst = canvas + (cy * W + cx) * C;
            const float* srcp = tile + (r * tw + c) * C;
            for (int64_t ch = 0; ch < C; ++ch)
                dst[ch] = dst[ch] * inv + srcp[ch] * m;
        }
    }
}

// weighted accumulation variant: acc += tile*mask; wsum += mask
// (normalized compositing across overlapping tiles, order-independent)
void cdt_accumulate_tile(float* acc, float* wsum,
                         int64_t H, int64_t W, int64_t C,
                         const float* tile, const float* mask,
                         int64_t th, int64_t tw, int64_t y, int64_t x) {
    for (int64_t r = 0; r < th; ++r) {
        const int64_t cy = y + r;
        if (cy < 0 || cy >= H) continue;
        for (int64_t c = 0; c < tw; ++c) {
            const int64_t cx = x + c;
            if (cx < 0 || cx >= W) continue;
            const float m = mask[r * tw + c];
            float* dst = acc + (cy * W + cx) * C;
            const float* srcp = tile + (r * tw + c) * C;
            for (int64_t ch = 0; ch < C; ++ch)
                dst[ch] += srcp[ch] * m;
            wsum[cy * W + cx] += m;
        }
    }
}

// ---------------------------------------------------------------------------
// uint8 <-> float32 image conversion (codec hot path)
// ---------------------------------------------------------------------------

void cdt_f32_to_u8(const float* src, uint8_t* dst, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        float v = src[i];
        v = v < 0.0f ? 0.0f : (v > 1.0f ? 1.0f : v);
        dst[i] = (uint8_t)(v * 255.0f + 0.5f);
    }
}

void cdt_u8_to_f32(const uint8_t* src, float* dst, int64_t n) {
    const float k = 1.0f / 255.0f;
    for (int64_t i = 0; i < n; ++i) dst[i] = src[i] * k;
}

}  // extern "C"
