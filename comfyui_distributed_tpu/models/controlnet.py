"""ControlNet (LDM ``cldm`` architecture) in flax.

The reference gets ControlNet from ComfyUI core and its USDU path crops
control hints per tile (``/root/reference/utils/usdu_utils.py:506``
``crop_cond``, ``utils/crop_model_patch.py`` — SURVEY §7 hard-part #3).
A standalone framework owns the model: this is the published ControlNet
topology — an exact copy of the UNet encoder + middle (so SD1.5/SDXL
control checkpoints convert via the same walk the UNet converter uses,
``convert._unet_down_layout``), an 8-conv hint stem (image-res hint →
/8 latent res), one zero-init 1×1 conv per skip connection, and a middle
output zero-conv. Outputs are residuals the UNet adds to its skips and
middle state (``models/unet.py`` ``control=`` hook).

TPU notes: bf16 trunk on the MXU like the UNet; the whole control pass
fuses into the same XLA program as the denoise step. The hint stem is
recomputed per step inside the sampler scan — it is ~8 thin convs
(<1% of step FLOPs), and keeping ``__call__`` single-method keeps the
module compact and the converter template exact.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .layers import (
    Downsample,
    GroupNorm32,
    ResBlock,
    SpatialTransformer,
    timestep_embedding,
)
from .unet import UNetConfig

# hint-stem channel ladder (published cldm: 16,16,32,32,96,96,256 → model_ch)
_HINT_CHANNELS = (16, 16, 32, 32, 96, 96, 256)
_HINT_STRIDES = (1, 1, 2, 1, 2, 1, 2)


class ControlNet(nn.Module):
    """x[B,h,w,C], t[B], context, y, hint[B,H,W,3] → (skip residuals, mid)."""

    config: UNetConfig
    hint_channels: int = 3

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        t: jax.Array,
        context: Optional[jax.Array],
        y: Optional[jax.Array],
        hint: jax.Array,
    ) -> tuple[list[jax.Array], jax.Array]:
        cfg = self.config
        dt = cfg.jnp_dtype
        time_dim = cfg.model_channels * 4
        assert hint.shape[-1] == self.hint_channels, (
            f"hint has {hint.shape[-1]} channels, module expects "
            f"{self.hint_channels}")

        emb = timestep_embedding(t, cfg.model_channels)
        emb = nn.Dense(time_dim, dtype=dt, name="time_1")(emb.astype(dt))
        emb = nn.Dense(time_dim, dtype=dt, name="time_2")(nn.silu(emb))
        if cfg.adm_in_channels:
            assert y is not None, "config.adm_in_channels set but y not given"
            yemb = nn.Dense(time_dim, dtype=dt, name="label_1")(y.astype(dt))
            yemb = nn.Dense(time_dim, dtype=dt, name="label_2")(nn.silu(yemb))
            emb = emb + yemb

        # hint stem: image-res control map → latent-res features
        g = hint.astype(dt)
        for j, (ch, stride) in enumerate(zip(_HINT_CHANNELS, _HINT_STRIDES)):
            g = nn.silu(nn.Conv(ch, (3, 3), strides=stride, padding=1,
                                dtype=dt, name=f"hint_{j}")(g))
        g = nn.Conv(cfg.model_channels, (3, 3), padding=1, dtype=dt,
                    name=f"hint_{len(_HINT_CHANNELS)}")(g)

        x = x.astype(dt)
        if context is not None:
            context = context.astype(dt)

        zero = lambda i, h: nn.Conv(
            h.shape[-1], (1, 1), dtype=jnp.float32, name=f"zero_{i}",
            kernel_init=nn.initializers.zeros,
        )(h.astype(jnp.float32))

        h = nn.Conv(cfg.model_channels, (3, 3), padding=1, dtype=dt,
                    name="conv_in")(x)
        h = h + g
        outs = [zero(0, h)]
        zi = 1

        for level, mult in enumerate(cfg.channel_mult):
            ch = cfg.model_channels * mult
            for i in range(cfg.num_res_blocks):
                h = ResBlock(ch, dt, name=f"down_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level]:
                    h = SpatialTransformer(
                        cfg.heads_for(ch), cfg.transformer_depth[level], dt,
                        name=f"down_{level}_attn_{i}")(h, context)
                outs.append(zero(zi, h))
                zi += 1
            if level < len(cfg.channel_mult) - 1:
                h = Downsample(ch, dt, name=f"down_{level}_ds")(h)
                outs.append(zero(zi, h))
                zi += 1

        mid_ch = cfg.model_channels * cfg.channel_mult[-1]
        h = ResBlock(mid_ch, dt, name="mid_res_1")(h, emb)
        if cfg.transformer_depth[-1]:
            h = SpatialTransformer(
                cfg.heads_for(mid_ch), cfg.transformer_depth[-1], dt,
                name="mid_attn")(h, context)
        h = ResBlock(mid_ch, dt, name="mid_res_2")(h, emb)
        mid = nn.Conv(mid_ch, (1, 1), dtype=jnp.float32, name="mid_out",
                      kernel_init=nn.initializers.zeros)(
            h.astype(jnp.float32))
        return outs, mid


_uid_counter = itertools.count()


@dataclasses.dataclass
class ControlNetBundle:
    """Module + params + the conditioning-dict payload contract: a
    conditioning entry carries ``{"model": bundle, "hint": [B,H,W,3],
    "strength": float}`` under its ``"control"`` key (ControlNetApply).

    ``uid`` is a process-unique token for compile-clone caches (``id()``
    is recycled after GC and would alias stale compiled programs)."""

    model: ControlNet
    params: dict
    name: str = "controlnet"
    uid: int = dataclasses.field(default_factory=_uid_counter.__next__)

    def apply(self, x, t, context, y, hint):
        return self.model.apply(self.params, x, t, context, y, hint)


def init_controlnet(
    config: UNetConfig,
    rng: jax.Array,
    sample_shape: tuple[int, int, int] = (64, 64, 4),
    context_len: int = 77,
    hint_channels: int = 3,
) -> ControlNetBundle:
    model = ControlNet(config, hint_channels=hint_channels)
    H, W, C = sample_shape
    down = 8  # hint stem downscale (three stride-2 convs)
    x = jnp.zeros((1, H, W, C), jnp.float32)
    t = jnp.zeros((1,), jnp.float32)
    ctx = jnp.zeros((1, context_len, config.context_dim), jnp.float32)
    y = (jnp.zeros((1, config.adm_in_channels), jnp.float32)
         if config.adm_in_channels else None)
    hint = jnp.zeros((1, H * down, W * down, hint_channels), jnp.float32)
    params = jax.jit(model.init)(rng, x, t, ctx, y, hint)
    return ControlNetBundle(model, params)
