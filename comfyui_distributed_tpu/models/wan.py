"""WAN 2.x video DiT — the exact published architecture, flax-native.

``models/video_dit.py`` keeps the generic MMDiT-over-frames stack; this
module is the weight-faithful WAN t2v transformer (Wan-2.1/2.2 family)
so published checkpoints convert without surgery:

- Conv3d patch embedding (temporal patch 1, spatial 2×2);
- N identical blocks: self-attention with 3-axis rotary embeddings and
  **full-dim** learned-scale qk RMSNorm, cross-attention to UMT5 text
  (no RoPE), tanh-GELU FFN; modulation = a learned per-block ``[1,6,dim]``
  parameter **added** to the shared time projection, chunked into
  shift/scale/gate for the attention and FFN branches;
- head: LayerNorm + linear with a learned ``[1,2,dim]`` shift/scale
  modulation over the *unprojected* time embedding.

The reference runs WAN through ComfyUI (SURVEY "external substrate");
here the stack is native and sequence-parallel: ``sp_axis`` shards the
frame axis — self-attention runs as ring attention over the shards with
frame-offset RoPE ids (exact), cross-attention is token-local and needs
no collective. This is the capability the reference lacks entirely
(SURVEY §2.10/§5.7: no sequence/context parallelism).

Converter: :func:`convert_wan` (official ``blocks.N.*`` layout, bare or
under ``model.diffusion_model.``). Differential test:
``tests/test_wan.py`` against a torch replica of the published forward.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from ..ops.attention import full_attention, ring_attention
from .dit import apply_rope, rope_freqs
from .layers import timestep_embedding


@dataclasses.dataclass(frozen=True)
class WanConfig:
    patch_size: tuple[int, int, int] = (1, 2, 2)
    in_channels: int = 16
    out_channels: int = 16
    dim: int = 5120
    ffn_dim: int = 13824
    num_layers: int = 40
    num_heads: int = 40
    text_dim: int = 4096
    freq_dim: int = 256
    eps: float = 1e-6
    cross_attn_norm: bool = True
    dtype: str = "bfloat16"
    remat: bool = False
    attn_backend: str = "dense"    # "dense" | "flash" — "flash" prefers
                                   # the pallas kernel regardless of the
                                   # seq-length gate (memory-starved
                                   # offload executors; ops/attention.py)

    @classmethod
    def wan_14b(cls) -> "WanConfig":
        from ..utils import constants

        return cls(remat=constants.REMAT)

    @classmethod
    def wan_1_3b(cls) -> "WanConfig":
        from ..utils import constants

        return cls(dim=1536, ffn_dim=8960, num_layers=30, num_heads=12,
                   remat=constants.REMAT)

    @classmethod
    def tiny(cls, **kw) -> "WanConfig":
        base = dict(in_channels=4, out_channels=4, dim=48, ffn_dim=96,
                    num_layers=2, num_heads=4, text_dim=32, freq_dim=32,
                    dtype="float32")
        base.update(kw)
        return cls(**base)

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads

    @property
    def axes_dim(self) -> tuple[int, int, int]:
        """Per-axis RoPE widths over (frame, row, col) — WAN's split:
        2·(d/6) each for rows/cols, the remainder for time."""
        d = self.head_dim
        dh = 2 * (d // 6)
        return (d - 2 * dh, dh, dh)

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def video_ids(f: int, h: int, w: int, frame_offset: int = 0) -> jax.Array:
    """[f·h·w, 3] (frame, row, col) token ids, frame-major."""
    fs = jnp.repeat(jnp.arange(f) + frame_offset, h * w)
    rows = jnp.tile(jnp.repeat(jnp.arange(h), w), (f,))
    cols = jnp.tile(jnp.arange(w), (f * h,))
    return jnp.stack([fs, rows, cols], axis=-1)


class WanRMSNorm(nn.Module):
    """Full-width RMS norm with learned scale (WAN's qk norm)."""

    eps: float

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) \
            * w.astype(x.dtype)


class WanSelfAttention(nn.Module):
    config: WanConfig

    @nn.compact
    def __call__(self, x, pe, sp_axis: Optional[str]):
        cfg = self.config
        dt = cfg.jnp_dtype
        B, N, _ = x.shape
        q = WanRMSNorm(cfg.eps, name="norm_q")(
            nn.Dense(cfg.dim, dtype=dt, name="q")(x))
        k = WanRMSNorm(cfg.eps, name="norm_k")(
            nn.Dense(cfg.dim, dtype=dt, name="k")(x))
        v = nn.Dense(cfg.dim, dtype=dt, name="v")(x)
        shape = (B, N, cfg.num_heads, cfg.head_dim)
        q = apply_rope(q.reshape(shape), pe)
        k = apply_rope(k.reshape(shape), pe)
        v = v.reshape(shape)
        if sp_axis is None:
            out = full_attention(q, k, v,
                                 prefer_flash=cfg.attn_backend == "flash")
        else:
            out = ring_attention(q, k, v, sp_axis)
        return nn.Dense(cfg.dim, dtype=dt, name="o")(
            out.reshape(B, N, cfg.dim))


class WanCrossAttention(nn.Module):
    """Text cross-attention (no RoPE). Context is replicated per shard,
    queries are token-local — sp needs no collective here."""

    config: WanConfig

    @nn.compact
    def __call__(self, x, context):
        cfg = self.config
        dt = cfg.jnp_dtype
        B, N, _ = x.shape
        T = context.shape[1]
        q = WanRMSNorm(cfg.eps, name="norm_q")(
            nn.Dense(cfg.dim, dtype=dt, name="q")(x))
        k = WanRMSNorm(cfg.eps, name="norm_k")(
            nn.Dense(cfg.dim, dtype=dt, name="k")(context))
        v = nn.Dense(cfg.dim, dtype=dt, name="v")(context)
        out = full_attention(q.reshape(B, N, cfg.num_heads, cfg.head_dim),
                             k.reshape(B, T, cfg.num_heads, cfg.head_dim),
                             v.reshape(B, T, cfg.num_heads, cfg.head_dim))
        return nn.Dense(cfg.dim, dtype=dt, name="o")(
            out.reshape(B, N, cfg.dim))


class WanBlock(nn.Module):
    config: WanConfig

    @nn.compact
    def __call__(self, x, e0, context, pe, sp_axis: Optional[str]):
        """x [B,N,dim]; e0 [B,6,dim] (shared time projection)."""
        cfg = self.config
        dt = cfg.jnp_dtype
        mod = self.param("modulation", nn.initializers.normal(0.02),
                         (1, 6, cfg.dim))
        m = (mod.astype(jnp.float32) + e0.astype(jnp.float32)).astype(dt)
        m0, m1, m2, m3, m4, m5 = [m[:, i][:, None, :] for i in range(6)]

        ln = dict(use_scale=False, use_bias=False, epsilon=cfg.eps, dtype=dt)
        y = WanSelfAttention(cfg, name="self_attn")(
            nn.LayerNorm(**ln)(x) * (1 + m1) + m0, pe, sp_axis)
        x = x + y * m2
        h = x
        if cfg.cross_attn_norm:
            h = nn.LayerNorm(epsilon=cfg.eps, dtype=dt, name="norm3")(x)
        x = x + WanCrossAttention(cfg, name="cross_attn")(h, context)
        y = nn.LayerNorm(**ln)(x) * (1 + m4) + m3
        y = nn.Dense(cfg.ffn_dim, dtype=dt, name="ffn_0")(y)
        y = nn.Dense(cfg.dim, dtype=dt, name="ffn_2")(
            nn.gelu(y, approximate=True))
        return x + y * m5


class WanModel(nn.Module):
    """x[B,F,h,w,C], t[B] (flow time in [0,1]), context[B,T,text_dim]
    → velocity [B,F,h,w,out]. ``pooled`` is accepted and ignored (WAN has
    no pooled-vector conditioning) so the video pipeline drives either
    architecture unchanged."""

    config: WanConfig
    # tensor-parallel rule family (parallel/tensor.py): separate q/k/v/o +
    # ffn_0/ffn_2 naming — NOT the MMDiT fused-qkv layout
    tp_family = "wan"

    @nn.compact
    def __call__(self, x, t, context, pooled=None,
                 sp_axis: Optional[str] = None):
        cfg = self.config
        dt = cfg.jnp_dtype
        B, F, H, W, C = x.shape
        pt, ph, pw = cfg.patch_size

        tok = nn.Conv(cfg.dim, kernel_size=cfg.patch_size,
                      strides=cfg.patch_size, dtype=dt,
                      name="patch_embedding")(x.astype(dt))
        f, h, w = F // pt, H // ph, W // pw
        tok = tok.reshape(B, f * h * w, cfg.dim)

        if sp_axis is None:
            ids = video_ids(f, h, w)
        else:
            idx = jax.lax.axis_index(sp_axis)
            ids = video_ids(f, h, w, frame_offset=idx * f)
        pe = rope_freqs(ids, cfg.axes_dim, 10000.0)

        emb = timestep_embedding(t * 1000.0, cfg.freq_dim).astype(dt)
        e = nn.Dense(cfg.dim, dtype=dt, name="time_emb_0")(emb)
        e = nn.Dense(cfg.dim, dtype=dt, name="time_emb_2")(nn.silu(e))
        e0 = nn.Dense(cfg.dim * 6, dtype=dt, name="time_proj_1")(
            nn.silu(e)).reshape(B, 6, cfg.dim)

        ctx = nn.Dense(cfg.dim, dtype=dt, name="text_emb_0")(
            context.astype(dt))
        ctx = nn.Dense(cfg.dim, dtype=dt, name="text_emb_2")(
            nn.gelu(ctx, approximate=True))

        Block = (nn.remat(WanBlock, static_argnums=(4,))
                 if cfg.remat else WanBlock)
        for i in range(cfg.num_layers):
            tok = Block(cfg, name=f"block_{i}")(tok, e0, ctx, pe, sp_axis)

        head_mod = self.param("head_modulation",
                              nn.initializers.normal(0.02), (1, 2, cfg.dim))
        hm = (head_mod.astype(jnp.float32)
              + e.astype(jnp.float32)[:, None, :]).astype(dt)
        sh, sc = hm[:, 0][:, None, :], hm[:, 1][:, None, :]
        tok = nn.LayerNorm(use_scale=False, use_bias=False, epsilon=cfg.eps,
                           dtype=dt)(tok) * (1 + sc) + sh
        out = nn.Dense(pt * ph * pw * cfg.out_channels, dtype=jnp.float32,
                       name="head")(tok.astype(jnp.float32))

        # unpatchify: tokens frame-major; WAN head features are ordered
        # (pt, ph, pw, c) — channel LAST (`view(*v, *patch_size, c)` in the
        # published unpatchify) — so head weights map verbatim
        o = cfg.out_channels
        out = out.reshape(B, f, h, w, pt, ph, pw, o)
        out = out.transpose(0, 1, 4, 2, 5, 3, 6, 7)   # B f pt h ph w pw c
        return out.reshape(B, F, H, W, o)


def init_wan(config: WanConfig, rng: jax.Array,
             sample_fhw: tuple[int, int, int] = (5, 8, 8),
             context_len: int = 16, abstract: bool = False,
             param_dtype=None):
    """``param_dtype`` casts float params inside the fused init program
    (see ``models/unet.init_unet``) — a 14B WAN never fits as fp32."""
    from .unet import casting_init

    model = WanModel(config)
    f, h, w = sample_fhw
    args = (rng, jnp.zeros((1, f, h, w, config.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, context_len, config.text_dim)),
            jnp.zeros((1, 16)))
    init_fn = casting_init(model.init, param_dtype)
    if abstract:
        return model, jax.eval_shape(init_fn, *args)
    return model, jax.jit(init_fn)(*args)


# ---------------------------------------------------------------------------
# converter (official Wan2.x layout)
# ---------------------------------------------------------------------------

WAN_PREFIXED = "model.diffusion_model."


def convert_wan(sd, template, config: WanConfig, prefix: str = "") -> dict:
    """Official WAN t2v state dict → :class:`WanModel` params.

    Key walk: ``patch_embedding``, ``{text,time}_embedding.{0,2}``,
    ``time_projection.1``, ``blocks.N.{self_attn,cross_attn}.{q,k,v,o}``
    (+ full-dim ``norm_q``/``norm_k`` scales), ``blocks.N.norm3``,
    ``blocks.N.ffn.{0,2}``, per-block ``modulation`` ``[1,6,dim]``,
    ``head.{head,modulation}``. i2v-specific keys (``k_img``/``img_emb``)
    raise a targeted error until the image-conditioned variant lands.
    """
    from .convert import ConversionError, _Filler, _lin

    if any(".k_img." in k or k.startswith(f"{prefix}img_emb.") for k in sd):
        raise ConversionError(
            "WAN i2v checkpoint (image-conditioned cross-attention) is not "
            "yet supported — use a t2v checkpoint")
    p = prefix
    f = _Filler(sd, template["params"])

    def conv3d(w):
        return np.asarray(w, np.float32).transpose(2, 3, 4, 1, 0)

    f.put(f"{p}patch_embedding.weight", "patch_embedding/kernel", conv3d)
    f.put(f"{p}patch_embedding.bias", "patch_embedding/bias")
    f.linear(f"{p}text_embedding.0", "text_emb_0")
    f.linear(f"{p}text_embedding.2", "text_emb_2")
    f.linear(f"{p}time_embedding.0", "time_emb_0")
    f.linear(f"{p}time_embedding.2", "time_emb_2")
    f.linear(f"{p}time_projection.1", "time_proj_1")

    for i in range(config.num_layers):
        src, dst = f"{p}blocks.{i}", f"block_{i}"
        f.put(f"{src}.modulation", f"{dst}/modulation")
        for attn in ("self_attn", "cross_attn"):
            for proj in ("q", "k", "v", "o"):
                f.linear(f"{src}.{attn}.{proj}", f"{dst}/{attn}/{proj}")
            f.put(f"{src}.{attn}.norm_q.weight",
                  f"{dst}/{attn}/norm_q/weight")
            f.put(f"{src}.{attn}.norm_k.weight",
                  f"{dst}/{attn}/norm_k/weight")
        if config.cross_attn_norm:
            f.norm(f"{src}.norm3", f"{dst}/norm3")
        f.linear(f"{src}.ffn.0", f"{dst}/ffn_0")
        f.linear(f"{src}.ffn.2", f"{dst}/ffn_2")

    f.put(f"{p}head.head.weight", "head/kernel", _lin)
    f.put(f"{p}head.head.bias", "head/bias")
    f.put(f"{p}head.modulation", "head_modulation")
    tree = f.finish(expect_prefix=p)
    if not p:
        leftover = [k for k in sd if k not in f.used]
        if leftover:
            raise ConversionError(
                f"unconsumed WAN keys: {leftover[:8]}"
                f"{'…' if len(leftover) > 8 else ''}")
    return {"params": tree}
