"""LoRA loading and merging (kohya ``.safetensors`` format).

The reference gets LoRA for free from ComfyUI core (``LoraLoader`` node);
a standalone framework owns it. This implements the dominant published
format — kohya sd-scripts keys, as shipped by civitai for SD1.5/SDXL:

- ``lora_unet_{ldm_module_path_with_underscores}.lora_down.weight`` /
  ``.lora_up.weight`` / ``.alpha`` for the UNet,
- ``lora_te_…`` (SD1.5) / ``lora_te1_…``+``lora_te2_…`` (SDXL) with HF
  ``CLIPTextModel`` module paths for the text encoders.

Key-map derivation is the part every implementation gets subtly wrong;
here it cannot drift: the map is RECORDED from the weight converter's own
layout walks (``convert._unet_layout`` / ``convert._clip_hf_layout`` via
``convert._Recorder``), so a LoRA key matches exactly where the
corresponding base weight would land, and the converter's torch→flax
transforms are reused verbatim on the delta (``W' = W + s·(α/r)·B·A``,
merged — TPU-first: merging keeps the hot path one fused matmul; runtime
adapter branches would add per-layer matmuls XLA cannot fold away).
"""

from __future__ import annotations

import copy
from pathlib import Path
from typing import Mapping

import numpy as np

from ..utils.exceptions import ValidationError
from ..utils.logging import debug_log, log
from .convert import (_Recorder, _clip_hf_layout, _unet_layout,
                      load_safetensors)


def unet_records(config, linear_proj: bool = True,
                 prefix: str = "model.diffusion_model."):
    rec = _Recorder()
    _unet_layout(rec, config, prefix, linear_proj)
    return rec.records


def clip_hf_records(config, prefix: str = "text_model."):
    rec = _Recorder()
    _clip_hf_layout(rec, config, prefix)
    return rec.records


def _delta(down: np.ndarray, up: np.ndarray, alpha, transform) -> np.ndarray:
    """torch-layout ΔW = (α/r)·up·down, then the converter's torch→flax
    transform (valid because every transform is a pure layout map)."""
    r = down.shape[0]
    scale = (float(alpha) / r) if alpha is not None else 1.0
    down = np.asarray(down, np.float32)
    up = np.asarray(up, np.float32)
    if down.ndim == 2:                       # Linear: [r,in] / [out,r]
        d = up @ down
    else:                                    # Conv: [r,in,k,k] / [out,r,1,1]
        d = (up.reshape(up.shape[0], -1) @ down.reshape(r, -1)).reshape(
            up.shape[0], *down.shape[1:])
    return transform(d * scale)


def collect_deltas(
    lora_sd: Mapping[str, np.ndarray],
    records,
    lora_prefix: str,
    converter_prefix: str,
    strength: float,
) -> tuple[dict[str, np.ndarray], set[str]]:
    """Match LoRA keys against recorded converter entries.

    Returns (dst_path → flax-layout delta, consumed source keys).
    """
    deltas: dict[str, np.ndarray] = {}
    used: set[str] = set()
    for src_key, dst_path, transform in records:
        if (not src_key.endswith(".weight")
                or not src_key.startswith(converter_prefix)):
            continue
        base = src_key[len(converter_prefix):-len(".weight")]
        lkey = lora_prefix + base.replace(".", "_")
        dk, uk, ak = (f"{lkey}.lora_down.weight", f"{lkey}.lora_up.weight",
                      f"{lkey}.alpha")
        if dk not in lora_sd or uk not in lora_sd:
            continue
        alpha = lora_sd.get(ak)
        deltas[dst_path] = strength * _delta(
            lora_sd[dk], lora_sd[uk], alpha, transform)
        used.update({dk, uk})
        if ak in lora_sd:
            used.add(ak)
    return deltas, used


def apply_deltas(params: dict, deltas: Mapping[str, np.ndarray]) -> dict:
    """Return a tree sharing every untouched leaf with ``params``, with
    deltas added along patched paths (shape-checked against the live tree
    — a geometry-mismatched LoRA fails loudly). Path-copy, not deep copy:
    a real SDXL UNet is ~GBs, and only the LoRA'd leaves change."""
    tree = dict(params["params"])
    out = {**params, "params": tree}
    for dst, d in deltas.items():
        parts = dst.split("/")
        node = tree
        for part in parts[:-1]:          # copy-on-write down the path
            child = node.get(part)
            if not isinstance(child, dict):
                raise ValidationError(f"LoRA target {dst!r} not in params tree")
            child = dict(child)
            node[part] = child
            node = child
        leaf = node.get(parts[-1])
        if leaf is None:
            raise ValidationError(f"LoRA target {dst!r} not in params tree")
        if tuple(leaf.shape) != tuple(d.shape):
            raise ValidationError(
                f"LoRA delta for {dst!r}: shape {d.shape} != {tuple(leaf.shape)}")
        node[parts[-1]] = np.asarray(leaf, np.float32) + d
    return out


def load_lora_file(path: Path) -> dict[str, np.ndarray]:
    return load_safetensors(Path(path))


def apply_lora(bundle, lora_sd: Mapping[str, np.ndarray], *,
               strength_model: float = 1.0, strength_clip: float = 1.0,
               name: str = "lora"):
    """Merge a kohya LoRA into copies of a unet-kind ``ModelBundle``'s
    params. Returns ``(patched_bundle, patched_conditioner_or_None)``.

    The input bundle is never mutated (registry bundles are shared);
    pipelines are shallow-cloned with fresh compile caches.
    """
    if bundle.kind != "unet":
        raise ValidationError(
            f"LoRA merging supports unet-kind presets; {bundle.preset.name!r} "
            f"is {bundle.kind!r} (FLUX/video LoRA formats differ)")

    used: set[str] = set()
    unet_cfg = bundle.preset.unet
    linear_proj = not (unet_cfg.context_dim == 768 and
                      unet_cfg.adm_in_channels == 0)
    recs = unet_records(unet_cfg, linear_proj=linear_proj)
    deltas, u = collect_deltas(lora_sd, recs, "lora_unet_",
                               "model.diffusion_model.", strength_model)
    used |= u

    patched = copy.copy(bundle)
    patched.pipeline = copy.copy(bundle.pipeline)
    patched.pipeline._fn_cache = {}
    patched.pipeline._i2i_cache = {}
    patched.pipeline._control_clones = {}   # never share pre-LoRA clones
    if deltas and strength_model:
        patched.pipeline.unet_params = apply_deltas(
            bundle.pipeline.unet_params, deltas)

    # text encoders: only the weight-faithful CLIP stack is patchable
    conditioner = None
    stack = getattr(bundle, "clip_stack", None)
    if stack is not None and strength_clip:
        from .clip import CLIPConditioner

        te_parts = []
        if hasattr(stack, "clip_l"):          # SDXL dual stack
            te_parts = [("lora_te1_", stack.clip_l), ("lora_te2_", stack.clip_g)]
        else:                                  # SD1.5 single encoder
            te_parts = [("lora_te_", stack)]
        new_stack = copy.copy(stack)
        for prefix, enc in te_parts:
            d, u = collect_deltas(
                lora_sd, clip_hf_records(enc.config),
                prefix + "text_model_", "text_model.", strength_clip)
            used |= u
            if d:
                new_enc = copy.copy(enc)
                new_enc.params = apply_deltas(enc.params, d)
                if enc is getattr(stack, "clip_l", None):
                    new_stack.clip_l = new_enc
                elif enc is getattr(stack, "clip_g", None):
                    new_stack.clip_g = new_enc
                else:
                    new_stack = new_enc
        patched.clip_stack = new_stack
        conditioner = CLIPConditioner(
            new_stack, kind=bundle.preset.clip or "clip-l")
        # keep the bundle self-consistent: its own encoder must produce
        # LoRA'd conditioning too, not just the returned CLIP output
        patched.text_encoder = conditioner

    unmatched = len([k for k in lora_sd if k not in used])
    log(f"LoRA {name!r}: merged {len(deltas)} unet tensors"
        f"{' + text encoders' if conditioner else ''}"
        f"{f' ({unmatched} keys unmatched)' if unmatched else ''}")
    if unmatched:
        sample = [k for k in lora_sd if k not in used][:4]
        debug_log(f"LoRA {name!r} unmatched keys (first 4): {sample}")
    return patched, conditioner
