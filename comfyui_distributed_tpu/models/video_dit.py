"""WAN-class video DiT (text→video / image→video family).

Covers BASELINE's "WAN-2.2 14B t2v" config family: flow-matching DiT over
spatio-temporal tokens. Geometry: latent video [B,F,h,w,C] patchified
per-frame (p×p spatial, temporal patch 1), tokens ordered frame-major, 3-D
axial sincos positions (t,h,w). Transformer blocks are the same MMDiT
double/single blocks as the image DiT (``models/dit.py``) — they are
geometry-agnostic — so sequence parallelism (ring attention over the
``sp`` axis) works over *frames*: each shard owns a contiguous frame
block, the TPU-native form of the reference's temporal chunking
(``upscale/modes/dynamic.py`` per-image queue + ImageBatchDivider,
SURVEY §5.7).

The reference's WAN-specific 4n+1 frame-batch rule
(``nodes/distributed_upscale.py:131-142``) is provided as padding helpers.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from ..utils.jax_compat import axis_size as _axis_size
from flax import linen as nn

from .dit import DiTConfig, DoubleBlock, Modulation, SingleBlock, _modulate
from .layers import timestep_embedding


def pad_frames_4n1(frames: int) -> int:
    """Smallest 4n+1 ≥ frames (reference video-model constraint)."""
    if frames <= 1:
        return 1
    return ((frames - 2) // 4 + 1) * 4 + 1


def validate_frames_4n1(frames: int) -> bool:
    return frames >= 1 and (frames - 1) % 4 == 0


@dataclasses.dataclass(frozen=True)
class VideoDiTConfig:
    patch_size: int = 2
    in_channels: int = 16
    hidden: int = 5120               # WAN-14B class
    depth_double: int = 20
    depth_single: int = 20
    heads: int = 40
    context_dim: int = 4096
    pooled_dim: int = 768
    dtype: str = "bfloat16"
    remat: bool = False              # recompute block activations (HBM relief)

    @classmethod
    def wan(cls) -> "VideoDiTConfig":
        from ..utils import constants

        return cls(remat=constants.REMAT)

    @classmethod
    def tiny(cls) -> "VideoDiTConfig":
        return cls(patch_size=2, in_channels=4, hidden=64, depth_double=1,
                   depth_single=1, heads=4, context_dim=32, pooled_dim=16)

    def as_dit_config(self, dtype: Optional[str] = None) -> DiTConfig:
        return DiTConfig(
            patch_size=self.patch_size, in_channels=self.in_channels,
            hidden=self.hidden, depth_double=self.depth_double,
            depth_single=self.depth_single, heads=self.heads,
            context_dim=self.context_dim, pooled_dim=self.pooled_dim,
            guidance_embed=False, dtype=dtype or self.dtype,
            remat=self.remat)

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def patchify_video(x: jax.Array, p: int) -> jax.Array:
    """[B,F,H,W,C] → [B, F·(H/p)·(W/p), p·p·C], frame-major order."""
    B, F, H, W, C = x.shape
    x = x.reshape(B, F, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 2, 4, 3, 5, 6)
    return x.reshape(B, F * (H // p) * (W // p), p * p * C)


def unpatchify_video(tokens: jax.Array, fhw: tuple[int, int, int], p: int,
                     c: int) -> jax.Array:
    F, H, W = fhw
    B = tokens.shape[0]
    x = tokens.reshape(B, F, H // p, W // p, p, p, c)
    x = x.transpose(0, 1, 2, 4, 3, 5, 6)
    return x.reshape(B, F, H, W, c)


def sincos_3d(f: int, h: int, w: int, dim: int) -> jax.Array:
    """Axial 3-D position table [f·h·w, dim]: time/row/col chunks."""
    def axis_table(n, d):
        pos = jnp.arange(n, dtype=jnp.float32)
        freqs = jnp.exp(-math.log(10000.0) *
                        jnp.arange(d // 2, dtype=jnp.float32) / max(d // 2, 1))
        args = pos[:, None] * freqs[None]
        return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)

    dt_ = dim // 4                       # quarter for time, rest split h/w
    dh = (dim - dt_) // 2
    dw = dim - dt_ - dh
    tt = axis_table(f, dt_)              # [f, dt]
    th = axis_table(h, dh)
    tw = axis_table(w, dw)
    out = jnp.concatenate([
        jnp.repeat(tt, h * w, axis=0),
        jnp.tile(jnp.repeat(th, w, axis=0), (f, 1)),
        jnp.tile(tw, (f * h, 1)),
    ], axis=-1)
    return out


class VideoDiT(nn.Module):
    """x[B,F,h,w,C], t[B], context[B,T,ctx], pooled[B,P] → velocity."""

    config: VideoDiTConfig

    @nn.compact
    def __call__(self, x, t, context, pooled, sp_axis: Optional[str] = None):
        cfg = self.config
        dcfg = cfg.as_dit_config()
        dt = cfg.jnp_dtype
        B, F, H, W, C = x.shape
        p = cfg.patch_size

        tokens = patchify_video(x.astype(dt), p)
        img = nn.Dense(cfg.hidden, dtype=dt, name="img_in")(tokens)
        if sp_axis is None:
            pos = sincos_3d(F, H // p, W // p, cfg.hidden)
        else:
            n_sh = _axis_size(sp_axis)
            idx = jax.lax.axis_index(sp_axis)
            pos_full = sincos_3d(F * n_sh, H // p, W // p, cfg.hidden)
            per = pos_full.shape[0] // n_sh
            pos = jax.lax.dynamic_slice_in_dim(pos_full, idx * per, per, axis=0)
        img = img + pos[None].astype(dt)

        txt = nn.Dense(cfg.hidden, dtype=dt, name="txt_in")(context.astype(dt))
        vec = nn.Dense(cfg.hidden, dtype=dt, name="t_in")(
            timestep_embedding(t * 1000.0, 256).astype(dt))
        vec = vec + nn.Dense(cfg.hidden, dtype=dt, name="pool_in")(
            pooled.astype(dt))
        vec = nn.Dense(cfg.hidden, dtype=dt, name="vec_mlp")(nn.silu(vec))

        DBlock = (nn.remat(DoubleBlock, static_argnums=(4,))
                  if dcfg.remat else DoubleBlock)
        SBlock = (nn.remat(SingleBlock, static_argnums=(3, 4))
                  if dcfg.remat else SingleBlock)
        for i in range(cfg.depth_double):
            img, txt = DBlock(dcfg, name=f"double_{i}")(img, txt, vec, sp_axis)
        xcat = jnp.concatenate([txt, img], axis=1)
        T = txt.shape[1]
        for i in range(cfg.depth_single):
            xcat = SBlock(dcfg, name=f"single_{i}")(xcat, vec, T, sp_axis)
        img = xcat[:, T:]

        sh, sc, _ = Modulation(1, cfg.hidden, dt, name="final_mod")(vec)
        img = _modulate(
            nn.LayerNorm(use_scale=False, use_bias=False, dtype=dt)(img), sh, sc)
        out = nn.Dense(p * p * C, dtype=jnp.float32,
                       kernel_init=nn.initializers.zeros, name="img_out")(
            img.astype(jnp.float32))
        return unpatchify_video(out, (F, H, W), p, C)


def init_video_dit(config: VideoDiTConfig, rng: jax.Array,
                   sample_fhw: tuple[int, int, int] = (5, 8, 8),
                   context_len: int = 16, abstract: bool = False):
    model = VideoDiT(config)
    f, h, w = sample_fhw
    x = jnp.zeros((1, f, h, w, config.in_channels))
    args = (rng, x, jnp.zeros((1,)),
            jnp.zeros((1, context_len, config.context_dim)),
            jnp.zeros((1, config.pooled_dim)))
    if abstract:
        return model, jax.eval_shape(model.init, *args)
    return model, jax.jit(model.init)(*args)
