"""FLUX/SD3-class rectified-flow MMDiT.

Covers the BASELINE "FLUX.1-dev txt2img" config family — double-stream
(image/text) transformer blocks followed by single-stream blocks — AND the
SD3/SD3.5 family (``sd3_medium``/``sd35_large`` presets): joint-only
depth (``depth_single=0``), learned cropped position table, optional
qk-norm, no distilled-guidance embedder. Both share adaLN-Zero modulation
from (timestep, pooled text[, guidance]), patchified latents, and velocity
prediction for flow matching. The reference runs these models through
ComfyUI; here the architecture is native and **sequence-parallel
capable**: ``attn_backend="ring"`` runs joint attention with image tokens
sharded over the ``sp`` mesh axis (``ops/attention.joint_ring_attention``)
— the capability the reference entirely lacks (SURVEY §2.10: SP/CP
absent).

Positional encoding: selectable per config —

- ``pos_embed="sincos"``: axial 2-D sinusoidal added to patch embeddings
  (simple, fine for from-scratch training);
- ``pos_embed="rope"`` (the FLUX preset's default): 3-axis rotary
  embeddings applied to q/k per head exactly in FLUX's layout (axis 0 =
  text/time slot, axes 1-2 = patch row/col; ``rope_axes_dim`` must sum
  to ``head_dim``) — the form real FLUX checkpoints require, so weight
  porting needs no architectural surgery;
- ``pos_embed="learned"`` (the SD3 presets' default): a trained
  ``pos_embed_max_size²``-entry table added to patch embeddings after a
  CENTER crop to the sample's patch grid — SD3's exact scheme, so its
  checkpoints port table-intact and any resolution ≤ the table's square
  samples without interpolation.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from ..utils.jax_compat import axis_size as _axis_size
from flax import linen as nn

from ..ops.attention import full_attention, joint_ring_attention
from ..utils import constants
from .layers import timestep_embedding


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    patch_size: int = 2
    in_channels: int = 16            # FLUX VAE: 16 latent channels
    hidden: int = 3072
    depth_double: int = 19
    depth_single: int = 38
    heads: int = 24
    context_dim: int = 4096          # T5 features
    pooled_dim: int = 768            # CLIP pooled
    guidance_embed: bool = True      # FLUX-dev distilled guidance input
    dtype: str = "bfloat16"
    attn_backend: str = "dense"      # "dense" | "ring" | "flash"
                                     # ("flash" = dense compute with the
                                     # pallas kernel preferred regardless
                                     # of the seq-length gate — required
                                     # by the memory-starved offload
                                     # executor, ops/attention.py)
    pos_embed: str = "sincos"        # "sincos" | "rope" | "learned"
    pos_embed_max_size: int = 0      # "learned": side of the square table
    qk_norm: bool = True             # RMS qk-norm (FLUX, SD3.5; SD3-medium
                                     # checkpoints have no norm scales)
    remat: bool = False              # recompute block activations (HBM relief)
    rope_theta: float = 10000.0
    rope_axes_dim: Optional[tuple[int, int, int]] = None   # None → derived

    @classmethod
    def flux(cls) -> "DiTConfig":
        from ..utils import constants

        # FLUX.1: head_dim 128 = 16 (txt/time axis) + 56 (row) + 56 (col)
        return cls(pos_embed="rope", rope_axes_dim=(16, 56, 56),
                   remat=constants.REMAT)

    @classmethod
    def sd3_medium(cls) -> "DiTConfig":
        """SD3-medium (2B): 24 joint blocks, width 1536, no qk-norm."""
        from ..utils import constants

        return cls(hidden=1536, depth_double=24, depth_single=0, heads=24,
                   context_dim=4096, pooled_dim=2048, guidance_embed=False,
                   pos_embed="learned", pos_embed_max_size=192,
                   qk_norm=False, remat=constants.REMAT)

    @classmethod
    def sd35_large(cls) -> "DiTConfig":
        """SD3.5-large (8B): 38 joint blocks, width 2432, RMS qk-norm."""
        from ..utils import constants

        return cls(hidden=2432, depth_double=38, depth_single=0, heads=38,
                   context_dim=4096, pooled_dim=2048, guidance_embed=False,
                   pos_embed="learned", pos_embed_max_size=192,
                   qk_norm=True, remat=constants.REMAT)

    @classmethod
    def tiny(cls, attn_backend: str = "dense",
             pos_embed: str = "sincos", **kw) -> "DiTConfig":
        base = dict(patch_size=2, in_channels=4, hidden=64, depth_double=2,
                    depth_single=2, heads=4, context_dim=32, pooled_dim=16,
                    attn_backend=attn_backend, pos_embed=pos_embed)
        base.update(kw)
        return cls(**base)

    @classmethod
    def sd3_tiny(cls, attn_backend: str = "dense") -> "DiTConfig":
        """SD3-shaped tiny: joint-only depth, learned cropped pos table."""
        return cls.tiny(attn_backend, pos_embed="learned",
                        pos_embed_max_size=12, depth_double=2,
                        depth_single=0, qk_norm=False)

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @property
    def axes_dim(self) -> tuple[int, int, int]:
        """Per-axis RoPE widths (must sum to head_dim, all even)."""
        if self.rope_axes_dim is not None:
            return self.rope_axes_dim
        d0 = max(2, (self.head_dim // 8) // 2 * 2)
        rest = self.head_dim - d0
        dh = (rest // 2) // 2 * 2
        return (d0, dh, rest - dh)

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


def patchify(x: jax.Array, p: int) -> jax.Array:
    """[B,H,W,C] → [B, (H/p)(W/p), p·p·C]."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // p, p, W // p, p, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, (H // p) * (W // p), p * p * C)


def unpatchify(tokens: jax.Array, hw: tuple[int, int], p: int, c: int) -> jax.Array:
    B = tokens.shape[0]
    h, w = hw[0] // p, hw[1] // p
    x = tokens.reshape(B, h, w, p, p, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, hw[0], hw[1], c)


def sincos_2d(h: int, w: int, dim: int) -> jax.Array:
    """Axial 2-D sinusoidal position table [h·w, dim]."""
    def axis_table(n, d):
        pos = jnp.arange(n, dtype=jnp.float32)
        freqs = jnp.exp(-math.log(10000.0) * jnp.arange(d // 2, dtype=jnp.float32)
                        / (d // 2))
        args = pos[:, None] * freqs[None]
        return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)

    dh = dim // 2
    th = axis_table(h, dh)                      # [h, dh]
    tw = axis_table(w, dim - dh)                # [w, dim-dh]
    grid = jnp.concatenate([
        jnp.repeat(th, w, axis=0),
        jnp.tile(tw, (h, 1)),
    ], axis=-1)
    return grid


def rope_freqs(ids: jax.Array, axes_dim: tuple[int, ...],
               theta: float) -> tuple[jax.Array, jax.Array]:
    """FLUX multi-axis RoPE table.

    ``ids``: [N, n_axes] integer positions per token (txt tokens all-zero,
    img tokens (0, row, col)). Returns (cos, sin), each [N, head_dim/2]:
    axis a contributes ``axes_dim[a]/2`` rotation frequencies, concatenated
    in axis order — FLUX's EmbedND layout.
    """
    parts_cos, parts_sin = [], []
    for a, d in enumerate(axes_dim):
        half = d // 2
        freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) * 2.0 / d))
        args = ids[:, a].astype(jnp.float32)[:, None] * freqs[None]
        parts_cos.append(jnp.cos(args))
        parts_sin.append(jnp.sin(args))
    return (jnp.concatenate(parts_cos, axis=-1),
            jnp.concatenate(parts_sin, axis=-1))


def apply_rope(x: jax.Array, pe: tuple[jax.Array, jax.Array]) -> jax.Array:
    """Rotate q/k pairs: x [B, N, heads, head_dim], pe ([N, hd/2], [N, hd/2])."""
    cos, sin = pe
    cos = cos[None, :, None, :].astype(jnp.float32)
    sin = sin[None, :, None, :].astype(jnp.float32)
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., 0::2], xf[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def image_ids(h: int, w: int, row_offset: int = 0) -> jax.Array:
    """[h·w, 3] FLUX image token ids: (0, row, col)."""
    rows = jnp.repeat(jnp.arange(h) + row_offset, w)
    cols = jnp.tile(jnp.arange(w), (h,))
    return jnp.stack([jnp.zeros_like(rows), rows, cols], axis=-1)


class MLPEmbedder(nn.Module):
    """FLUX conditioning embedder: Dense → silu → Dense (in_layer/out_layer).

    Matches the checkpoint layout of FLUX's ``time_in``/``vector_in``/
    ``guidance_in`` MLPs so published weights convert without surgery.
    """

    hidden: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.Dense(self.hidden, dtype=self.dtype, name="in_layer")(x)
        return nn.Dense(self.hidden, dtype=self.dtype, name="out_layer")(nn.silu(h))


class Modulation(nn.Module):
    """adaLN-Zero: conditioning vector → (shift, scale, gate) × n."""

    n_outputs: int
    hidden: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, vec: jax.Array) -> tuple[jax.Array, ...]:
        out = nn.Dense(self.hidden * 3 * self.n_outputs, dtype=self.dtype,
                       kernel_init=nn.initializers.zeros, name="mod")(nn.silu(vec))
        return tuple(jnp.split(out[:, None, :], 3 * self.n_outputs, axis=-1))


def _modulate(x, shift, scale):
    return x * (1 + scale) + shift


class _QKV(nn.Module):
    hidden: int
    heads: int
    dtype: jnp.dtype
    qk_norm: bool = True

    @nn.compact
    def __call__(self, x):
        B, N, _ = x.shape
        qkv = nn.Dense(self.hidden * 3, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = self.hidden // self.heads
        shape = (B, N, self.heads, hd)
        if not self.qk_norm:
            # SD3-medium: raw q/k (its checkpoints carry no norm scales)
            return q.reshape(shape), k.reshape(shape), v.reshape(shape)
        # qk-norm (learned-scale RMS over head_dim) as in FLUX's QKNorm /
        # SD3.5's ln_q/ln_k — the scales land from checkpoints'
        # {query,key}_norm.scale / ln_{q,k}.weight entries
        qs = self.param("q_scale", nn.initializers.ones, (hd,), jnp.float32)
        ks = self.param("k_scale", nn.initializers.ones, (hd,), jnp.float32)
        q = _rms(q.reshape(shape)) * qs.astype(self.dtype)
        k = _rms(k.reshape(shape)) * ks.astype(self.dtype)
        return q, k, v.reshape(shape)


def _rms(x, eps=1e-6):
    return x * jax.lax.rsqrt(jnp.mean(x.astype(jnp.float32) ** 2, -1,
                                      keepdims=True) + eps).astype(x.dtype)


class DoubleBlock(nn.Module):
    """Separate image/text streams with joint attention (MMDiT)."""

    config: DiTConfig

    @nn.compact
    def __call__(self, img, txt, vec, sp_axis: Optional[str],
                 pe_img=None, pe_txt=None):
        cfg = self.config
        dt = cfg.jnp_dtype
        i_sh1, i_sc1, i_g1, i_sh2, i_sc2, i_g2 = Modulation(2, cfg.hidden, dt,
                                                            name="img_mod")(vec)
        t_sh1, t_sc1, t_g1, t_sh2, t_sc2, t_g2 = Modulation(2, cfg.hidden, dt,
                                                            name="txt_mod")(vec)

        img_n = _modulate(nn.LayerNorm(use_scale=False, use_bias=False,
                                       dtype=dt)(img), i_sh1, i_sc1)
        txt_n = _modulate(nn.LayerNorm(use_scale=False, use_bias=False,
                                       dtype=dt)(txt), t_sh1, t_sc1)
        iq, ik, iv = _QKV(cfg.hidden, cfg.heads, dt, cfg.qk_norm, name="img_qkv")(img_n)
        tq, tk, tv = _QKV(cfg.hidden, cfg.heads, dt, cfg.qk_norm, name="txt_qkv")(txt_n)
        if pe_img is not None:
            iq, ik = apply_rope(iq, pe_img), apply_rope(ik, pe_img)
            tq, tk = apply_rope(tq, pe_txt), apply_rope(tk, pe_txt)

        if sp_axis is None:
            q = jnp.concatenate([tq, iq], axis=1)
            k = jnp.concatenate([tk, ik], axis=1)
            v = jnp.concatenate([tv, iv], axis=1)
            out = full_attention(q, k, v,
                                 prefer_flash=cfg.attn_backend == "flash")
        else:
            q = jnp.concatenate([tq, iq], axis=1)
            out = joint_ring_attention(q, tk, tv, ik, iv, sp_axis)
        T = txt.shape[1]
        t_out, i_out = out[:, :T], out[:, T:]
        B = img.shape[0]
        i_out = i_out.reshape(B, -1, cfg.hidden)
        t_out = t_out.reshape(B, T, cfg.hidden)
        img = img + i_g1 * nn.Dense(cfg.hidden, dtype=dt, name="img_proj")(i_out)
        txt = txt + t_g1 * nn.Dense(cfg.hidden, dtype=dt, name="txt_proj")(t_out)

        img_m = _modulate(nn.LayerNorm(use_scale=False, use_bias=False,
                                       dtype=dt)(img), i_sh2, i_sc2)
        txt_m = _modulate(nn.LayerNorm(use_scale=False, use_bias=False,
                                       dtype=dt)(txt), t_sh2, t_sc2)
        img_h = nn.Dense(cfg.hidden * 4, dtype=dt, name="img_mlp_up")(img_m)
        img = img + i_g2 * nn.Dense(cfg.hidden, dtype=dt,
                                    name="img_mlp_down")(nn.gelu(img_h))
        txt_h = nn.Dense(cfg.hidden * 4, dtype=dt, name="txt_mlp_up")(txt_m)
        txt = txt + t_g2 * nn.Dense(cfg.hidden, dtype=dt,
                                    name="txt_mlp_down")(nn.gelu(txt_h))
        return img, txt


class SingleBlock(nn.Module):
    """Merged-stream block (FLUX single blocks)."""

    config: DiTConfig

    @nn.compact
    def __call__(self, x, vec, txt_len: int, sp_axis: Optional[str],
                 pe_full=None):
        cfg = self.config
        dt = cfg.jnp_dtype
        sh, sc, g = Modulation(1, cfg.hidden, dt, name="mod")(vec)
        xn = _modulate(nn.LayerNorm(use_scale=False, use_bias=False, dtype=dt)(x),
                       sh, sc)
        q, k, v = _QKV(cfg.hidden, cfg.heads, dt, cfg.qk_norm, name="qkv")(xn)
        if pe_full is not None:
            q, k = apply_rope(q, pe_full), apply_rope(k, pe_full)
        if sp_axis is None:
            out = full_attention(q, k, v,
                                 prefer_flash=cfg.attn_backend == "flash")
        else:
            # txt tokens lead the sequence on every shard
            tk, ik = k[:, :txt_len], k[:, txt_len:]
            tv, iv = v[:, :txt_len], v[:, txt_len:]
            out = joint_ring_attention(q, tk, tv, ik, iv, sp_axis)
        B, N, _, _ = out.shape
        out = out.reshape(B, N, cfg.hidden)
        mlp_in = nn.Dense(cfg.hidden * 4, dtype=dt, name="mlp_up")(xn)
        fused = jnp.concatenate([out, nn.gelu(mlp_in)], axis=-1)
        return x + g * nn.Dense(cfg.hidden, dtype=dt, name="out")(fused)


class DiT(nn.Module):
    """x[B,h,w,C], t[B] (flow time in [0,1]), context[B,T,ctx],
    pooled[B,P], guidance[B] → velocity [B,h,w,C]."""

    config: DiTConfig

    @nn.compact
    def __call__(self, x, t, context, pooled, guidance=None,
                 sp_axis: Optional[str] = None):
        cfg = self.config
        dt = cfg.jnp_dtype
        B, H, W, C = x.shape
        p = cfg.patch_size

        tokens = patchify(x.astype(dt), p)
        img = nn.Dense(cfg.hidden, dtype=dt, name="img_in")(tokens)
        pe_img = pe_txt = pe_full = None
        if cfg.pos_embed == "rope":
            # per-head rotary positions (FLUX layout); in sp mode the row
            # ids are offset by this shard's global row-block start so a
            # sharded run rotates identically to the unsharded one
            if sp_axis is None:
                ids_img = image_ids(H // p, W // p)
            else:
                idx = jax.lax.axis_index(sp_axis)
                ids_img = image_ids(H // p, W // p,
                                    row_offset=idx * (H // p))
            ids_txt = jnp.zeros((context.shape[1], 3), jnp.int32)
            pe_img = rope_freqs(ids_img, cfg.axes_dim, cfg.rope_theta)
            pe_txt = rope_freqs(ids_txt, cfg.axes_dim, cfg.rope_theta)
            pe_full = (jnp.concatenate([pe_txt[0], pe_img[0]], axis=0),
                       jnp.concatenate([pe_txt[1], pe_img[1]], axis=0))
        elif cfg.pos_embed == "learned":
            # SD3: trained (max × max) table, CENTER-cropped to the patch
            # grid; in sp mode each shard crops its own row block of the
            # global grid so the sharded run adds identical positions
            m = cfg.pos_embed_max_size
            table = self.param("pos_emb", nn.initializers.normal(0.01),
                               (m * m, cfg.hidden)).reshape(m, m, cfg.hidden)
            hp, wp = H // p, W // p
            n_sh = 1 if sp_axis is None else _axis_size(sp_axis)
            gh = hp * n_sh                       # global patch rows
            if gh > m or wp > m:
                raise ValueError(
                    f"sample grid {gh}×{wp} exceeds the learned position "
                    f"table ({m}×{m}) — SD3-family models cannot sample "
                    "beyond pos_embed_max_size patches per side")
            top, left = (m - gh) // 2, (m - wp) // 2
            rows = table[:, left:left + wp]
            if sp_axis is None:
                pos = rows[top:top + hp]
            else:
                idx = jax.lax.axis_index(sp_axis)
                pos = jax.lax.dynamic_slice_in_dim(
                    rows, top + idx * hp, hp, axis=0)
            img = img + pos.reshape(hp * wp, cfg.hidden)[None].astype(dt)
        elif sp_axis is None:
            pos = sincos_2d(H // p, W // p, cfg.hidden)
            img = img + pos[None].astype(dt)
        else:
            # x is this shard's row block of the global image: build the
            # global position table and slice this shard's rows
            n_sh = _axis_size(sp_axis)
            idx = jax.lax.axis_index(sp_axis)
            pos_full = sincos_2d((H * n_sh) // p, W // p, cfg.hidden)
            per = pos_full.shape[0] // n_sh
            pos = jax.lax.dynamic_slice_in_dim(pos_full, idx * per, per, axis=0)
            img = img + pos[None].astype(dt)

        txt = nn.Dense(cfg.hidden, dtype=dt, name="txt_in")(context.astype(dt))

        # FLUX conditioning vector: summed MLPEmbedder outputs (time_in /
        # vector_in / guidance_in) — the exact functional form of the
        # published checkpoints, so weights port without surgery
        vec = MLPEmbedder(cfg.hidden, dt, name="time_in")(
            timestep_embedding(t * 1000.0, 256).astype(dt))
        vec = vec + MLPEmbedder(cfg.hidden, dt, name="vector_in")(pooled.astype(dt))
        if cfg.guidance_embed:
            gvec = guidance if guidance is not None else jnp.full((B,), 3.5)
            vec = vec + MLPEmbedder(cfg.hidden, dt, name="guidance_in")(
                timestep_embedding(gvec * 1000.0, 256).astype(dt))

        DBlock = (nn.remat(DoubleBlock, static_argnums=(4,))
                  if cfg.remat else DoubleBlock)
        SBlock = (nn.remat(SingleBlock, static_argnums=(3, 4))
                  if cfg.remat else SingleBlock)
        for i in range(cfg.depth_double):
            img, txt = DBlock(cfg, name=f"double_{i}")(
                img, txt, vec, sp_axis, pe_img, pe_txt)
        xcat = jnp.concatenate([txt, img], axis=1)
        T = txt.shape[1]
        for i in range(cfg.depth_single):
            xcat = SBlock(cfg, name=f"single_{i}")(xcat, vec, T, sp_axis,
                                                   pe_full)
        img = xcat[:, T:]

        sh, sc, _ = Modulation(1, cfg.hidden, dt, name="final_mod")(vec)
        img = _modulate(nn.LayerNorm(use_scale=False, use_bias=False, dtype=dt)(img),
                        sh, sc)
        out = nn.Dense(p * p * C, dtype=jnp.float32,
                       kernel_init=nn.initializers.zeros, name="img_out")(
            img.astype(jnp.float32))
        # in sp mode (H, W) is the local row block — output stays local,
        # so the sampler update is shard-local too
        return unpatchify(out, (H, W), p, C)


def init_dit(config: DiTConfig, rng: jax.Array,
             sample_hw: tuple[int, int] = (32, 32), context_len: int = 16,
             abstract: bool = False, param_dtype=None):
    """``abstract=True`` returns a ShapeDtypeStruct tree instead of
    materialized random params — the shape template weight conversion
    needs without paying a 12B-param random init (FLUX-size presets).
    ``param_dtype`` casts float params inside the fused init program
    (see ``models/unet.init_unet``) — bf16 residency is what lets a
    FLUX-class model fit accelerator HBM at all."""
    from .unet import casting_init

    model = DiT(config)
    h, w = sample_hw
    x = jnp.zeros((1, h, w, config.in_channels))
    t = jnp.zeros((1,))
    ctx = jnp.zeros((1, context_len, config.context_dim))
    pooled = jnp.zeros((1, config.pooled_dim))
    init_fn = casting_init(model.init, param_dtype)
    if abstract:
        params = jax.eval_shape(init_fn, rng, x, t, ctx, pooled)
    else:
        params = jax.jit(init_fn)(rng, x, t, ctx, pooled)
    return model, params
