"""Diffusion model zoo in flax (TPU-native).

The reference delegates all model code to ComfyUI (``comfy.samplers``,
``comfy.model_management`` — SURVEY "external substrate"); a standalone TPU
framework must supply it. Models are written flax-linen, bfloat16 compute /
float32 params, static shapes, MXU-friendly (channels stay multiples of 64,
attention via fused ``jax.nn.dot_product_attention``).

Families
--------
unet     SDXL-class latent UNet (eps-pred, cross-attention conditioning)
vae      AutoencoderKL encoder/decoder (latent ↔ pixel)
dit      FLUX-class rectified-flow MMDiT
text     text conditioning encoders
video    WAN-class video DiT (frame-axis aware)
"""

from .unet import UNetConfig, UNet2D  # noqa: F401
from .vae import VAEConfig, Decoder, Encoder, AutoencoderKL  # noqa: F401
