"""T5-family text encoder (flax) + weight converter.

FLUX conditions on T5-XXL last-hidden features (context_dim 4096) and
WAN-class video models on UMT5-XXL; the reference gets both for free from
ComfyUI's text-encoder loaders (SURVEY "external substrate"). This module
owns them natively:

- :class:`T5Encoder` — encoder-only stack: relative-position-bias
  attention (shared-first-layer for T5 v1.1, per-layer for UMT5),
  pre-RMSNorm, un-scaled dot-product scores (T5 folds the 1/√d into its
  init), gated-GELU feed-forward.
- :func:`convert_t5` — HF ``T5EncoderModel``/``UMT5EncoderModel`` state
  dicts → these params, template-driven with the same
  shape/coverage guarantees as ``models/convert.py``.
- :class:`FluxTextStack` — the conditioning pair FLUX checkpoints assume
  (T5 context + CLIP-L pooled), ``TextEncoder``-compatible via
  :class:`clip.CLIPConditioner`-style ``encode``.

Differential tests: ``tests/test_t5.py`` requires exact output parity
against ``transformers`` T5/UMT5 encoders with random weights.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 4096
    d_ff: int = 10240
    num_layers: int = 24
    num_heads: int = 64
    d_kv: int = 64
    rel_buckets: int = 32
    rel_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    per_layer_rel_bias: bool = False     # UMT5: every layer owns a table
    max_len: int = 512
    dtype: str = "float32"

    @classmethod
    def xxl(cls) -> "T5Config":
        """google/t5-v1_1-xxl encoder — FLUX's text tower."""
        return cls()

    @classmethod
    def umt5_xxl(cls) -> "T5Config":
        """google/umt5-xxl encoder — WAN-class video models' text tower."""
        return cls(vocab_size=256384, per_layer_rel_bias=True, max_len=512)

    @classmethod
    def tiny(cls, **kw) -> "T5Config":
        base = dict(vocab_size=128, d_model=32, d_ff=64, num_layers=2,
                    num_heads=4, d_kv=8, rel_buckets=8, rel_max_distance=16,
                    max_len=16)
        base.update(kw)
        return cls(**base)


def _rel_bucket(rel: jax.Array, num_buckets: int, max_distance: int) -> jax.Array:
    """T5 bidirectional relative-position bucketing (HF semantics)."""
    num_buckets //= 2
    ret = (rel > 0).astype(jnp.int32) * num_buckets
    n = jnp.abs(rel)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # avoid log(0); is_small branch covers n < max_exact anyway
    nf = jnp.maximum(n, 1).astype(jnp.float32)
    val_large = max_exact + (
        jnp.log(nf / max_exact) / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


class _T5LayerNorm(nn.Module):
    """RMS norm, no bias, no mean subtraction (T5 style)."""

    eps: float

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


class _T5Attention(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x: jax.Array, bias: jax.Array,
                 mask: Optional[jax.Array]) -> jax.Array:
        cfg = self.config
        inner = cfg.num_heads * cfg.d_kv
        B, N, _ = x.shape
        shape = (B, N, cfg.num_heads, cfg.d_kv)
        q = nn.Dense(inner, use_bias=False, name="q")(x).reshape(shape)
        k = nn.Dense(inner, use_bias=False, name="k")(x).reshape(shape)
        v = nn.Dense(inner, use_bias=False, name="v")(x).reshape(shape)
        # T5 does NOT scale scores: 1/√d is folded into the init
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) + bias
        if mask is not None:
            s = s + mask
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, N, inner)
        return nn.Dense(cfg.d_model, use_bias=False, name="o")(out)


class _T5FF(nn.Module):
    """Gated-GELU feed forward (T5 v1.1 / UMT5)."""

    config: T5Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        g = nn.Dense(cfg.d_ff, use_bias=False, name="wi_0")(x)
        u = nn.Dense(cfg.d_ff, use_bias=False, name="wi_1")(x)
        return nn.Dense(cfg.d_model, use_bias=False, name="wo")(
            nn.gelu(g, approximate=True) * u)


class T5Encoder(nn.Module):
    """tokens [B,N] (+ optional attn_mask [B,N]) → last hidden [B,N,d]."""

    config: T5Config

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 attn_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        B, N = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.d_model, name="shared")(tokens)

        pos = jnp.arange(N)
        rel = pos[None, :] - pos[:, None]              # memory - query
        buckets = _rel_bucket(rel, cfg.rel_buckets, cfg.rel_max_distance)
        mask = None
        if attn_mask is not None:
            mask = (1.0 - attn_mask[:, None, None, :].astype(jnp.float32)) * -1e9

        def bias_table(name):
            emb = nn.Embed(cfg.rel_buckets, cfg.num_heads, name=name)
            return emb(buckets).transpose(2, 0, 1)[None]   # [1,H,Nq,Nk]

        shared_bias = None
        for i in range(cfg.num_layers):
            if cfg.per_layer_rel_bias:
                bias = bias_table(f"rel_bias_{i}")
            else:
                if shared_bias is None:
                    shared_bias = bias_table("rel_bias")
                bias = shared_bias
            h = _T5LayerNorm(cfg.layer_norm_eps, name=f"ln_attn_{i}")(x)
            x = x + _T5Attention(cfg, name=f"attn_{i}")(h, bias, mask)
            h = _T5LayerNorm(cfg.layer_norm_eps, name=f"ln_ff_{i}")(x)
            x = x + _T5FF(cfg, name=f"ff_{i}")(h)
        return _T5LayerNorm(cfg.layer_norm_eps, name="final_ln")(x)


@dataclasses.dataclass
class T5Model:
    """Host wrapper: module + params."""

    config: T5Config
    params: Optional[dict] = None

    def __post_init__(self):
        self.module = T5Encoder(self.config)

    def init(self, rng: jax.Array, abstract: bool = False) -> "T5Model":
        toks = jnp.zeros((1, self.config.max_len), jnp.int32)
        if abstract:
            # shape template only (conversion about to replace every leaf
            # — a T5-XXL random init alone is ~19 GB)
            self.params = jax.eval_shape(self.module.init, rng, toks)
        else:
            self.params = jax.jit(self.module.init)(rng, toks)
        return self

    def __call__(self, tokens: jax.Array, attn_mask=None) -> jax.Array:
        from .layers import jit_apply

        return jit_apply(self, self.module)(self.params, tokens, attn_mask)


# ---------------------------------------------------------------------------
# converter (HF T5EncoderModel / UMT5EncoderModel state dicts)
# ---------------------------------------------------------------------------

def convert_t5(sd, template, config: T5Config) -> dict:
    """HF ``T5EncoderModel``/``UMT5EncoderModel`` state dict → params."""
    from .convert import ConversionError, _Filler

    f = _Filler(sd, template["params"])
    f.put("shared.weight", "shared/embedding")
    if "encoder.embed_tokens.weight" in sd:       # tied copy HF also emits
        f.used.add("encoder.embed_tokens.weight")
    for i in range(config.num_layers):
        blk = f"encoder.block.{i}.layer"
        for proj in ("q", "k", "v", "o"):
            f.put(f"{blk}.0.SelfAttention.{proj}.weight",
                  f"attn_{i}/{proj}/kernel",
                  lambda w: np.asarray(w, np.float32).T)
        f.put(f"{blk}.0.layer_norm.weight", f"ln_attn_{i}/weight")
        bias_key = f"{blk}.0.SelfAttention.relative_attention_bias.weight"
        if config.per_layer_rel_bias:
            f.put(bias_key, f"rel_bias_{i}/embedding")
        elif i == 0:
            f.put(bias_key, "rel_bias/embedding")
        for proj in ("wi_0", "wi_1", "wo"):
            f.put(f"{blk}.1.DenseReluDense.{proj}.weight",
                  f"ff_{i}/{proj}/kernel",
                  lambda w: np.asarray(w, np.float32).T)
        f.put(f"{blk}.1.layer_norm.weight", f"ln_ff_{i}/weight")
    f.put("encoder.final_layer_norm.weight", "final_ln/weight")
    tree = f.finish()
    leftover = [k for k in sd if k not in f.used]
    if leftover:
        raise ConversionError(
            f"unconsumed T5 keys: {leftover[:8]}"
            f"{'…' if len(leftover) > 8 else ''}")
    return {"params": tree}


def load_t5_tokenizer(tok_dir=None):
    """SentencePiece tokenizer for T5, loaded via ``transformers`` from
    ``CDT_T5_TOKENIZER_DIR`` (the ``spiece.model``/``tokenizer.json`` every
    T5 distribution ships). Returns None when unavailable — callers fall
    back to hash tokens exactly like the CLIP path."""
    from ..utils import constants

    tok_dir = tok_dir or constants.T5_TOKENIZER_DIR.get()
    if not tok_dir:
        return None
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(tok_dir)
    except Exception as e:                        # noqa: BLE001
        from ..utils.logging import log

        log(f"WARNING: T5 tokenizer load failed ({e}); hash fallback in use")
        return None


def t5_token_ids(cfg: T5Config, tok, texts, count: bool = True):
    """Strings → (ids [B,max_len], mask [B,max_len]): SentencePiece when a
    tokenizer is loaded, deterministic hash fallback (with </s> framing so
    masking works) otherwise. ``count=False`` skips the degradation
    counter (cache key-signature tokenization)."""
    if tok is not None:
        enc = tok(list(texts), padding="max_length", truncation=True,
                  max_length=cfg.max_len, return_tensors="np")
        return (jnp.asarray(enc["input_ids"], jnp.int32),
                jnp.asarray(enc["attention_mask"], jnp.int32))
    if count:
        from .clip import _count_hash_tokenization

        _count_hash_tokenization("t5")
    import hashlib

    def fallback(text):
        ids = [int.from_bytes(
            hashlib.blake2s(w.encode(), digest_size=4).digest(),
            "little") % (cfg.vocab_size - 2) + 2
            for w in text.lower().split()][: cfg.max_len - 1]
        ids = ids + [1]                           # </s>
        mask = [1] * len(ids) + [0] * (cfg.max_len - len(ids))
        return ids + [0] * (cfg.max_len - len(ids)), mask

    pairs = [fallback(t) for t in texts]
    return (jnp.asarray([p[0] for p in pairs], jnp.int32),
            jnp.asarray([p[1] for p in pairs], jnp.int32))


class UMT5Conditioner:
    """WAN-class conditioning: UMT5 last-hidden context only (the model
    has no pooled-vector input — ``WanModel`` ignores ``pooled``, which is
    returned as zeros purely for ``TextEncoder.encode`` API parity)."""

    def __init__(self, t5: T5Model, tok=None, pooled_dim: int = 768):
        self.t5 = t5
        self.pooled_dim = pooled_dim
        self.tok = tok if tok is not None else load_t5_tokenizer()
        if self.tok is None:
            from ..utils.logging import log

            log("WARNING: no T5 tokenizer (CDT_T5_TOKENIZER_DIR) — text is "
                "hash-tokenized; conditioning will not reflect the prompt")

    @classmethod
    def init_random(cls, rng: jax.Array, tiny: bool = False,
                    abstract_t5: bool = False) -> "UMT5Conditioner":
        cfg = (T5Config.tiny(per_layer_rel_bias=True) if tiny
               else T5Config.umt5_xxl())
        return cls(T5Model(cfg).init(rng, abstract=abstract_t5))

    def token_signature(self, texts) -> tuple[list, str]:
        """Conditioning-cache key material (cluster/cache): ids+mask and
        the real-vs-hash mode, so a degraded (vocab-less) worker can
        never poison the shared tier."""
        ids, mask = t5_token_ids(self.t5.config, self.tok,
                                 [str(t) for t in texts], count=False)
        return ([ids.tolist(), mask.tolist()],
                f"t5={'sp' if self.tok is not None else 'hash'}")

    @property
    def tokenization_mode(self) -> str:
        return "sp" if self.tok is not None else "hash"

    def encode(self, texts) -> tuple[jax.Array, jax.Array]:
        texts = [str(t) for t in texts]
        ids, mask = t5_token_ids(self.t5.config, self.tok, texts)
        context = self.t5(ids, mask)
        return context, jnp.zeros((len(texts), self.pooled_dim),
                                  context.dtype)


class FluxTextStack:
    """The conditioning pair FLUX checkpoints assume: T5 last-hidden
    context + CLIP-L pooled vector.

    ``encode(texts)`` → ``context [B, T, d_model]``, ``pooled [B, 768]`` —
    drop-in for ``TextEncoder.encode`` so pipelines and graph nodes work
    unchanged (reference analogue: ComfyUI's DualCLIPLoader wiring).
    """

    def __init__(self, t5: T5Model, clip_l, t5_tok=None, clip_tok=None):
        self.t5 = t5
        self.clip_l = clip_l
        self.t5_tok = t5_tok if t5_tok is not None else load_t5_tokenizer()
        if clip_tok is None:
            from .clip import validate_tokenizer_vocab
            from .tokenizer import load_sd_tokenizers

            # tokenize to the TOWER's context length (its position table
            # only covers config.max_len), and refuse a mismatched vocab
            clip_tok, _ = load_sd_tokenizers(max_len=clip_l.config.max_len)
            if clip_tok is not None:
                validate_tokenizer_vocab(clip_tok, clip_l.config, "clip_l")
        self.clip_tok = clip_tok
        from ..utils.logging import log

        if self.t5_tok is None:
            log("WARNING: no T5 tokenizer (CDT_T5_TOKENIZER_DIR) — text is "
                "hash-tokenized; conditioning will not reflect the prompt")
        if self.clip_tok is None:
            log("WARNING: no CLIP vocab at CDT_TOKENIZER_DIR — the pooled "
                "vector is hash-tokenized and will not reflect the prompt")

    @classmethod
    def init_random(cls, rng: jax.Array, tiny: bool = False,
                    abstract_t5: bool = False) -> "FluxTextStack":
        from .clip import CLIPTextConfig, CLIPTextModel

        k1, k2 = jax.random.split(rng)
        t5_cfg = T5Config.tiny() if tiny else T5Config.xxl()
        clip_cfg = CLIPTextConfig.tiny() if tiny else CLIPTextConfig.clip_l()
        return cls(T5Model(t5_cfg).init(k1, abstract=abstract_t5),
                   CLIPTextModel(clip_cfg).init(k2))

    def token_signature(self, texts) -> tuple[list, str]:
        from .clip import tokenize_ids

        texts = [str(t) for t in texts]
        ids, mask = t5_token_ids(self.t5.config, self.t5_tok, texts,
                                 count=False)
        cfg = self.clip_l.config
        toks = tokenize_ids(texts, self.clip_tok, cfg, cfg.eot_token_id,
                            count=False)
        mode = (f"t5={'sp' if self.t5_tok is not None else 'hash'},"
                f"l={'bpe' if self.clip_tok is not None else 'hash'}")
        return [ids.tolist(), mask.tolist(), toks.tolist()], mode

    @property
    def tokenization_mode(self) -> str:
        return ("real" if (self.t5_tok is not None
                           and self.clip_tok is not None) else "hash")

    def encode(self, texts) -> tuple[jax.Array, jax.Array]:
        from .clip import tokenize_ids

        texts = [str(t) for t in texts]
        ids, mask = t5_token_ids(self.t5.config, self.t5_tok, texts)
        context = self.t5(ids, mask)
        cfg = self.clip_l.config
        toks = tokenize_ids(texts, self.clip_tok, cfg, cfg.eot_token_id,
                            tower="clip_l")
        pooled = self.clip_l(toks)["pooled"]
        return context, pooled


class SD3TextStack:
    """SD3-family tri-encoder conditioning (CLIP-L + CLIP-G + T5-XXL).

    SD3's contract (matching sd3's own inference wiring the reference
    inherits via ComfyUI's sd3_clip):

    - ``context`` = sequence concat of the zero-padded CLIP block and the
      T5 block: ``pad(concat_feat(L.penultimate, G.penultimate), d_t5)``
      followed by T5 last-hidden — ``[B, 77 + T5_len, 4096]`` at full
      size;
    - ``pooled`` = ``concat(L.projected, G.projected)`` — ``[B, 2048]``.

    ``encode(texts)`` is drop-in for ``TextEncoder.encode`` so pipelines
    and graph nodes work unchanged.
    """

    def __init__(self, clip_l, clip_g, t5: T5Model, t5_tok=None,
                 tok_l=None, tok_g=None):
        from ..utils.logging import log
        from .clip import validate_tokenizer_vocab
        from .tokenizer import CLIPBPETokenizer, load_sd_tokenizers

        self.clip_l = clip_l
        self.clip_g = clip_g
        self.t5 = t5
        self.t5_tok = t5_tok if t5_tok is not None else load_t5_tokenizer()
        if (tok_l is None) != (tok_g is None):
            # a single explicit tokenizer would crash vocab validation on
            # the None twin (advisor r05) — require the pair, loudly
            raise ValueError(
                "SD3TextStack needs both tok_l and tok_g (or neither, to "
                "auto-load from CDT_TOKENIZER_DIR); got only "
                f"{'tok_l' if tok_g is None else 'tok_g'}")
        if tok_l is None and tok_g is None:
            tok_l, _ = load_sd_tokenizers(max_len=clip_l.config.max_len)
            if tok_l is not None:
                tok_g = CLIPBPETokenizer.from_env(
                    max_len=clip_g.config.max_len, pad_token_id=0)
        self.tok_l, self.tok_g = tok_l, tok_g
        if self.tok_l is not None:
            validate_tokenizer_vocab(self.tok_l, clip_l.config, "clip_l")
            if self.tok_g is None:
                log("WARNING: no tokenizer for the clip_g tower; it "
                    "falls back to hash tokenization")
            else:
                validate_tokenizer_vocab(self.tok_g, clip_g.config,
                                         "clip_g")
        else:
            log("WARNING: no CLIP vocab at CDT_TOKENIZER_DIR — text is "
                "hash-tokenized; conditioning will not reflect the prompt")
        if self.t5_tok is None:
            log("WARNING: no T5 tokenizer (CDT_T5_TOKENIZER_DIR) — the T5 "
                "context block is hash-tokenized")

    @classmethod
    def init_random(cls, rng: jax.Array, tiny: bool = False,
                    abstract_t5: bool = False) -> "SD3TextStack":
        import dataclasses

        from .clip import CLIPTextConfig, CLIPTextModel

        k1, k2, k3 = jax.random.split(rng, 3)
        if tiny:
            # concat widths (16+16) == T5-tiny d_model, projections 8+8
            # == the sd3-tiny preset's pooled_dim
            cfg_l = CLIPTextConfig.tiny(width=16, heads=2, projection_dim=8)
            cfg_g = CLIPTextConfig.tiny(width=16, heads=2, act="gelu",
                                        projection_dim=8)
            t5_cfg = T5Config.tiny()
        else:
            cfg_l = dataclasses.replace(CLIPTextConfig.clip_l(),
                                        projection_dim=768)
            cfg_g = CLIPTextConfig.clip_g()
            t5_cfg = T5Config.xxl()
        return cls(CLIPTextModel(cfg_l).init(k1),
                   CLIPTextModel(cfg_g).init(k2),
                   T5Model(t5_cfg).init(k3, abstract=abstract_t5))

    def token_signature(self, texts) -> tuple[list, str]:
        from .clip import tokenize_ids

        texts = [str(t) for t in texts]
        l_cfg, g_cfg = self.clip_l.config, self.clip_g.config
        toks_l = tokenize_ids(texts, self.tok_l, l_cfg, l_cfg.eot_token_id,
                              count=False)
        toks_g = tokenize_ids(texts, self.tok_g, g_cfg, 0, count=False)
        ids, mask = t5_token_ids(self.t5.config, self.t5_tok, texts,
                                 count=False)
        mode = (f"l={'bpe' if self.tok_l is not None else 'hash'},"
                f"g={'bpe' if self.tok_g is not None else 'hash'},"
                f"t5={'sp' if self.t5_tok is not None else 'hash'}")
        return [toks_l.tolist(), toks_g.tolist(), ids.tolist(),
                mask.tolist()], mode

    @property
    def tokenization_mode(self) -> str:
        return ("real" if (self.tok_l is not None and self.tok_g is not None
                           and self.t5_tok is not None) else "hash")

    def encode(self, texts) -> tuple[jax.Array, jax.Array]:
        from .clip import tokenize_ids

        texts = [str(t) for t in texts]
        l_cfg, g_cfg = self.clip_l.config, self.clip_g.config
        out_l = self.clip_l(tokenize_ids(texts, self.tok_l, l_cfg,
                                         l_cfg.eot_token_id,
                                         tower="clip_l"))
        out_g = self.clip_g(tokenize_ids(texts, self.tok_g, g_cfg, 0,
                                         tower="clip_g"))
        clip_ctx = jnp.concatenate(
            [out_l["penultimate"], out_g["penultimate"]], axis=-1)
        d = self.t5.config.d_model
        if clip_ctx.shape[-1] > d:
            raise ValueError(
                f"CLIP concat width {clip_ctx.shape[-1]} exceeds the T5 "
                f"d_model {d} — the stack's towers are mismatched")
        clip_ctx = jnp.pad(
            clip_ctx, ((0, 0), (0, 0), (0, d - clip_ctx.shape[-1])))
        ids, mask = t5_token_ids(self.t5.config, self.t5_tok, texts)
        t5_ctx = self.t5(ids, mask)
        context = jnp.concatenate(
            [clip_ctx, t5_ctx.astype(clip_ctx.dtype)], axis=1)
        pooled = jnp.concatenate(
            [out_l["projected"], out_g["projected"]], axis=-1)
        return context, pooled
