"""WAN-geometry 3D causal video VAE (flax).

The reference free-rides on ComfyUI for video VAEs (SURVEY "external
substrate"); the WAN family compresses video 4× in time and 8× in space
through a *causal* 3D conv stack, which is what makes its 4n+1 frame
rule work: ``T`` pixel frames ↔ ``(T-1)/4 + 1`` latent frames, with the
first frame compressed alone (so single images are valid 1-frame
videos). This module implements that geometry TPU-natively:

- causal 3D convs (time padded front-only with edge replication — no
  future leakage, so prefix decodes are consistent with full decodes);
- channel-RMS norms, SiLU residual blocks, single-head spatial
  attention in the bottleneck;
- temporal downsample = stride-2 causal conv (``ceil(T/2)``); temporal
  upsample = per-frame frame-pair expansion minus the leading duplicate
  (``2T-1``) — exact inverses over the 4n+1 family.

The ~4× shorter latent frame axis is a direct transformer-sequence
reduction for ``WanModel`` — the dominant video-generation cost.

Weight portability for published WAN VAE checkpoints is **not yet
wired** (the official stack's streaming-cache forward has extra
chunk-boundary semantics); the architecture is init-compatible with the
geometry and ships behind the same ``encode``/``decode`` interface as
``AutoencoderKL`` so it slots into ``VideoPipeline`` unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class WanVAEConfig:
    in_channels: int = 3
    latent_channels: int = 16
    base_dim: int = 96
    dim_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    # one entry per downsample transition (len(dim_mult) - 1): True adds
    # stride-2 temporal compression to that spatial downsample
    temporal_downsample: tuple[bool, ...] = (False, True, True)
    scaling_factor: float = 1.0
    dtype: str = "float32"

    @classmethod
    def wan(cls, dtype: str = "bfloat16") -> "WanVAEConfig":
        # bf16 compute: a 33×480×832 decode holds multiple ~[33,480,832,96]
        # activation buffers — f32 needs >31 GB HBM (observed OOM on v5e),
        # bf16 halves it; combined with decode_tiled it fits one chip
        return cls(dtype=dtype)

    @classmethod
    def tiny(cls, **kw) -> "WanVAEConfig":
        base = dict(latent_channels=4, base_dim=16, dim_mult=(1, 2),
                    num_res_blocks=1, temporal_downsample=(True,))
        base.update(kw)
        return cls(**base)

    @property
    def downscale(self) -> int:
        """Spatial compression (one stride-2 per dim transition)."""
        return 2 ** (len(self.dim_mult) - 1)

    @property
    def temporal_downscale(self) -> int:
        return 2 ** sum(self.temporal_downsample)

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    def latent_frames(self, frames: int) -> int:
        """4n+1 pixel frames → n+1 latent frames (causal: first alone)."""
        return (frames - 1) // self.temporal_downscale + 1

    def pixel_frames(self, latent_frames: int) -> int:
        return (latent_frames - 1) * self.temporal_downscale + 1


def _tile_starts(full: int, t: int, step: int) -> list[int]:
    """Origin-anchored tile starts with the last start clamped to
    ``full - t`` so the final tile never runs past the edge."""
    if full <= t:
        return [0]
    out = list(range(0, full - t, step)) + [full - t]
    return sorted(set(out))


def _pair_feathers(starts_list: list[int], t: int):
    """Per-tile (lo, hi) feather widths in latent units: each side
    feathers over the ACTUAL overlap with its neighbor. The last start is
    clamped (``_tile_starts``), so its overlap with the previous tile can
    exceed the nominal ``overlap`` — feathering only the nominal width
    would leave a weight-1/weight-1 band that hard-averages (visible seam
    at the final row/column)."""
    ovs = [starts_list[i - 1] + t - starts_list[i]
           for i in range(1, len(starts_list))]
    return [0] + ovs, ovs + [0]


def _axis_ramp(n_lat: int, lo_o: int, hi_o: int, *, scale: int) -> np.ndarray:
    """Per-pixel weight along one axis of a decoded tile; ramps multiply
    so an extra-wide lo/hi pair composes instead of one overwriting the
    other."""
    n = n_lat * scale
    wgt = np.ones((n,), np.float32)
    o = min(lo_o, n_lat) * scale
    if o:
        wgt[:o] *= np.linspace(1.0 / (o + 1), 1.0, o, dtype=np.float32)
    o = min(hi_o, n_lat) * scale
    if o:                  # guard: wgt[-0:] is the WHOLE array
        wgt[-o:] *= np.linspace(1.0, 1.0 / (o + 1), o, dtype=np.float32)
    return wgt


def _pad_time_causal(x: jax.Array, n: int) -> jax.Array:
    """Front-pad the frame axis with ``n`` copies of the first frame."""
    if n == 0:
        return x
    first = jnp.repeat(x[:, :1], n, axis=1)
    return jnp.concatenate([first, x], axis=1)


class CausalConv3d(nn.Module):
    """[B,T,H,W,C] conv: causal (front-padded) in time, SAME in space."""

    features: int
    kernel: tuple[int, int, int] = (3, 3, 3)
    time_stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kt, kh, kw = self.kernel
        x = _pad_time_causal(x, kt - 1)
        return nn.Conv(
            self.features, self.kernel,
            strides=(self.time_stride, 1, 1),
            padding=[(0, 0), (kh // 2, kh // 2), (kw // 2, kw // 2)],
            dtype=self.dtype, name="conv")(x)


class ChannelRMSNorm(nn.Module):
    """L2-normalize the channel axis × √C × learned gamma (WAN's norm)."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        g = self.param("gamma", nn.initializers.ones, (c,))
        xf = x.astype(jnp.float32)
        n = xf * jax.lax.rsqrt(jnp.sum(xf * xf, -1, keepdims=True) + 1e-12)
        return (n * (c ** 0.5)).astype(x.dtype) * g.astype(x.dtype)


class ResBlock3d(nn.Module):
    features: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = ChannelRMSNorm(name="norm1")(x)
        h = CausalConv3d(self.features, dtype=self.dtype,
                         name="conv1")(nn.silu(h))
        h = ChannelRMSNorm(name="norm2")(h)
        h = CausalConv3d(self.features, dtype=self.dtype,
                         name="conv2")(nn.silu(h))
        if x.shape[-1] != self.features:
            x = nn.Dense(self.features, dtype=self.dtype, name="skip")(x)
        return x + h


class SpatialAttention(nn.Module):
    """Single-head per-frame spatial self-attention (bottleneck only)."""

    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, T, H, W, C = x.shape
        h = ChannelRMSNorm(name="norm")(x).reshape(B * T, H * W, C)
        qkv = nn.Dense(C * 3, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        s = jnp.einsum("bqc,bkc->bqk", q, k) / (C ** 0.5)
        out = jnp.einsum("bqk,bkc->bqc", jax.nn.softmax(s, axis=-1), v)
        out = nn.Dense(C, dtype=self.dtype, name="proj")(out)
        return x + out.reshape(B, T, H, W, C)


class _Downsample(nn.Module):
    features: int
    temporal: bool
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, T, H, W, C = x.shape
        # spatial: stride-2 conv per frame (zero-pad bottom/right, WAN style)
        h = x.reshape(B * T, H, W, C)
        h = jnp.pad(h, ((0, 0), (0, 1), (0, 1), (0, 0)))
        h = nn.Conv(self.features, (3, 3), strides=(2, 2), padding="VALID",
                    dtype=self.dtype, name="space")(h)
        h = h.reshape(B, T, H // 2, W // 2, self.features)
        if self.temporal:
            # stride-2 causal conv: T → ceil(T/2), frame 0 kept alone
            h = _pad_time_causal(h, 1)
            h = nn.Conv(self.features, (2, 1, 1), strides=(2, 1, 1),
                        padding="VALID", dtype=self.dtype, name="time")(h)
        return h


class _Upsample(nn.Module):
    features: int
    temporal: bool
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.temporal:
            # every latent frame expands to a frame pair; the leading
            # duplicate is dropped: T → 2T-1 (inverse of ceil(T/2))
            B, T, H, W, C = x.shape
            h = CausalConv3d(C * 2, (3, 1, 1), dtype=self.dtype,
                             name="time")(x)
            h = jnp.moveaxis(h.reshape(B, T, H, W, 2, C), 4, 2)
            x = h.reshape(B, 2 * T, H, W, C)[:, 1:]
        B, T, H, W, C = x.shape
        h = x.reshape(B * T, H, W, C)
        h = jax.image.resize(h, (B * T, H * 2, W * 2, C), "nearest")
        h = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype,
                    name="space")(h)
        return h.reshape(B, T, H * 2, W * 2, self.features)


class WanVAEEncoder(nn.Module):
    config: WanVAEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.jnp_dtype
        dims = [cfg.base_dim * m for m in cfg.dim_mult]
        h = CausalConv3d(dims[0], dtype=dt, name="conv_in")(x.astype(dt))
        for level, dim in enumerate(dims):
            for i in range(cfg.num_res_blocks):
                h = ResBlock3d(dim, dt, name=f"down_{level}_res_{i}")(h)
            if level < len(dims) - 1:
                h = _Downsample(dims[level + 1],
                                cfg.temporal_downsample[level], dt,
                                name=f"down_{level}_ds")(h)
        h = ResBlock3d(dims[-1], dt, name="mid_res1")(h)
        h = SpatialAttention(dt, name="mid_attn")(h)
        h = ResBlock3d(dims[-1], dt, name="mid_res2")(h)
        h = ChannelRMSNorm(name="norm_out")(h)
        h = CausalConv3d(cfg.latent_channels * 2, dtype=dt,
                         name="conv_out")(nn.silu(h))
        return nn.Dense(cfg.latent_channels * 2, dtype=jnp.float32,
                        name="quant")(h.astype(jnp.float32))


class WanVAEDecoder(nn.Module):
    """``stage`` (static) splits the decoder for tiled decode:

    - ``"head"``: post-quant → conv_in → mid blocks (incl. the GLOBAL
      SpatialAttention) at latent resolution — cheap, always whole-frame,
      so tiling never changes the attention statistics;
    - ``"tail"``: the upsampling stack + output conv — the memory-heavy
      part (activations grow ×downscale² per level), safe to run on
      spatial tiles because every op is a local conv;
    - ``"all"``: both (the normal whole-frame decode; init uses this so
      the param tree is identical regardless of how apply is staged).
    """

    config: WanVAEConfig

    @nn.compact
    def __call__(self, z: jax.Array, stage: str = "all") -> jax.Array:
        cfg = self.config
        dt = cfg.jnp_dtype
        dims = [cfg.base_dim * m for m in cfg.dim_mult]
        h = z
        if stage in ("all", "head"):
            zq = nn.Dense(cfg.latent_channels, dtype=jnp.float32,
                          name="post_quant")(z.astype(jnp.float32))
            h = CausalConv3d(dims[-1], dtype=dt, name="conv_in")(
                zq.astype(dt))
            h = ResBlock3d(dims[-1], dt, name="mid_res1")(h)
            h = SpatialAttention(dt, name="mid_attn")(h)
            h = ResBlock3d(dims[-1], dt, name="mid_res2")(h)
            if stage == "head":
                return h
        h = h.astype(dt)
        for level in reversed(range(len(dims))):
            for i in range(cfg.num_res_blocks + 1):
                h = ResBlock3d(dims[level], dt,
                               name=f"up_{level}_res_{i}")(h)
            if level > 0:
                h = _Upsample(dims[level - 1],
                              cfg.temporal_downsample[level - 1], dt,
                              name=f"up_{level}_us")(h)
        h = ChannelRMSNorm(name="norm_out")(h)
        h = CausalConv3d(cfg.in_channels, dtype=dt,
                         name="conv_out")(nn.silu(h))
        return h.astype(jnp.float32)


class WanVAE3D:
    """Host wrapper matching ``AutoencoderKL``'s interface over video
    tensors [B,T,H,W,C] — ``VideoPipeline`` drives either transparently."""

    def __init__(self, config: WanVAEConfig, enc_params=None,
                 dec_params=None):
        self.config = config
        self.encoder = WanVAEEncoder(config)
        self.decoder = WanVAEDecoder(config)
        self.enc_params = enc_params
        self.dec_params = dec_params
        # jit once (params are traced args, so weight swaps don't stale it);
        # inside an outer jit these inline, standalone calls compile once
        self._enc_fn = jax.jit(self.encoder.apply)
        self._dec_fn = jax.jit(self.decoder.apply,
                               static_argnames=("stage",))

    def init(self, rng: jax.Array, frames: int = 5,
             image_hw: tuple[int, int] = (32, 32)) -> "WanVAE3D":
        cfg = self.config
        H, W = image_hw
        k1, k2 = jax.random.split(rng)
        vid = jnp.zeros((1, frames, H, W, cfg.in_channels))
        lat = jnp.zeros((1, cfg.latent_frames(frames), H // cfg.downscale,
                         W // cfg.downscale, cfg.latent_channels))
        self.enc_params = jax.jit(self.encoder.init)(k1, vid)
        self.dec_params = jax.jit(self.decoder.init)(k2, lat)
        return self

    def encode(self, video: jax.Array, params=None) -> jax.Array:
        """[B,T,H,W,C] → latents; a rank-4 [B,H,W,C] image is treated as
        a 1-frame video (the causal design's single-image case) and the
        frame axis squeezed back out. ``params`` overrides the bundled
        encoder params (pipelines pass weights as jit arguments)."""
        single = video.ndim == 4
        if single:
            video = video[:, None]
        moments = self._enc_fn(
            self.enc_params if params is None else params, video)
        mean, _ = jnp.split(moments, 2, axis=-1)
        lat = mean * self.config.scaling_factor
        return lat[:, 0] if single else lat

    def decode(self, latents: jax.Array, params=None) -> jax.Array:
        single = latents.ndim == 4
        if single:
            latents = latents[:, None]
        out = self._dec_fn(self.dec_params if params is None else params,
                           latents / self.config.scaling_factor)
        return out[:, 0] if single else out

    def decode_tiled(self, latents: jax.Array, params=None,
                     tile: int = 32, overlap: int = 8) -> jax.Array:
        """Spatially-tiled decode: bound decoder activation memory for
        large clips (the ComfyUI analogue is ``VAEDecodeTiled``; the
        reference free-rides on it for big decodes — a 480p whole-frame
        f32 decode needs >31 GB of activations on one chip).

        Two stages (``WanVAEDecoder.stage``): the mid blocks — including
        the decoder's GLOBAL spatial attention — run whole-frame at cheap
        latent resolution, so tiling never changes attention statistics;
        only the memory-heavy local-conv upsampling stack runs per tile.
        Tiles overlap and blend with a linear feather; residual error is
        confined to conv-halo bands at tile seams (same approximation
        contract as ComfyUI's VAEDecodeTiled). The temporal axis stays
        whole, so causal state is exact. Tile positions are static, so
        this traces cleanly inside an outer jit, where XLA schedules the
        tile decodes sequentially — exactly the memory bound we want.
        """
        B, f, h, w, c = latents.shape
        if h <= tile and w <= tile:
            return self.decode(latents, params=params)
        if overlap >= tile:
            # env-configurable (CDT_VAE_TILE*) — fail fast with a clear
            # message instead of a trace-time shape error / step-1 blowup
            raise ValueError(
                f"vae tile overlap ({overlap}) must be smaller than the "
                f"tile ({tile})")
        p = self.dec_params if params is None else params
        head = self._dec_fn(p, latents / self.config.scaling_factor,
                            stage="head")          # [B,f,h,w,dims[-1]]
        s = self.config.downscale
        step = max(1, tile - overlap)
        # per-axis tile size: an axis smaller than `tile` is untiled, so
        # every extracted tile has identical shape — the lax.map below
        # requires it
        th, tw = min(tile, h), min(tile, w)

        ys = _tile_starts(h, th, step)
        xs = _tile_starts(w, tw, step)
        ylo, yhi = _pair_feathers(ys, th)
        xlo, xhi = _pair_feathers(xs, tw)
        ramp = functools.partial(_axis_ramp, scale=s)
        positions = [(y0, x0) for y0 in ys for x0 in xs]
        pos_feather = [(ylo[iy], yhi[iy], xlo[ix], xhi[ix])
                       for iy in range(len(ys)) for ix in range(len(xs))]
        tiles_in = jnp.stack(
            [head[:, :, y0:y0 + th, x0:x0 + tw, :] for y0, x0 in positions])

        # lax.map = hard sequentialization: unrolled tile decodes leave
        # XLA free to interleave them, and their remat/norm temporaries
        # then coexist (observed: 12 unrolled 480p tiles → 33 GB HBM).
        # Mapped, one tile's activations live at a time.
        tiles_out = jax.lax.map(
            lambda ht: self._dec_fn(p, ht, stage="tail").astype(
                jnp.float32),
            tiles_in)                      # [N,B,F,th·s,tw·s,3]

        F_out = (f - 1) * self.config.temporal_downscale + 1
        acc = jnp.zeros((B, F_out, h * s, w * s, self.config.in_channels),
                        jnp.float32)
        wsum = jnp.zeros((h * s, w * s, 1), jnp.float32)
        for i, (y0, x0) in enumerate(positions):
            f_ylo, f_yhi, f_xlo, f_xhi = pos_feather[i]
            wy = ramp(th, f_ylo, f_yhi)
            wx = ramp(tw, f_xlo, f_xhi)
            wgt = jnp.asarray(wy[:, None, None] * wx[None, :, None])
            acc = acc.at[:, :, y0 * s:(y0 + th) * s,
                         x0 * s:(x0 + tw) * s, :].add(tiles_out[i] * wgt)
            wsum = wsum.at[y0 * s:(y0 + th) * s,
                           x0 * s:(x0 + tw) * s, :].add(wgt)
        return acc / wsum
