"""AutoencoderKL (latent ↔ pixel codec) in flax.

Supplies the VAEEncode/VAEDecode capability the reference obtains from
ComfyUI (invoked per tile at ``upscale/tile_ops.py:157-287``). Standard
KL-autoencoder topology (SD family): conv stem, residual stages with
downsample, mid attention block, mirrored decoder.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .layers import GroupNorm32


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 128
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    scaling_factor: float = 0.13025      # SDXL VAE; SD1.5 uses 0.18215
    shift_factor: float = 0.0            # FLUX ae: 0.1159
    dtype: str = "bfloat16"

    @classmethod
    def sdxl(cls) -> "VAEConfig":
        return cls()

    @classmethod
    def tiny(cls, dtype: str = "bfloat16") -> "VAEConfig":
        """2× downscale toy VAE for tests (8× in real configs)."""
        return cls(base_channels=16, channel_mult=(1, 2), num_res_blocks=1,
                   scaling_factor=1.0, dtype=dtype)

    @property
    def jnp_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.channel_mult) - 1)


# LDM's AutoencoderKL normalizes with eps=1e-6 (vs the UNet's 1e-5) —
# weight parity requires matching it
_VAE_EPS = 1e-6


class _VAEResBlock(nn.Module):
    out_channels: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = GroupNorm32(epsilon=_VAE_EPS)(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype, name="conv1")(h)
        h = GroupNorm32(epsilon=_VAE_EPS)(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class _VAEAttention(nn.Module):
    """LDM AttnBlock: single-head attention with biased q/k/v/proj (the
    checkpoint stores them as 1×1 convs; Dense is the same linear map)."""

    dtype: jnp.dtype

    @nn.compact
    def __call__(self, h: jax.Array) -> jax.Array:
        B, N, C = h.shape
        q = nn.Dense(C, dtype=self.dtype, name="to_q")(h)
        k = nn.Dense(C, dtype=self.dtype, name="to_k")(h)
        v = nn.Dense(C, dtype=self.dtype, name="to_v")(h)
        s = jnp.einsum("bqc,bkc->bqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / (C ** 0.5)
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        out = jnp.einsum("bqk,bkc->bqc", p, v)
        return nn.Dense(C, dtype=self.dtype, name="to_out")(out)


class _MidBlock(nn.Module):
    channels: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = _VAEResBlock(self.channels, self.dtype, name="res1")(x)
        B, H, W, C = x.shape
        h = GroupNorm32(epsilon=_VAE_EPS)(x).reshape(B, H * W, C)
        h = _VAEAttention(self.dtype, name="attn")(h)
        x = x + h.reshape(B, H, W, C)
        return _VAEResBlock(self.channels, self.dtype, name="res2")(x)


class Encoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.jnp_dtype
        h = nn.Conv(cfg.base_channels, (3, 3), padding=1, dtype=dt, name="conv_in")(
            x.astype(dt)
        )
        for level, mult in enumerate(cfg.channel_mult):
            ch = cfg.base_channels * mult
            for i in range(cfg.num_res_blocks):
                h = _VAEResBlock(ch, dt, name=f"down_{level}_res_{i}")(h)
            if level < len(cfg.channel_mult) - 1:
                # LDM downsamples with asymmetric (0,1) padding — weight
                # parity requires the exact same spatial alignment
                h = nn.Conv(ch, (3, 3), strides=2, padding=((0, 1), (0, 1)),
                            dtype=dt, name=f"down_{level}_ds")(h)
        h = _MidBlock(h.shape[-1], dt, name="mid")(h)
        h = GroupNorm32(epsilon=_VAE_EPS, name="norm_out")(h)
        h = nn.silu(h)
        # 2×latent: mean and logvar
        h = nn.Conv(cfg.latent_channels * 2, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(h.astype(jnp.float32))
        return nn.Conv(cfg.latent_channels * 2, (1, 1), dtype=jnp.float32,
                       name="quant_conv")(h)


class Decoder(nn.Module):
    config: VAEConfig

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.jnp_dtype
        z = nn.Conv(cfg.latent_channels, (1, 1), dtype=jnp.float32,
                    name="post_quant_conv")(z.astype(jnp.float32))
        ch = cfg.base_channels * cfg.channel_mult[-1]
        h = nn.Conv(ch, (3, 3), padding=1, dtype=dt, name="conv_in")(z.astype(dt))
        h = _MidBlock(ch, dt, name="mid")(h)
        for level in reversed(range(len(cfg.channel_mult))):
            ch = cfg.base_channels * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                h = _VAEResBlock(ch, dt, name=f"up_{level}_res_{i}")(h)
            if level > 0:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), method="nearest")
                h = nn.Conv(C, (3, 3), padding=1, dtype=dt, name=f"up_{level}_us")(h)
        h = GroupNorm32(epsilon=_VAE_EPS, name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(cfg.in_channels, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(h.astype(jnp.float32))


class AutoencoderKL:
    """Bundled encoder/decoder with scaling-factor handling.

    ``encode`` returns scaled latents (mode of the posterior — diffusion
    inference never needs the sample noise); ``decode`` maps scaled latents
    back to [-1, 1] pixels.
    """

    def __init__(self, config: VAEConfig, enc_params=None, dec_params=None):
        self.config = config
        self.encoder = Encoder(config)
        self.decoder = Decoder(config)
        self.enc_params = enc_params
        self.dec_params = dec_params

    def init(self, rng: jax.Array, image_hw: tuple[int, int] = (64, 64)) -> "AutoencoderKL":
        H, W = image_hw
        cfg = self.config
        k1, k2 = jax.random.split(rng)
        img = jnp.zeros((1, H, W, cfg.in_channels))
        lat = jnp.zeros((1, H // cfg.downscale, W // cfg.downscale, cfg.latent_channels))
        # jitted: one compiled init program instead of per-op eager dispatch
        self.enc_params = jax.jit(self.encoder.init)(k1, img)
        self.dec_params = jax.jit(self.decoder.init)(k2, lat)
        return self

    def encode(self, images: jax.Array, params=None) -> jax.Array:
        """``params`` overrides the bundled encoder params — pipelines pass
        weights as jit ARGUMENTS (closure capture would embed multi-GB
        constants into the lowered MLIR; see pipeline ``_weights``).
        The apply is jitted with params as an argument (``jit_apply``):
        eager (node-level) calls get one program instead of per-op
        dispatch, and inside an outer jit the call inlines."""
        from .layers import jit_apply

        moments = jit_apply(self, self.encoder, "_enc_fn")(
            self.enc_params if params is None else params, images)
        mean, _logvar = jnp.split(moments, 2, axis=-1)
        return (mean - self.config.shift_factor) * self.config.scaling_factor

    def decode(self, latents: jax.Array, params=None) -> jax.Array:
        from .layers import jit_apply

        return jit_apply(self, self.decoder, "_dec_fn")(
            self.dec_params if params is None else params,
            latents / self.config.scaling_factor + self.config.shift_factor)
