"""Named model registry: checkpoint name → assembled pipeline stack.

The reference resolves model names through ComfyUI's ``folder_paths`` and
ships them to workers by name (``nodes/utilities.py:164-224``,
``DistributedModelName``). Here a name maps to (architecture preset,
optional orbax checkpoint dir). Without a checkpoint the stack is
random-initialized — enough for benchmarks, tests, and architecture work;
drop real weights into the checkpoint dir to get real outputs.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Callable, Optional

import jax

from ..utils.exceptions import ValidationError
from ..utils.logging import log
from .text import TextEncoder, TextEncoderConfig
from .unet import UNetConfig, init_unet
from .vae import AutoencoderKL, VAEConfig


@dataclasses.dataclass(frozen=True)
class ModelPreset:
    name: str
    unet: "UNetConfig | None"
    vae: VAEConfig
    text: TextEncoderConfig
    sample_hw: tuple[int, int] = (128, 128)   # init-time latent H,W
    dit: "object | None" = None               # DiTConfig for flow models
    video: "object | None" = None             # VideoDiTConfig for t2v models
    clip: "str | None" = None   # real text stack: "sdxl" | "clip-l" | "flux" (T5+CLIP-L)
    # WAN-2.2 dual-expert (MoE) models: sigma boundary between the
    # high-noise and low-noise expert DiTs (t2v 0.875, i2v 0.9); None =
    # single-expert
    moe_boundary: "float | None" = None

    @property
    def kind(self) -> str:
        if self.video is not None:
            return "video"
        return "dit" if self.dit is not None else "unet"


def _flux_preset():
    from .dit import DiTConfig

    return ModelPreset(
        "flux", unet=None,
        vae=VAEConfig(latent_channels=16, scaling_factor=0.3611,
                      shift_factor=0.1159),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(32, 32), dit=DiTConfig.flux(), clip="flux")


def _flux_tiny_preset():
    from .dit import DiTConfig

    return ModelPreset(
        "flux-tiny", unet=None, vae=VAEConfig.tiny(),
        text=TextEncoderConfig.tiny(),
        sample_hw=(8, 8), dit=DiTConfig.tiny())


def _sd3_medium_preset():
    from .dit import DiTConfig

    # SD3's 16-ch KL-VAE (downscale 8); conditioning = CLIP-L/G + T5-XXL
    # via the sd3 tri-encoder stack (build_clip_stack kind="sd3")
    return ModelPreset(
        "sd3-medium", unet=None,
        vae=VAEConfig(latent_channels=16, scaling_factor=1.5305,
                      shift_factor=0.0609),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=2048),
        sample_hw=(128, 128), dit=DiTConfig.sd3_medium(), clip="sd3")


def _sd35_large_preset():
    import dataclasses as _dc

    from .dit import DiTConfig

    base = _sd3_medium_preset()
    return _dc.replace(base, name="sd35-large", dit=DiTConfig.sd35_large())


def _sd3_tiny_preset():
    from .dit import DiTConfig

    return ModelPreset(
        "sd3-tiny", unet=None, vae=VAEConfig.tiny(),
        text=TextEncoderConfig.tiny(), sample_hw=(8, 8),
        dit=DiTConfig.sd3_tiny(), clip="sd3")


def _wan_preset():
    from .wan import WanConfig
    from .wan_vae import WanVAEConfig

    # WAN t2v (exact published architecture): 16-ch video latents from
    # the 3D causal VAE (4× temporal compression), UMT5-width context
    return ModelPreset(
        "wan", unet=None, vae=WanVAEConfig.wan(),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(60, 104),             # 480×832 / 8
        video=WanConfig.wan_14b(), clip="umt5")


def _wan_tiny_preset():
    from .wan import WanConfig

    return ModelPreset(
        "wan-tiny", unet=None, vae=VAEConfig.tiny(),
        text=TextEncoderConfig.tiny(),
        sample_hw=(8, 8), video=WanConfig.tiny())


def _wan_i2v_preset():
    from .wan import WanConfig
    from .wan_vae import WanVAEConfig

    # WAN 2.2-style i2v: first frame conditions via latent concat —
    # in_channels 36 = 16 noise + 4 mask (one per compressed pixel
    # frame) + 16 conditioning latents; no CLIP-vision branch
    return ModelPreset(
        "wan-i2v", unet=None, vae=WanVAEConfig.wan(),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(60, 104),
        video=dataclasses.replace(WanConfig.wan_14b(), in_channels=36),
        clip="umt5")


def _wan_i2v_tiny_preset():
    from .wan import WanConfig
    from .wan_vae import WanVAEConfig

    # tiny arithmetic: 4 noise + 2 mask (2× temporal VAE) + 4 cond = 10
    return ModelPreset(
        "wan-i2v-tiny", unet=None, vae=WanVAEConfig.tiny(),
        text=TextEncoderConfig.tiny(), sample_hw=(8, 8),
        video=WanConfig.tiny(in_channels=10))


def _wan_tiny_3d_preset():
    from .wan import WanConfig
    from .wan_vae import WanVAEConfig

    # tiny real-geometry stack: 3D causal VAE (2× temporal here) + WAN
    # transformer — the full video architecture at test scale
    return ModelPreset(
        "wan-tiny-3d", unet=None, vae=WanVAEConfig.tiny(),
        text=TextEncoderConfig.tiny(),
        sample_hw=(8, 8), video=WanConfig.tiny())


def _wan22_t2v_preset():
    from .wan import WanConfig
    from .wan_vae import WanVAEConfig

    # WAN-2.2 14B t2v IS a two-expert model: high-noise + low-noise DiTs
    # switched at timestep boundary 0.875·1000 (the published release
    # ships two transformer safetensors). Same architecture per expert as
    # wan-14b; the pipeline runs the sigma ladder in two segments.
    return ModelPreset(
        "wan-2.2-t2v", unet=None, vae=WanVAEConfig.wan(),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(60, 104),
        video=WanConfig.wan_14b(), clip="umt5", moe_boundary=0.875)


def _wan22_tiny_preset():
    from .wan import WanConfig

    return ModelPreset(
        "wan-2.2-tiny", unet=None, vae=VAEConfig.tiny(),
        text=TextEncoderConfig.tiny(),
        sample_hw=(8, 8), video=WanConfig.tiny(), moe_boundary=0.875)


def _wan_mmdit_preset():
    from .video_dit import VideoDiTConfig

    # the generic MMDiT-over-frames stack (pre-WAN-parity architecture,
    # kept for from-scratch work and as the video-sp reference design)
    return ModelPreset(
        "video-mmdit", unet=None,
        vae=VAEConfig(latent_channels=16, scaling_factor=0.3611),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(60, 104), video=VideoDiTConfig.wan())


PRESETS: dict[str, ModelPreset] = {
    "sdxl": ModelPreset("sdxl", UNetConfig.sdxl(), VAEConfig.sdxl(),
                        TextEncoderConfig(), clip="sdxl"),
    "sd15": ModelPreset("sd15", UNetConfig.sd15(),
                        VAEConfig(scaling_factor=0.18215),
                        TextEncoderConfig(output_dim=768, pooled_dim=768),
                        clip="clip-l"),
    "tiny": ModelPreset("tiny", UNetConfig.tiny(), VAEConfig.tiny(),
                        TextEncoderConfig.tiny(), sample_hw=(8, 8)),
    "flux": _flux_preset(),
    "flux-tiny": _flux_tiny_preset(),
    "sd3-medium": _sd3_medium_preset(),
    "sd35-large": _sd35_large_preset(),
    "sd3-tiny": _sd3_tiny_preset(),
    "wan": _wan_preset(),
    "wan-tiny": _wan_tiny_preset(),
    "wan-tiny-3d": _wan_tiny_3d_preset(),
    "wan-i2v": _wan_i2v_preset(),
    "wan-i2v-tiny": _wan_i2v_tiny_preset(),
    "wan-2.2-t2v": _wan22_t2v_preset(),
    "wan-2.2-tiny": _wan22_tiny_preset(),
    "video-mmdit": _wan_mmdit_preset(),
}


def _weights_tag(ckpt: "Path | None", seed: int = 0) -> str:
    """Weights-provenance tag the cache keys carry: random-init weights
    are pinned to (seed, jax version) — deterministic per jax build
    only; checkpoint-backed ones to the checkpoint path + mtime, so
    swapping weights in place invalidates the shared tiers naturally."""
    if ckpt is None:
        import jax

        return f"seed{seed}:jax{jax.__version__}"
    try:
        return f"ckpt:{Path(ckpt).name}:{int(Path(ckpt).stat().st_mtime)}"
    except OSError:
        return f"ckpt:{ckpt}"


def _encoder_identity(preset_name: str, stack: str, ckpt: "Path | None",
                      seed: int = 0) -> str:
    """Identity string the conditioning cache keys on
    (``cluster/cache/conditioning.py``)."""
    return f"{preset_name}/{stack}/{_weights_tag(ckpt, seed)}"


class ModelBundle:
    """Loaded stack: pipeline + text encoder, built lazily and cached."""

    def __init__(self, preset: ModelPreset, checkpoint_dir: Optional[Path] = None,
                 seed: int = 0, abstract_core: bool = False):
        """``abstract_core=True`` builds the core model's params as a
        ShapeDtypeStruct template instead of random weights — for
        conversion flows where every leaf is about to be overwritten
        (a FLUX-size random init alone is ~48 GB of wasted fp32)."""
        self.preset = preset
        self.clip_stack = None      # built lazily (real-weight path only)
        self._weights_source = None   # set by the checkpoint loaders
        self._init_seed = int(seed)
        k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
        img_hw = (preset.sample_hw[0] * preset.vae.downscale,
                  preset.sample_hw[1] * preset.vae.downscale)
        from .wan_vae import WanVAE3D, WanVAEConfig

        if isinstance(preset.vae, WanVAEConfig):
            vae = WanVAE3D(preset.vae).init(k2, frames=5, image_hw=img_hw)
        else:
            vae = AutoencoderKL(preset.vae).init(k2, image_hw=img_hw)
        self.text_encoder = TextEncoder(preset.text).init(k3)
        if preset.kind == "video":
            from ..diffusion.pipeline_video import VideoPipeline
            from .wan import WanConfig, init_wan

            if isinstance(preset.video, WanConfig):
                model, params = init_wan(
                    preset.video, k1,
                    sample_fhw=(5, *preset.sample_hw),
                    context_len=preset.text.max_len, abstract=abstract_core)
            else:
                from .video_dit import init_video_dit

                model, params = init_video_dit(
                    preset.video, k1,
                    sample_fhw=(5, *preset.sample_hw),
                    context_len=preset.text.max_len, abstract=abstract_core)
            params_low = None
            if preset.moe_boundary is not None:
                if not isinstance(preset.video, WanConfig):
                    raise ValidationError(
                        f"preset {preset.name!r}: moe_boundary is only "
                        "supported for WAN-architecture video models")
                # the low-noise expert is a SECOND full DiT of the same
                # architecture (WAN-2.2's high/low pair)
                _, params_low = init_wan(
                    preset.video, jax.random.fold_in(k1, 1),
                    sample_fhw=(5, *preset.sample_hw),
                    context_len=preset.text.max_len,
                    abstract=abstract_core)
            self.pipeline = VideoPipeline(
                model, params, vae, dit_params_low=params_low,
                expert_boundary=preset.moe_boundary)
        elif preset.kind == "dit":
            from ..diffusion.pipeline_flow import FlowPipeline
            from .dit import init_dit

            model, params = init_dit(preset.dit, k1,
                                     sample_hw=preset.sample_hw,
                                     context_len=preset.text.max_len,
                                     abstract=abstract_core)
            self.pipeline = FlowPipeline(model, params, vae)
        else:
            from ..diffusion.pipeline import Txt2ImgPipeline

            model, params = init_unet(
                preset.unet, k1,
                sample_shape=(*preset.sample_hw, preset.unet.in_channels),
                context_len=preset.text.max_len, abstract=abstract_core,
            )
            self.pipeline = Txt2ImgPipeline(model, params, vae)
        if checkpoint_dir is not None:
            p = Path(checkpoint_dir)
            hi = p.parent / f"{p.name}.high.safetensors"
            lo = p.parent / f"{p.name}.low.safetensors"
            # NOT with_suffix: dotted preset names ("wan-2.2-t2v") would
            # have ".2-t2v" treated as the suffix and silently miss
            single = p.parent / f"{p.name}.safetensors"
            if p.is_dir():
                self._load_checkpoint(p)
            elif preset.moe_boundary is not None and hi.is_file() \
                    and lo.is_file():
                # WAN-2.2 releases ship TWO transformer files; drop them
                # as `<name>.high.safetensors` + `<name>.low.safetensors`
                self.load_safetensors_moe(hi, lo)
            elif preset.moe_boundary is not None and (hi.is_file()
                                                      or lo.is_file()):
                # one expert present, one missing/misnamed: serving random
                # weights for the other expert would generate noise with
                # no diagnostic
                missing = lo if hi.is_file() else hi
                raise ValidationError(
                    f"dual-expert checkpoint incomplete: {missing} not "
                    "found (need both .high.safetensors and "
                    ".low.safetensors)")
            elif single.is_file():
                # drop `<name>.safetensors` next to the orbax dirs and the
                # published checkpoint converts on first load
                self.load_safetensors_checkpoint(single)
        self._stamp_text_encoder()

    def _stamp_text_encoder(self) -> None:
        """Give the active text encoder its conditioning-cache identity
        (``cluster/cache/conditioning.py``). Re-stamped whenever the
        encoder object OR the weights behind it change (clip-stack
        build, every checkpoint loader, standalone text-encoder files);
        LoRA-patched clones are deliberately NOT stamped — an
        unidentified encoder is never cached."""
        stack = self.preset.clip if self.clip_stack is not None else "text"
        self.text_encoder._cdt_encoder_id = _encoder_identity(
            self.preset.name, stack or "text", self._weights_source,
            seed=self._init_seed)

    def weights_identity(self) -> str:
        """Provenance of this bundle's CORE (denoiser) weights — the
        result-cache key carries it so an in-place checkpoint swap (same
        ``ckpt_name``, new bytes, new mtime) can never serve a stale
        persisted image (``cluster/frontdoor/microbatch.py``)."""
        return f"{self.preset.name}/{_weights_tag(self._weights_source, self._init_seed)}"

    @property
    def kind(self) -> str:
        return self.preset.kind

    def _core_params(self):
        if self.kind in ("dit", "video"):
            return self.pipeline.dit_params
        return self.pipeline.unet_params

    def _set_core_params(self, params) -> None:
        if self.kind in ("dit", "video"):
            self.pipeline.dit_params = params
        else:
            self.pipeline.unet_params = params

    def build_clip_stack(self, tiny: bool = False,
                         abstract_t5: bool = False):
        """Instantiate the weight-faithful text stack for this preset and
        swap the bundle's text encoder to it (``models/clip.py`` /
        ``models/t5.py``). ``abstract_t5=True`` leaves the (XXL-size) T5
        params as a ShapeDtypeStruct template for callers about to
        restore or convert real weights over them."""
        from .clip import (CLIPConditioner, CLIPTextConfig, CLIPTextModel,
                           SDXLTextStack)

        if self.clip_stack is not None:
            return self.clip_stack
        kind = self.preset.clip
        if kind is None:
            raise ValidationError(
                f"preset {self.preset.name!r} has no real-CLIP stack")
        key = jax.random.key(0)
        if kind == "sdxl":
            self.clip_stack = SDXLTextStack.init_random(key, tiny=tiny)
        elif kind == "flux":
            from .t5 import FluxTextStack

            self.clip_stack = FluxTextStack.init_random(
                key, tiny=tiny, abstract_t5=abstract_t5)
            self.text_encoder = self.clip_stack    # encode()-compatible
            self._stamp_text_encoder()
            return self.clip_stack
        elif kind == "umt5":
            from .t5 import UMT5Conditioner

            self.clip_stack = UMT5Conditioner.init_random(
                key, tiny=tiny, abstract_t5=abstract_t5)
            self.text_encoder = self.clip_stack
            self._stamp_text_encoder()
            return self.clip_stack
        elif kind == "sd3":
            from .t5 import SD3TextStack

            self.clip_stack = SD3TextStack.init_random(
                key, tiny=tiny, abstract_t5=abstract_t5)
            self.text_encoder = self.clip_stack
            self._stamp_text_encoder()
            return self.clip_stack
        else:
            cfg = CLIPTextConfig.tiny() if tiny else CLIPTextConfig.clip_l()
            self.clip_stack = CLIPTextModel(cfg).init(key)
        self.text_encoder = CLIPConditioner(self.clip_stack, kind=kind)
        self._stamp_text_encoder()
        return self.clip_stack

    def _state_entries(self) -> dict:
        state = {
            "core": self._core_params(),
            "vae_enc": self.pipeline.vae.enc_params,
            "vae_dec": self.pipeline.vae.dec_params,
        }
        if getattr(self.pipeline, "dit_params_low", None) is not None:
            state["core_low"] = self.pipeline.dit_params_low
        if self.clip_stack is not None:
            if self.preset.clip == "sdxl":
                state["clip_l"] = self.clip_stack.clip_l.params
                state["clip_g"] = self.clip_stack.clip_g.params
            elif self.preset.clip == "flux":
                state["clip_l"] = self.clip_stack.clip_l.params
                state["t5"] = self.clip_stack.t5.params
            elif self.preset.clip == "sd3":
                state["clip_l"] = self.clip_stack.clip_l.params
                state["clip_g"] = self.clip_stack.clip_g.params
                state["t5"] = self.clip_stack.t5.params
            elif self.preset.clip == "umt5":
                state["t5"] = self.clip_stack.t5.params
            else:
                state["clip_l"] = self.clip_stack.params
        else:
            state["text"] = self.text_encoder.params
        return state

    def _apply_entries(self, restored: dict) -> None:
        self._set_core_params(restored["core"])
        if "core_low" in restored:
            self.pipeline.dit_params_low = restored["core_low"]
        self.pipeline.vae.enc_params = restored["vae_enc"]
        self.pipeline.vae.dec_params = restored["vae_dec"]
        if "clip_l" in restored:
            if self.preset.clip == "sdxl":
                self.clip_stack.clip_l.params = restored["clip_l"]
                self.clip_stack.clip_g.params = restored["clip_g"]
            elif self.preset.clip == "flux":
                self.clip_stack.clip_l.params = restored["clip_l"]
                self.clip_stack.t5.params = restored["t5"]
            elif self.preset.clip == "sd3":
                self.clip_stack.clip_l.params = restored["clip_l"]
                self.clip_stack.clip_g.params = restored["clip_g"]
                self.clip_stack.t5.params = restored["t5"]
            else:
                self.clip_stack.params = restored["clip_l"]
        elif "t5" in restored:                     # umt5-only stack
            self.clip_stack.t5.params = restored["t5"]
        if "text" in restored:
            self.text_encoder.params = restored["text"]

    def _load_checkpoint(self, ckpt: Path) -> None:
        import json

        import orbax.checkpoint as ocp

        ckpt = Path(ckpt)
        state_dir = ckpt / "state"
        if not state_dir.exists():
            raise ValidationError(
                f"{ckpt} is not a converted checkpoint (no state/ dir); "
                "re-run `python -m comfyui_distributed_tpu convert`")
        manifest = {}
        mf = ckpt / "cdt_manifest.json"
        self._weights_source = ckpt
        if mf.is_file():
            manifest = json.loads(mf.read_text())
        saved_arch = manifest.get("arch")
        if saved_arch and saved_arch != self._arch_fingerprint():
            raise ValidationError(
                f"checkpoint {ckpt} was saved with architecture "
                f"{saved_arch} but the current preset resolves to "
                f"{self._arch_fingerprint()}; a mismatched positional "
                "encoding restores byte-compatibly yet generates garbage — "
                "re-convert the checkpoint for this preset")
        if {"clip_l", "t5"} & set(manifest.get("entries", [])):
            # abstract T5 targets: orbax restores over ShapeDtypeStructs,
            # so a T5-XXL restore never pays a ~19 GB random init first
            self.build_clip_stack(tiny=bool(manifest.get("tiny_clip")),
                                  abstract_t5="t5" in manifest["entries"])
        targets = self._state_entries()
        if manifest.get("entries"):
            targets = {k: v for k, v in targets.items()
                       if k in manifest["entries"]}
        with ocp.StandardCheckpointer() as ckptr:
            restored = ckptr.restore(state_dir.resolve(), targets)
        self._apply_entries(restored)
        # the encoder's weights just changed provenance: a stale
        # random-init identity here would let this bundle share cache
        # entries with a genuinely random-init twin
        self._stamp_text_encoder()
        log(f"loaded checkpoint {ckpt}")

    def save_checkpoint(self, ckpt: Path) -> None:
        """Persist the stack with orbax (enables real-weight workflows:
        convert → save once → every controller restores). A small manifest
        records which entries exist so restore can rebuild the right
        text-encoder stack."""
        import json

        import orbax.checkpoint as ocp

        ckpt = Path(ckpt)
        state = self._state_entries()
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save((ckpt / "state").resolve(), state)
        tiny_clip = False
        if self.clip_stack is not None:
            if self.preset.clip == "umt5":
                tiny_clip = self.clip_stack.t5.config.d_model < 256
            else:
                cl = (self.clip_stack.clip_l
                      if self.preset.clip in ("sdxl", "flux", "sd3")
                      else self.clip_stack)
                tiny_clip = cl.config.width < 256
        ckpt.mkdir(parents=True, exist_ok=True)
        (ckpt / "cdt_manifest.json").write_text(json.dumps(
            {"preset": self.preset.name, "entries": sorted(state),
             "tiny_clip": tiny_clip,
             "arch": self._arch_fingerprint()}))
        log(f"saved checkpoint {ckpt}")

    def _arch_fingerprint(self) -> dict:
        """Architecture facts that change SEMANTICS without changing the
        param tree (a rope↔sincos flip restores byte-compatibly but
        generates garbage); recorded at save, validated at load."""
        core = (self.preset.dit or self.preset.video or self.preset.unet)
        fp: dict = {"kind": self.kind}
        for field in ("pos_embed", "rope_theta", "rope_axes_dim"):
            if hasattr(core, field):
                v = getattr(core, field)
                fp[field] = list(v) if isinstance(v, tuple) else v
        return fp

    def load_safetensors_checkpoint(self, path: Path) -> None:
        """Convert a published single-file ``.safetensors`` checkpoint
        (SDXL/SD1.5/FLUX layout) into this bundle in place."""
        from .convert import convert_checkpoint

        if self.preset.clip not in (None, "flux", "umt5", "sd3"):
            # FLUX/WAN/SD3 single files carry only the transformer; the
            # (large) T5 stacks are built on demand by
            # load_text_encoder_files — pre-building here would
            # materialize ~19-23 GB of random fp32 T5 weights and, worse,
            # let save_checkpoint persist them as if they were real
            self.build_clip_stack()
        self._weights_source = Path(path)
        convert_checkpoint(path, self)
        self._stamp_text_encoder()

    def load_safetensors_moe(self, high: Path, low: Path) -> None:
        """Convert a WAN-2.2 dual-expert release: the high-noise
        transformer file into the main params and the low-noise file into
        ``dit_params_low`` (both shape-checked against this preset's
        architecture; the experts are architecturally identical)."""
        from .convert import convert_checkpoint

        if self.preset.moe_boundary is None:
            raise ValidationError(
                f"preset {self.preset.name!r} is not a dual-expert model; "
                "use load_safetensors_checkpoint for single-transformer "
                "releases")
        self._weights_source = Path(high)
        convert_checkpoint(Path(high), self)
        hi_params = self.pipeline.dit_params
        # the low expert converts against the low template in the same
        # code path, then the trees swap back into place
        self.pipeline.dit_params = self.pipeline.dit_params_low
        try:
            convert_checkpoint(Path(low), self)
            self.pipeline.dit_params_low = self.pipeline.dit_params
        finally:
            self.pipeline.dit_params = hi_params
        self._stamp_text_encoder()

    def load_text_encoder_files(self, t5: Optional[Path] = None,
                                clip_l: Optional[Path] = None,
                                clip_g: Optional[Path] = None) -> None:
        """Convert the standalone text-encoder ``.safetensors`` files
        FLUX/SD3 distributions ship (``t5xxl_*.safetensors`` in HF T5
        layout, ``clip_l.safetensors``/``clip_g.safetensors`` in HF
        ``text_model.*`` layout) into this bundle's conditioning stack."""
        from .convert import convert_clip_hf, load_safetensors
        from .t5 import convert_t5

        if self.preset.clip not in ("flux", "umt5", "sd3"):
            raise ValidationError(
                "separate text-encoder files are a flux/wan/sd3-stack "
                f"feature; preset {self.preset.name!r} bundles its "
                "encoders in the single-file checkpoint")
        if self.clip_stack is None:
            from .t5 import FluxTextStack, SD3TextStack, UMT5Conditioner

            # T5-XXL random init is ~19 GB; skip it when the converter is
            # about to overwrite every leaf
            if self.preset.clip == "flux":
                self.clip_stack = FluxTextStack.init_random(
                    jax.random.key(0), abstract_t5=t5 is not None)
            elif self.preset.clip == "sd3":
                self.clip_stack = SD3TextStack.init_random(
                    jax.random.key(0), abstract_t5=t5 is not None)
            else:
                self.clip_stack = UMT5Conditioner.init_random(
                    jax.random.key(0), abstract_t5=t5 is not None)
            self.text_encoder = self.clip_stack
        if t5 is not None:
            self.clip_stack.t5.params = convert_t5(
                load_safetensors(Path(t5)), self.clip_stack.t5.params,
                self.clip_stack.t5.config)
        if clip_l is not None:
            if self.preset.clip not in ("flux", "sd3"):
                raise ValidationError(
                    "clip_l is part of the flux/sd3 stacks only")
            self.clip_stack.clip_l.params = convert_clip_hf(
                load_safetensors(Path(clip_l)),
                self.clip_stack.clip_l.params, self.clip_stack.clip_l.config)
        if clip_g is not None:
            if self.preset.clip != "sd3":
                raise ValidationError("clip_g is part of the sd3 stack only")
            self.clip_stack.clip_g.params = convert_clip_hf(
                load_safetensors(Path(clip_g)),
                self.clip_stack.clip_g.params, self.clip_stack.clip_g.config)
        if self._weights_source is None and t5 is not None:
            self._weights_source = Path(t5)
        self._stamp_text_encoder()

    def release_device(self) -> None:
        """Drop everything this bundle holds ON DEVICE so its HBM can be
        reused (residency-planner eviction, ``cluster/residency.py``):
        offload executors' stacked/resident blocks are freed explicitly
        (``diffusion/offload.release_store``), and every pipeline compile
        cache is cleared so no jitted closure keeps device arrays alive.
        Host-side params (numpy/orbax trees) survive — re-acquiring the
        bundle re-uploads, it does not re-convert."""
        from ..diffusion.offload import release_store

        for cache_name in ("_fn_cache", "_i2i_cache", "_control_clones"):
            cache = getattr(self.pipeline, cache_name, None)
            if not isinstance(cache, dict):
                continue
            for v in cache.values():
                if hasattr(v, "stacked") and hasattr(v, "resident"):
                    release_store(v)
            cache.clear()

    def load_vae_file(self, path: Path) -> None:
        """Convert a standalone VAE ``.safetensors`` into this bundle.

        Detects the three published layouts: LDM-embedded
        (``first_stage_model.*``), standalone SD VAE (bare keys with
        ``quant_conv``), and BFL ``ae.safetensors`` (bare keys, no quant
        convs — FLUX's 16-channel KL-VAE)."""
        from .convert import ConversionError, convert_vae, load_safetensors
        from .wan_vae import WanVAEConfig

        if isinstance(self.preset.vae, WanVAEConfig):
            raise ConversionError(
                "WAN 3D-causal-VAE weight portability is not yet wired "
                "(models/wan_vae.py) — the preset's VAE keeps its current "
                "weights; --vae applies to image-VAE presets only")
        sd = load_safetensors(Path(path))
        if any(k.startswith("first_stage_model.") for k in sd):
            prefix, qc = "first_stage_model.", True
        elif "quant_conv.weight" in sd:
            prefix, qc = "", True
        else:
            prefix, qc = "", False
        enc, dec = convert_vae(sd, self.pipeline.vae.enc_params,
                               self.pipeline.vae.dec_params,
                               self.preset.vae, prefix=prefix,
                               quant_convs=qc)
        self.pipeline.vae.enc_params = enc
        self.pipeline.vae.dec_params = dec


class ModelRegistry:
    def __init__(self, checkpoint_root: Optional[Path] = None,
                 hbm_budget_bytes: Optional[int] = None):
        """``hbm_budget_bytes`` (default: ``CDT_HBM_BUDGET_GB``) attaches
        the multi-model residency planner (``cluster/residency.py``):
        cached bundles then live under a per-chip HBM budget with
        LRU/priority eviction instead of accumulating until OOM."""
        self.checkpoint_root = Path(checkpoint_root) if checkpoint_root else None
        self._cache: dict[str, ModelBundle] = {}
        # registry access is lock-serialized: the stage-split encode
        # pool resolves bundles from N worker threads concurrently, and
        # an unguarded check-then-build would construct two bundles of
        # the same preset — distinct pipeline objects whose members then
        # never stack in one microbatch (cluster/stages, docs/stages.md)
        from ..lint.lockorder import tracked_lock

        self._lock = tracked_lock("model.registry", reentrant=True)
        self.residency = None
        if hbm_budget_bytes is None:
            from ..cluster.residency import hbm_budget_bytes as _budget

            hbm_budget_bytes = _budget()
        if hbm_budget_bytes and hbm_budget_bytes > 0:
            from ..cluster.residency import BundleResidency

            self.residency = BundleResidency(self, hbm_budget_bytes)

    def available(self) -> list[str]:
        return sorted(PRESETS)

    def get(self, name: str) -> ModelBundle:
        with self._lock:
            if name not in self._cache:
                preset = PRESETS.get(name)
                if preset is None:
                    raise ValidationError(f"unknown model {name!r}; have {self.available()}")
                ckpt = self.checkpoint_root / name if self.checkpoint_root else None
                self._cache[name] = ModelBundle(preset, ckpt)
            bundle = self._cache[name]
            if self.residency is not None:
                try:
                    self.residency.note_use(name, bundle)
                except Exception:
                    # an unplaceable bundle must not squat in the cache
                    # (permanently over budget, unevictable because it was
                    # never registered) — drop it and re-raise
                    self._cache.pop(name, None)
                    bundle.release_device()
                    raise
                # back-ref so holders (sampler nodes) can pin the bundle
                # for the duration of a generate call without reaching
                # the registry (cluster/residency.pinned_bundle)
                bundle._residency = self.residency
            return bundle
