"""Named model registry: checkpoint name → assembled pipeline stack.

The reference resolves model names through ComfyUI's ``folder_paths`` and
ships them to workers by name (``nodes/utilities.py:164-224``,
``DistributedModelName``). Here a name maps to (architecture preset,
optional orbax checkpoint dir). Without a checkpoint the stack is
random-initialized — enough for benchmarks, tests, and architecture work;
drop real weights into the checkpoint dir to get real outputs.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Callable, Optional

import jax

from ..utils.exceptions import ValidationError
from ..utils.logging import log
from .text import TextEncoder, TextEncoderConfig
from .unet import UNetConfig, init_unet
from .vae import AutoencoderKL, VAEConfig


@dataclasses.dataclass(frozen=True)
class ModelPreset:
    name: str
    unet: "UNetConfig | None"
    vae: VAEConfig
    text: TextEncoderConfig
    sample_hw: tuple[int, int] = (128, 128)   # init-time latent H,W
    dit: "object | None" = None               # DiTConfig for flow models
    video: "object | None" = None             # VideoDiTConfig for t2v models
    clip: "str | None" = None                 # "sdxl" | "clip-l" real-CLIP stack

    @property
    def kind(self) -> str:
        if self.video is not None:
            return "video"
        return "dit" if self.dit is not None else "unet"


def _flux_preset():
    from .dit import DiTConfig

    return ModelPreset(
        "flux", unet=None,
        vae=VAEConfig(latent_channels=16, scaling_factor=0.3611),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(32, 32), dit=DiTConfig.flux())


def _flux_tiny_preset():
    from .dit import DiTConfig

    return ModelPreset(
        "flux-tiny", unet=None, vae=VAEConfig.tiny(),
        text=TextEncoderConfig.tiny(),
        sample_hw=(8, 8), dit=DiTConfig.tiny())


def _wan_preset():
    from .video_dit import VideoDiTConfig

    # WAN-class t2v: 16-ch video latents, T5-width context
    return ModelPreset(
        "wan", unet=None,
        vae=VAEConfig(latent_channels=16, scaling_factor=0.3611),
        text=TextEncoderConfig(output_dim=4096, pooled_dim=768),
        sample_hw=(60, 104),             # 480×832 / 8
        video=VideoDiTConfig.wan())


def _wan_tiny_preset():
    from .video_dit import VideoDiTConfig

    return ModelPreset(
        "wan-tiny", unet=None, vae=VAEConfig.tiny(),
        text=TextEncoderConfig.tiny(),
        sample_hw=(8, 8), video=VideoDiTConfig.tiny())


PRESETS: dict[str, ModelPreset] = {
    "sdxl": ModelPreset("sdxl", UNetConfig.sdxl(), VAEConfig.sdxl(),
                        TextEncoderConfig(), clip="sdxl"),
    "sd15": ModelPreset("sd15", UNetConfig.sd15(),
                        VAEConfig(scaling_factor=0.18215),
                        TextEncoderConfig(output_dim=768, pooled_dim=768),
                        clip="clip-l"),
    "tiny": ModelPreset("tiny", UNetConfig.tiny(), VAEConfig.tiny(),
                        TextEncoderConfig.tiny(), sample_hw=(8, 8)),
    "flux": _flux_preset(),
    "flux-tiny": _flux_tiny_preset(),
    "wan": _wan_preset(),
    "wan-tiny": _wan_tiny_preset(),
}


class ModelBundle:
    """Loaded stack: pipeline + text encoder, built lazily and cached."""

    def __init__(self, preset: ModelPreset, checkpoint_dir: Optional[Path] = None,
                 seed: int = 0):
        self.preset = preset
        self.clip_stack = None      # built lazily (real-weight path only)
        k1, k2, k3 = jax.random.split(jax.random.key(seed), 3)
        img_hw = (preset.sample_hw[0] * preset.vae.downscale,
                  preset.sample_hw[1] * preset.vae.downscale)
        vae = AutoencoderKL(preset.vae).init(k2, image_hw=img_hw)
        self.text_encoder = TextEncoder(preset.text).init(k3)
        if preset.kind == "video":
            from ..diffusion.pipeline_video import VideoPipeline
            from .video_dit import init_video_dit

            model, params = init_video_dit(
                preset.video, k1,
                sample_fhw=(5, *preset.sample_hw),
                context_len=preset.text.max_len)
            self.pipeline = VideoPipeline(model, params, vae)
        elif preset.kind == "dit":
            from ..diffusion.pipeline_flow import FlowPipeline
            from .dit import init_dit

            model, params = init_dit(preset.dit, k1,
                                     sample_hw=preset.sample_hw,
                                     context_len=preset.text.max_len)
            self.pipeline = FlowPipeline(model, params, vae)
        else:
            from ..diffusion.pipeline import Txt2ImgPipeline

            model, params = init_unet(
                preset.unet, k1,
                sample_shape=(*preset.sample_hw, preset.unet.in_channels),
                context_len=preset.text.max_len,
            )
            self.pipeline = Txt2ImgPipeline(model, params, vae)
        if checkpoint_dir is not None:
            p = Path(checkpoint_dir)
            if p.is_dir():
                self._load_checkpoint(p)
            elif p.with_suffix(".safetensors").is_file():
                # drop `<name>.safetensors` next to the orbax dirs and the
                # published checkpoint converts on first load
                self.load_safetensors_checkpoint(p.with_suffix(".safetensors"))

    @property
    def kind(self) -> str:
        return self.preset.kind

    def _core_params(self):
        if self.kind in ("dit", "video"):
            return self.pipeline.dit_params
        return self.pipeline.unet_params

    def _set_core_params(self, params) -> None:
        if self.kind in ("dit", "video"):
            self.pipeline.dit_params = params
        else:
            self.pipeline.unet_params = params

    def build_clip_stack(self, tiny: bool = False):
        """Instantiate the weight-faithful CLIP stack for this preset and
        swap the bundle's text encoder to it (``models/clip.py``)."""
        from .clip import (CLIPConditioner, CLIPTextConfig, CLIPTextModel,
                           SDXLTextStack)

        if self.clip_stack is not None:
            return self.clip_stack
        kind = self.preset.clip
        if kind is None:
            raise ValidationError(
                f"preset {self.preset.name!r} has no real-CLIP stack")
        key = jax.random.key(0)
        if kind == "sdxl":
            self.clip_stack = SDXLTextStack.init_random(key, tiny=tiny)
        else:
            cfg = CLIPTextConfig.tiny() if tiny else CLIPTextConfig.clip_l()
            self.clip_stack = CLIPTextModel(cfg).init(key)
        self.text_encoder = CLIPConditioner(self.clip_stack, kind=kind)
        return self.clip_stack

    def _state_entries(self) -> dict:
        state = {
            "core": self._core_params(),
            "vae_enc": self.pipeline.vae.enc_params,
            "vae_dec": self.pipeline.vae.dec_params,
        }
        if self.clip_stack is not None:
            if self.preset.clip == "sdxl":
                state["clip_l"] = self.clip_stack.clip_l.params
                state["clip_g"] = self.clip_stack.clip_g.params
            else:
                state["clip_l"] = self.clip_stack.params
        else:
            state["text"] = self.text_encoder.params
        return state

    def _apply_entries(self, restored: dict) -> None:
        self._set_core_params(restored["core"])
        self.pipeline.vae.enc_params = restored["vae_enc"]
        self.pipeline.vae.dec_params = restored["vae_dec"]
        if "clip_l" in restored:
            if self.preset.clip == "sdxl":
                self.clip_stack.clip_l.params = restored["clip_l"]
                self.clip_stack.clip_g.params = restored["clip_g"]
            else:
                self.clip_stack.params = restored["clip_l"]
        if "text" in restored:
            self.text_encoder.params = restored["text"]

    def _load_checkpoint(self, ckpt: Path) -> None:
        import json

        import orbax.checkpoint as ocp

        ckpt = Path(ckpt)
        state_dir = ckpt / "state"
        if not state_dir.exists():
            raise ValidationError(
                f"{ckpt} is not a converted checkpoint (no state/ dir); "
                "re-run `python -m comfyui_distributed_tpu convert`")
        manifest = {}
        mf = ckpt / "cdt_manifest.json"
        if mf.is_file():
            manifest = json.loads(mf.read_text())
        saved_arch = manifest.get("arch")
        if saved_arch and saved_arch != self._arch_fingerprint():
            raise ValidationError(
                f"checkpoint {ckpt} was saved with architecture "
                f"{saved_arch} but the current preset resolves to "
                f"{self._arch_fingerprint()}; a mismatched positional "
                "encoding restores byte-compatibly yet generates garbage — "
                "re-convert the checkpoint for this preset")
        if "clip_l" in manifest.get("entries", []):
            self.build_clip_stack(tiny=bool(manifest.get("tiny_clip")))
        targets = self._state_entries()
        if manifest.get("entries"):
            targets = {k: v for k, v in targets.items()
                       if k in manifest["entries"]}
        with ocp.StandardCheckpointer() as ckptr:
            restored = ckptr.restore(state_dir.resolve(), targets)
        self._apply_entries(restored)
        log(f"loaded checkpoint {ckpt}")

    def save_checkpoint(self, ckpt: Path) -> None:
        """Persist the stack with orbax (enables real-weight workflows:
        convert → save once → every controller restores). A small manifest
        records which entries exist so restore can rebuild the right
        text-encoder stack."""
        import json

        import orbax.checkpoint as ocp

        ckpt = Path(ckpt)
        state = self._state_entries()
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save((ckpt / "state").resolve(), state)
        tiny_clip = False
        if self.clip_stack is not None:
            cl = (self.clip_stack.clip_l if self.preset.clip == "sdxl"
                  else self.clip_stack)
            tiny_clip = cl.config.width < 256
        ckpt.mkdir(parents=True, exist_ok=True)
        (ckpt / "cdt_manifest.json").write_text(json.dumps(
            {"preset": self.preset.name, "entries": sorted(state),
             "tiny_clip": tiny_clip,
             "arch": self._arch_fingerprint()}))
        log(f"saved checkpoint {ckpt}")

    def _arch_fingerprint(self) -> dict:
        """Architecture facts that change SEMANTICS without changing the
        param tree (a rope↔sincos flip restores byte-compatibly but
        generates garbage); recorded at save, validated at load."""
        core = (self.preset.dit or self.preset.video or self.preset.unet)
        fp: dict = {"kind": self.kind}
        for field in ("pos_embed", "rope_theta", "rope_axes_dim"):
            if hasattr(core, field):
                v = getattr(core, field)
                fp[field] = list(v) if isinstance(v, tuple) else v
        return fp

    def load_safetensors_checkpoint(self, path: Path) -> None:
        """Convert a published single-file ``.safetensors`` checkpoint
        (SDXL/SD1.5 layout) into this bundle in place."""
        from .convert import convert_checkpoint

        if self.preset.clip is not None:
            self.build_clip_stack()
        convert_checkpoint(path, self)


class ModelRegistry:
    def __init__(self, checkpoint_root: Optional[Path] = None):
        self.checkpoint_root = Path(checkpoint_root) if checkpoint_root else None
        self._cache: dict[str, ModelBundle] = {}

    def available(self) -> list[str]:
        return sorted(PRESETS)

    def get(self, name: str) -> ModelBundle:
        if name not in self._cache:
            preset = PRESETS.get(name)
            if preset is None:
                raise ValidationError(f"unknown model {name!r}; have {self.available()}")
            ckpt = self.checkpoint_root / name if self.checkpoint_root else None
            self._cache[name] = ModelBundle(preset, ckpt)
        return self._cache[name]
