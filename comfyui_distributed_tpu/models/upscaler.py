"""RRDBNet (ESRGAN-family) learned upscaler in flax.

The reference's upscale workflows run an ESRGAN-class model before tile
diffusion (``/root/reference/workflows/distributed-upscale.json`` —
``UpscaleModelLoader`` → ``ImageUpscaleWithModel`` feeding
``UltimateSDUpscaleDistributed``'s ``upscaled_image`` input,
``nodes/distributed_upscale.py:84-91``); ComfyUI supplies the model zoo.
A standalone framework owns that capability: this is the standard RRDBNet
topology every published ESRGAN/Real-ESRGAN ``.safetensors``/``.pth``
checkpoint (4x-UltraSharp, RealESRGAN_x4plus, …) maps onto, so converted
weights drop straight in (``convert.convert_upscaler``).

TPU notes: convs compute in bf16 on the MXU (params stay f32); the whole
forward is one fused XLA program. Real-ESRGAN x2 checkpoints use a
pixel-unshuffle stem (input space-to-depth by 2, then a 4× trunk) — that
is reproduced exactly so their weights convert.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class UpscalerConfig:
    scale: int = 4                    # output scale of the checkpoint
    in_channels: int = 3
    out_channels: int = 3
    num_feat: int = 64
    num_block: int = 23
    grow_ch: int = 32
    dtype: str = "bfloat16"

    @classmethod
    def esrgan_x4(cls) -> "UpscalerConfig":
        return cls()

    @classmethod
    def realesrgan_x2(cls) -> "UpscalerConfig":
        # x2 models keep the 4× trunk behind a pixel-unshuffle stem
        return cls(scale=2)

    @classmethod
    def tiny(cls, scale: int = 2) -> "UpscalerConfig":
        return cls(scale=scale, num_feat=8, num_block=2, grow_ch=4)

    @property
    def jnp_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


def _lrelu(x):
    return nn.leaky_relu(x, negative_slope=0.2)


class _DenseBlock(nn.Module):
    """Residual dense block: 5 convs, each seeing all prior features."""

    num_feat: int
    grow_ch: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        conv = lambda ch, name: nn.Conv(ch, (3, 3), padding=1,
                                        dtype=self.dtype, name=name)
        x1 = _lrelu(conv(self.grow_ch, "conv1")(x))
        x2 = _lrelu(conv(self.grow_ch, "conv2")(jnp.concatenate([x, x1], -1)))
        x3 = _lrelu(conv(self.grow_ch, "conv3")(jnp.concatenate([x, x1, x2], -1)))
        x4 = _lrelu(conv(self.grow_ch, "conv4")(
            jnp.concatenate([x, x1, x2, x3], -1)))
        x5 = conv(self.num_feat, "conv5")(
            jnp.concatenate([x, x1, x2, x3, x4], -1))
        return x + 0.2 * x5


class _RRDB(nn.Module):
    num_feat: int
    grow_ch: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        h = _DenseBlock(self.num_feat, self.grow_ch, self.dtype, name="rdb1")(x)
        h = _DenseBlock(self.num_feat, self.grow_ch, self.dtype, name="rdb2")(h)
        h = _DenseBlock(self.num_feat, self.grow_ch, self.dtype, name="rdb3")(h)
        return x + 0.2 * h


def _nearest_x2(x):
    B, H, W, C = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (B, H, 2, W, 2, C))
    return x.reshape(B, 2 * H, 2 * W, C)


def _pixel_unshuffle(x, factor: int):
    """NHWC pixel-unshuffle with torch's output channel order
    ``c·f² + fy·f + fx`` — required for weight portability (the stem
    conv's input channels are laid out this way in checkpoints)."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // factor, factor, W // factor, factor, C)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(
        B, H // factor, W // factor, C * factor * factor)


class RRDBNet(nn.Module):
    """[B,H,W,3] in [0,1] → [B,H·s,W·s,3]."""

    config: UpscalerConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dt = cfg.jnp_dtype
        conv = lambda ch, name: nn.Conv(ch, (3, 3), padding=1,
                                        dtype=dt, name=name)
        h = x.astype(dt)
        if cfg.scale == 2:
            h = _pixel_unshuffle(h, 2)
        elif cfg.scale == 1:
            h = _pixel_unshuffle(h, 4)
        feat = conv(cfg.num_feat, "conv_first")(h)
        body = feat
        for i in range(cfg.num_block):
            body = _RRDB(cfg.num_feat, cfg.grow_ch, dt, name=f"body_{i}")(body)
        feat = feat + conv(cfg.num_feat, "conv_body")(body)
        # trunk is always 4×: two nearest-neighbour ×2 hops
        feat = _lrelu(conv(cfg.num_feat, "conv_up1")(_nearest_x2(feat)))
        feat = _lrelu(conv(cfg.num_feat, "conv_up2")(_nearest_x2(feat)))
        out = nn.Conv(cfg.out_channels, (3, 3), padding=1,
                      dtype=jnp.float32, name="conv_last")(
            _lrelu(conv(cfg.num_feat, "conv_hr")(feat)))
        return jnp.clip(out.astype(jnp.float32), 0.0, 1.0)


@dataclasses.dataclass
class UpscalerBundle:
    """Module + params + the checkpoint's scale, as flowing through the
    graph from ``UpscaleModelLoader`` to ``ImageUpscaleWithModel``."""

    model: RRDBNet
    params: dict
    name: str = "upscaler"

    @property
    def scale(self) -> int:
        return self.model.config.scale

    def apply(self, images: jax.Array) -> jax.Array:
        return self.model.apply(self.params, images)


def init_upscaler(config: UpscalerConfig, rng: jax.Array,
                  sample_hw: tuple[int, int] = (32, 32)) -> UpscalerBundle:
    model = RRDBNet(config)
    x = jnp.zeros((1, *sample_hw, config.in_channels), jnp.float32)
    params = jax.jit(model.init)(rng, x)
    return UpscalerBundle(model, params)
