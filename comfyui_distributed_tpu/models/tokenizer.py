"""CLIP byte-pair-encoding tokenizer (exact algorithm, file-loaded vocab).

The reference gets tokenization for free from ComfyUI's CLIP stack; a
standalone framework owns it. This is a faithful implementation of the
OpenAI CLIP tokenizer (the one SD/SDXL checkpoints were trained with):

- byte→unicode table, lowercased input, whitespace collapse,
- the CLIP word-splitting regex (letters / numbers / punctuation runs,
  contraction suffixes),
- greedy lowest-rank BPE merges with the ``</w>`` end-of-word marker,
- ``<|startoftext|>`` / ``<|endoftext|>`` specials, truncate-then-pad to
  ``max_len``.

Vocab files are the standard ``vocab.json`` + ``merges.txt`` pair every
SD checkpoint distribution carries (this environment is zero-egress so no
vocab is vendored here; point ``CDT_TOKENIZER_DIR`` at one). Differential
tests validate the algorithm against ``transformers.CLIPTokenizer`` on
synthetic vocabularies (``tests/test_tokenizer.py``).

Padding: CLIP-L convention pads with EOT (SD1.5/SDXL first encoder);
CLIP-G pads with 0 — pass ``pad_token_id`` accordingly.
"""

from __future__ import annotations

import functools
import json
from pathlib import Path
from typing import Optional, Sequence

try:
    import regex as _re
except ImportError:  # pragma: no cover - regex ships with transformers
    import re as _re

_PATTERN = _re.compile(
    r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""",
    _re.IGNORECASE,
)

SOT = "<|startoftext|>"
EOT = "<|endoftext|>"


@functools.lru_cache(maxsize=1)
def bytes_to_unicode() -> dict[int, str]:
    """The GPT-2/CLIP reversible byte→printable-unicode table."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return set(zip(word[:-1], word[1:]))


class CLIPBPETokenizer:
    def __init__(self, vocab: dict[str, int],
                 merges: Sequence[tuple[str, str]], max_len: int = 77,
                 pad_token_id: Optional[int] = None):
        self.vocab = dict(vocab)
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.max_len = max_len
        self.byte_encoder = bytes_to_unicode()
        self.sot_id = self.vocab[SOT]
        self.eot_id = self.vocab[EOT]
        self.pad_token_id = self.eot_id if pad_token_id is None else pad_token_id
        self._cache: dict[str, list[str]] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def from_dir(cls, path: Path, **kw) -> "CLIPBPETokenizer":
        """Load the standard HF-format ``vocab.json`` + ``merges.txt``."""
        path = Path(path)
        vocab = json.loads((path / "vocab.json").read_text(encoding="utf-8"))
        merges = []
        for line in (path / "merges.txt").read_text(encoding="utf-8").splitlines():
            if line.startswith("#version") or not line.strip():
                continue
            a, b = line.split()
            merges.append((a, b))
        return cls(vocab, merges, **kw)

    @classmethod
    def from_env(cls, subdir: str = "", **kw) -> Optional["CLIPBPETokenizer"]:
        from ..utils import constants

        root = constants.TOKENIZER_DIR.get()
        if not root:
            return None
        path = Path(root) / subdir if subdir else Path(root)
        if not (path / "vocab.json").is_file():
            return None
        return cls.from_dir(path, **kw)

    # -- BPE ----------------------------------------------------------------

    def _bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        if len(word) == 1:
            self._cache[token] = list(word)
            return list(word)
        while len(word) > 1:
            pairs = _get_pairs(word)
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            a, b = best
            merged: list[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        self._cache[token] = list(word)
        return list(word)

    def tokenize_text(self, text: str) -> list[int]:
        """Text → BPE ids (no specials, no padding)."""
        text = " ".join(text.split()).strip().lower()
        ids: list[int] = []
        for tok in _PATTERN.findall(text):
            encoded = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            for unit in self._bpe(encoded):
                ids.append(self.vocab[unit])
        return ids

    def encode(self, text: str) -> list[int]:
        """Text → fixed-length [SOT, …, EOT, pad…] id sequence."""
        ids = self.tokenize_text(text)[: self.max_len - 2]
        out = [self.sot_id] + ids + [self.eot_id]
        return out + [self.pad_token_id] * (self.max_len - len(out))


def load_sd_tokenizers(max_len: int = 77):
    """(CLIP-L tokenizer, CLIP-G tokenizer) from ``CDT_TOKENIZER_DIR``,
    or ``(None, None)`` when no vocab is available (hash fallback path).
    Both towers share one vocab; they differ only in padding id."""
    tok_l = CLIPBPETokenizer.from_env(max_len=max_len)
    if tok_l is None:
        return None, None
    tok_g = CLIPBPETokenizer.from_env(max_len=max_len, pad_token_id=0)
    return tok_l, tok_g
