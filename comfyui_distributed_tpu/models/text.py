"""Text conditioning encoders.

The reference obtains CLIP conditioning from ComfyUI's loader nodes; this
module supplies a native flax encoder with the same *interface* (sequence
context + pooled vector) so pipelines are weight-source-agnostic: load real
CLIP weights into it when available, or run random-init for benchmarks.

Tokenization is a deterministic stable-hash fallback (zero-egress
environments have no vocab files); swap in a real tokenizer by passing
``tokenize_fn``.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from .layers import Attention


def _stable_hash_token(word: str, vocab_size: int) -> int:
    h = hashlib.blake2s(word.encode("utf-8"), digest_size=4).digest()
    return int.from_bytes(h, "little") % (vocab_size - 2) + 2   # 0=pad, 1=eot


def hash_tokenize(text: str, max_len: int, vocab_size: int) -> list[int]:
    toks = [_stable_hash_token(w, vocab_size) for w in text.lower().split()]
    toks = toks[: max_len - 1] + [1]
    return toks + [0] * (max_len - len(toks))


@dataclasses.dataclass(frozen=True)
class TextEncoderConfig:
    vocab_size: int = 49408
    max_len: int = 77
    width: int = 768
    layers: int = 4
    heads: int = 12
    output_dim: int = 2048        # cross-attention context dim (SDXL: 2048)
    pooled_dim: int = 1280        # pooled vector dim (SDXL: 1280)
    dtype: str = "bfloat16"

    @classmethod
    def tiny(cls) -> "TextEncoderConfig":
        return cls(vocab_size=1024, max_len=16, width=32, layers=1, heads=2,
                   output_dim=32, pooled_dim=16)


class TextTransformer(nn.Module):
    config: TextEncoderConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> tuple[jax.Array, jax.Array]:
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.width, dtype=dt, name="tok_emb")(tokens)
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.01), (cfg.max_len, cfg.width)
        )
        x = x + pos[None, : x.shape[1]].astype(dt)
        head_dim = cfg.width // cfg.heads
        for i in range(cfg.layers):
            x = x + Attention(cfg.heads, head_dim, dt, name=f"attn_{i}")(
                nn.LayerNorm(dtype=dt)(x)
            )
            h = nn.LayerNorm(dtype=dt)(x)
            h = nn.Dense(cfg.width * 4, dtype=dt, name=f"mlp_{i}_up")(h)
            x = x + nn.Dense(cfg.width, dtype=dt, name=f"mlp_{i}_down")(nn.gelu(h))
        x = nn.LayerNorm(dtype=dt, name="final_ln")(x)
        context = nn.Dense(cfg.output_dim, dtype=jnp.float32, name="ctx_proj")(
            x.astype(jnp.float32)
        )
        # pool at the EOT position (token id 1), CLIP-style
        eot = jnp.argmax((tokens == 1).astype(jnp.int32), axis=1)
        pooled_src = x[jnp.arange(x.shape[0]), eot]
        pooled = nn.Dense(cfg.pooled_dim, dtype=jnp.float32, name="pool_proj")(
            pooled_src.astype(jnp.float32)
        )
        return context, pooled


class TextEncoder:
    """Host-facing wrapper: strings → (context [B,N,D], pooled [B,P])."""

    def __init__(
        self,
        config: TextEncoderConfig,
        params=None,
        tokenize_fn: Optional[Callable[[str], Sequence[int]]] = None,
    ):
        self.config = config
        self.module = TextTransformer(config)
        self.params = params
        # tokenization mode for the conditioning cache key
        # (cluster/cache/conditioning.py): this encoder hash-tokenizes BY
        # DESIGN (random-init benchmark stack), which is not the degraded
        # "hash" fallback of the real CLIP/T5 stacks — hence the distinct
        # mode name, so its entries may still persist
        self._tokenize_mode = "custom" if tokenize_fn else "hash-native"
        self._tokenize = tokenize_fn or (
            lambda s: hash_tokenize(s, config.max_len, config.vocab_size)
        )

    def init(self, rng: jax.Array) -> "TextEncoder":
        tokens = jnp.zeros((1, self.config.max_len), jnp.int32)
        self.params = jax.jit(self.module.init)(rng, tokens)
        return self

    def tokenize(self, texts: Sequence[str]) -> jax.Array:
        return jnp.asarray([list(self._tokenize(t)) for t in texts], jnp.int32)

    def token_signature(self, texts: Sequence[str]) -> tuple[list, str]:
        """(token ids as nested lists, tokenization mode) — the
        conditioning cache's key material (cluster/cache)."""
        return ([list(self._tokenize(str(t))) for t in texts],
                self._tokenize_mode)

    def encode(self, texts: Sequence[str]) -> tuple[jax.Array, jax.Array]:
        from .layers import jit_apply

        return jit_apply(self, self.module)(self.params,
                                            self.tokenize(texts))
