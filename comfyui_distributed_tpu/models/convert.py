"""safetensors → flax weight converters (SD-family checkpoints).

The reference never converts weights — it ships model *names* to workers
and lets ComfyUI load the checkpoints (``nodes/utilities.py:164-224``,
SURVEY "external substrate"). A standalone framework must own this step:
these converters map the published single-file checkpoint layouts onto
this repo's flax module trees.

Supported source layouts (key prefixes of the standard single-file
``.safetensors``):

- UNet: ``model.diffusion_model.*`` (LDM/SGM ``UNetModel`` numbering)
- VAE: ``first_stage_model.*`` (LDM ``AutoencoderKL``)
- CLIP-L: ``conditioner.embedders.0.transformer.text_model.*`` (SDXL) or
  ``cond_stage_model.transformer.text_model.*`` (SD1.5) — HF layout
- CLIP-G: ``conditioner.embedders.1.model.*`` (SDXL) — OpenCLIP layout
  with fused ``in_proj_weight`` attention weights

Every converter is **template-driven**: it fills a pytree shaped exactly
like ``module.init(...)``'s params, asserting per-tensor shape equality
and that every source key under the prefix is consumed — a silent partial
load is impossible.

Conventions: torch ``Linear.weight`` is ``[out, in]`` → transposed to
flax ``kernel [in, out]``; conv ``OIHW`` → ``HWIO``; 1×1 convs squeeze to
Dense kernels where the flax module uses Dense.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Mapping

import numpy as np

from ..utils.logging import log


class ConversionError(ValueError):
    pass


# ---------------------------------------------------------------------------
# plumbing
# ---------------------------------------------------------------------------

def _lin(w):   # torch Linear weight -> flax Dense kernel
    return np.asarray(w, np.float32).T


def _conv(w):  # torch Conv2d OIHW -> flax HWIO
    return np.asarray(w, np.float32).transpose(2, 3, 1, 0)


def _conv1x1_to_dense(w):  # [O,I,1,1] -> [I,O]
    return np.asarray(w, np.float32)[:, :, 0, 0].T


def _id(w):
    return np.asarray(w, np.float32)


class _PutHelpers:
    """Shared src→dst naming rules over an abstract ``put`` — the single
    definition both the real filler and the LoRA-key recorder use, so the
    two can never drift."""

    def put(self, src_key: str, dst_path: str,
            transform: Callable = _id) -> None:
        raise NotImplementedError

    def linear(self, src: str, dst: str, bias: bool = True) -> None:
        self.put(f"{src}.weight", f"{dst}/kernel", _lin)
        if bias:
            self.put(f"{src}.bias", f"{dst}/bias")

    def conv(self, src: str, dst: str) -> None:
        self.put(f"{src}.weight", f"{dst}/kernel", _conv)
        self.put(f"{src}.bias", f"{dst}/bias")

    def norm(self, src: str, dst: str) -> None:
        self.put(f"{src}.weight", f"{dst}/scale")
        self.put(f"{src}.bias", f"{dst}/bias")


class _Filler(_PutHelpers):
    """Writes converted tensors into a template-shaped tree with shape
    checks; tracks which source keys and which template leaves were hit."""

    def __init__(self, sd: Mapping[str, np.ndarray], template):
        self.sd = sd
        self.tree = _map_leaves(template, lambda x: None)
        self.template = template
        self.used: set[str] = set()

    def put(self, src_key: str, dst_path: str,
            transform: Callable = _id) -> None:
        if src_key not in self.sd:
            raise ConversionError(f"missing source key {src_key!r}")
        value = transform(self.sd[src_key])
        tmpl = _get_path(self.template, dst_path)
        if tmpl is None:
            raise ConversionError(f"no template leaf at {dst_path!r}")
        if tuple(tmpl.shape) != tuple(value.shape):
            raise ConversionError(
                f"{src_key} -> {dst_path}: shape {value.shape} != "
                f"template {tuple(tmpl.shape)}")
        _set_path(self.tree, dst_path, value.astype(np.float32))
        self.used.add(src_key)

    def put_raw(self, value: np.ndarray, dst_path: str) -> None:
        tmpl = _get_path(self.template, dst_path)
        if tmpl is None:
            raise ConversionError(f"no template leaf at {dst_path!r}")
        if tuple(tmpl.shape) != tuple(value.shape):
            raise ConversionError(
                f"-> {dst_path}: shape {value.shape} != "
                f"template {tuple(tmpl.shape)}")
        _set_path(self.tree, dst_path, np.asarray(value, np.float32))

    def finish(self, *, expect_prefix: str = "") -> dict:
        missing = [p for p, v in _walk(self.tree) if v is None]
        if missing:
            raise ConversionError(
                f"unfilled template leaves: {missing[:8]}"
                f"{'…' if len(missing) > 8 else ''}")
        if expect_prefix:
            leftover = [k for k in self.sd
                        if k.startswith(expect_prefix) and k not in self.used]
            if leftover:
                raise ConversionError(
                    f"unconsumed source keys under {expect_prefix!r}: "
                    f"{leftover[:8]}{'…' if len(leftover) > 8 else ''}")
        return self.tree


def _map_leaves(tree, fn):
    if isinstance(tree, dict):
        return {k: _map_leaves(v, fn) for k, v in tree.items()}
    return fn(tree)


def _walk(tree, prefix=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, f"{prefix}/{k}" if prefix else k)
    else:
        yield prefix, tree


def _get_path(tree, path: str):
    node = tree
    for part in path.split("/"):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _set_path(tree, path: str, value) -> None:
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = value


def load_safetensors(path: Path) -> dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    try:
        return load_file(str(path))
    except Exception:
        # f16/bf16 payloads: torch loader handles every dtype
        from safetensors.torch import load_file as load_torch

        return {k: v.float().numpy() for k, v in load_torch(str(path)).items()}


# ---------------------------------------------------------------------------
# CLIP (HF layout — SD1.5's encoder and SDXL's embedders.0)
# ---------------------------------------------------------------------------

class _Recorder(_PutHelpers):
    """A ``_Filler`` stand-in that records (src_key, dst_path, transform)
    triples instead of filling — the converter layout walks double as the
    source of truth for LoRA key maps (``models/lora.py``)."""

    def __init__(self):
        self.records: list[tuple[str, str, Callable]] = []
        self.used: set[str] = set()

    def put(self, src_key: str, dst_path: str, transform: Callable = _id):
        self.records.append((src_key, dst_path, transform))

    def put_raw(self, value, dst_path: str) -> None:
        pass


def _clip_hf_layout(f, config, p: str) -> None:
    f.put(f"{p}embeddings.token_embedding.weight", "tok_emb/embedding")
    f.put(f"{p}embeddings.position_embedding.weight", "pos_emb")
    for i in range(config.layers):
        src = f"{p}encoder.layers.{i}"
        dst = f"layer_{i}"
        f.norm(f"{src}.layer_norm1", f"{dst}/ln1")
        f.norm(f"{src}.layer_norm2", f"{dst}/ln2")
        for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
            f.linear(f"{src}.self_attn.{proj}", f"{dst}/attn/{proj}")
        f.linear(f"{src}.mlp.fc1", f"{dst}/fc1")
        f.linear(f"{src}.mlp.fc2", f"{dst}/fc2")
    f.norm(f"{p}final_layer_norm", "final_ln")
    if config.projection_dim:
        f.linear("text_projection", "text_projection", bias=False)


def convert_clip_hf(sd: Mapping[str, np.ndarray], template, config,
                    prefix: str = "text_model.") -> dict:
    """HF ``CLIPTextModel`` state dict → ``models.clip.CLIPTextTransformer``
    params. ``text_projection.weight`` (when the template wants one) lives
    *outside* ``text_model.`` in HF checkpoints."""
    f = _Filler(sd, template["params"])
    _clip_hf_layout(f, config, prefix)
    # position_ids buffers appear in older HF dumps — ignore them
    f.used.update(k for k in sd if k.endswith("position_ids"))
    return {"params": f.finish(expect_prefix=prefix)}


# ---------------------------------------------------------------------------
# CLIP (OpenCLIP layout — SDXL's embedders.1, fused qkv)
# ---------------------------------------------------------------------------

def convert_clip_openclip(sd: Mapping[str, np.ndarray], template, config,
                          prefix: str = "model.") -> dict:
    f = _Filler(sd, template["params"])
    p = prefix
    f.put(f"{p}token_embedding.weight", "tok_emb/embedding")
    f.put(f"{p}positional_embedding", "pos_emb")
    width = config.width
    for i in range(config.layers):
        src = f"{p}transformer.resblocks.{i}"
        dst = f"layer_{i}"
        f.norm(f"{src}.ln_1", f"{dst}/ln1")
        f.norm(f"{src}.ln_2", f"{dst}/ln2")
        in_w = np.asarray(sd[f"{src}.attn.in_proj_weight"], np.float32)
        in_b = np.asarray(sd[f"{src}.attn.in_proj_bias"], np.float32)
        if in_w.shape != (3 * width, width):
            raise ConversionError(
                f"{src}.attn.in_proj_weight: shape {in_w.shape} != "
                f"{(3 * width, width)}")
        for j, proj in enumerate(("q_proj", "k_proj", "v_proj")):
            f.put_raw(in_w[j * width:(j + 1) * width].T,
                      f"{dst}/attn/{proj}/kernel")
            f.put_raw(in_b[j * width:(j + 1) * width],
                      f"{dst}/attn/{proj}/bias")
        f.used.update({f"{src}.attn.in_proj_weight",
                       f"{src}.attn.in_proj_bias"})
        f.linear(f"{src}.attn.out_proj", f"{dst}/attn/out_proj")
        f.linear(f"{src}.mlp.c_fc", f"{dst}/fc1")
        f.linear(f"{src}.mlp.c_proj", f"{dst}/fc2")
    f.norm(f"{p}ln_final", "final_ln")
    # openclip applies `pooled @ text_projection` directly → already [in,out]
    f.put(f"{p}text_projection", "text_projection/kernel")
    f.used.update(k for k in sd
                  if k.startswith(p) and k.endswith(("attn_mask", "logit_scale")))
    return {"params": f.finish(expect_prefix=p)}


# ---------------------------------------------------------------------------
# UNet (LDM/SGM UNetModel numbering)
# ---------------------------------------------------------------------------

def _res_block(f: _Filler, src: str, dst: str, has_skip: bool) -> None:
    """LDM ResBlock: in_layers=[GN,SiLU,conv], emb_layers=[SiLU,Linear],
    out_layers=[GN,SiLU,dropout,conv], optional 1×1 skip_connection."""
    f.norm(f"{src}.in_layers.0", f"{dst}/GroupNorm32_0/GroupNorm_0")
    f.conv(f"{src}.in_layers.2", f"{dst}/conv1")
    f.linear(f"{src}.emb_layers.1", f"{dst}/time_proj")
    f.norm(f"{src}.out_layers.0", f"{dst}/GroupNorm32_1/GroupNorm_0")
    f.conv(f"{src}.out_layers.3", f"{dst}/conv2")
    if has_skip:
        f.put(f"{src}.skip_connection.weight", f"{dst}/skip/kernel", _conv)
        f.put(f"{src}.skip_connection.bias", f"{dst}/skip/bias")


def _spatial_transformer(f: _Filler, src: str, dst: str, depth: int,
                         linear_proj: bool) -> None:
    f.norm(f"{src}.norm", f"{dst}/GroupNorm32_0/GroupNorm_0")
    proj_tx = _lin if linear_proj else _conv1x1_to_dense
    f.put(f"{src}.proj_in.weight", f"{dst}/proj_in/kernel", proj_tx)
    f.put(f"{src}.proj_in.bias", f"{dst}/proj_in/bias")
    for d in range(depth):
        b_src = f"{src}.transformer_blocks.{d}"
        b_dst = f"{dst}/block_{d}"
        f.norm(f"{b_src}.norm1", f"{b_dst}/LayerNorm_0")
        f.norm(f"{b_src}.norm2", f"{b_dst}/LayerNorm_1")
        f.norm(f"{b_src}.norm3", f"{b_dst}/LayerNorm_2")
        for attn in ("attn1", "attn2"):
            for proj in ("to_q", "to_k", "to_v"):
                f.put(f"{b_src}.{attn}.{proj}.weight",
                      f"{b_dst}/{attn}/{proj}/kernel", _lin)
            f.linear(f"{b_src}.{attn}.to_out.0", f"{b_dst}/{attn}/to_out")
        f.linear(f"{b_src}.ff.net.0.proj", f"{b_dst}/ff/proj_in")
        f.linear(f"{b_src}.ff.net.2", f"{b_dst}/ff/proj_out")
    f.put(f"{src}.proj_out.weight", f"{dst}/proj_out/kernel", proj_tx)
    f.put(f"{src}.proj_out.bias", f"{dst}/proj_out/bias")


def _unet_embed_layout(f, cfg, p: str) -> None:
    f.linear(f"{p}time_embed.0", "time_1")
    f.linear(f"{p}time_embed.2", "time_2")
    if cfg.adm_in_channels:
        f.linear(f"{p}label_emb.0.0", "label_1")
        f.linear(f"{p}label_emb.0.2", "label_2")


def _unet_down_layout(f, cfg, p: str, linear_proj: bool) -> int:
    """Encoder walk (shared with the ControlNet converter, whose trunk is
    an exact copy of the UNet encoder). Returns the skip count."""
    f.conv(f"{p}input_blocks.0.0", "conv_in")
    idx = 1
    skips = 1
    prev_ch = cfg.model_channels
    for level, mult in enumerate(cfg.channel_mult):
        ch = cfg.model_channels * mult
        for i in range(cfg.num_res_blocks):
            src = f"{p}input_blocks.{idx}"
            _res_block(f, f"{src}.0", f"down_{level}_res_{i}",
                       has_skip=prev_ch != ch)
            if cfg.transformer_depth[level]:
                _spatial_transformer(f, f"{src}.1", f"down_{level}_attn_{i}",
                                     cfg.transformer_depth[level], linear_proj)
            prev_ch = ch
            idx += 1
            skips += 1
        if level < len(cfg.channel_mult) - 1:
            # Downsample/Upsample wrap an unnamed nn.Conv → auto "Conv_0"
            f.conv(f"{p}input_blocks.{idx}.0.op", f"down_{level}_ds/Conv_0")
            idx += 1
            skips += 1
    return skips


def _unet_mid_layout(f, cfg, p: str, linear_proj: bool) -> None:
    _res_block(f, f"{p}middle_block.0", "mid_res_1", has_skip=False)
    if cfg.transformer_depth[-1]:
        _spatial_transformer(f, f"{p}middle_block.1", "mid_attn",
                             cfg.transformer_depth[-1], linear_proj)
        _res_block(f, f"{p}middle_block.2", "mid_res_2", has_skip=False)
    else:
        _res_block(f, f"{p}middle_block.1", "mid_res_2", has_skip=False)


def _unet_layout(f, cfg, p: str, linear_proj: bool) -> None:
    """The full LDM→flax key walk (same block numbering the LDM
    constructor uses, so index math is config-derived). Drives both the
    real converter and the LoRA-key recorder."""
    _unet_embed_layout(f, cfg, p)
    _unet_down_layout(f, cfg, p, linear_proj)
    _unet_mid_layout(f, cfg, p, linear_proj)

    # up path: skip-concat changes input channels, so every ResBlock has a
    # skip 1×1. Mirror UNet2D's skip-pop order to know nothing more is
    # needed than has_skip=True throughout.
    idx = 0
    for level in reversed(range(len(cfg.channel_mult))):
        for i in range(cfg.num_res_blocks + 1):
            src = f"{p}output_blocks.{idx}"
            _res_block(f, f"{src}.0", f"up_{level}_res_{i}", has_skip=True)
            sub = 1
            if cfg.transformer_depth[level]:
                _spatial_transformer(f, f"{src}.{sub}", f"up_{level}_attn_{i}",
                                     cfg.transformer_depth[level], linear_proj)
                sub += 1
            if level > 0 and i == cfg.num_res_blocks:
                f.conv(f"{p}output_blocks.{idx}.{sub}.conv",
                       f"up_{level}_us/Conv_0")
            idx += 1

    f.norm(f"{p}out.0", "norm_out/GroupNorm_0")
    f.conv(f"{p}out.2", "conv_out")


def convert_unet(sd: Mapping[str, np.ndarray], template, config,
                 prefix: str = "model.diffusion_model.") -> dict:
    """LDM ``UNetModel`` → ``models.unet.UNet2D`` params."""
    f = _Filler(sd, template["params"])
    # SDXL uses linear proj_in/out in transformers; SD1.5 uses 1×1 convs.
    # Detect from the checkpoint itself.
    linear_proj = True
    for k in sd:
        if k.startswith(prefix) and k.endswith("proj_in.weight"):
            linear_proj = len(sd[k].shape) == 2
            break
    _unet_layout(f, config, prefix, linear_proj)
    return {"params": f.finish(expect_prefix=prefix)}


# ---------------------------------------------------------------------------
# VAE (LDM AutoencoderKL)
# ---------------------------------------------------------------------------

def _vae_res(f: _Filler, src: str, dst: str, has_skip: bool) -> None:
    f.norm(f"{src}.norm1", f"{dst}/GroupNorm32_0/GroupNorm_0")
    f.conv(f"{src}.conv1", f"{dst}/conv1")
    f.norm(f"{src}.norm2", f"{dst}/GroupNorm32_1/GroupNorm_0")
    f.conv(f"{src}.conv2", f"{dst}/conv2")
    if has_skip:
        f.put(f"{src}.nin_shortcut.weight", f"{dst}/skip/kernel", _conv)
        f.put(f"{src}.nin_shortcut.bias", f"{dst}/skip/bias")


def _vae_mid(f: _Filler, src: str, dst: str) -> None:
    _vae_res(f, f"{src}.block_1", f"{dst}/res1", has_skip=False)
    f.norm(f"{src}.attn_1.norm", f"{dst}/GroupNorm32_0/GroupNorm_0")
    for t_proj, o_proj in (("q", "to_q"), ("k", "to_k"), ("v", "to_v"),
                           ("proj_out", "to_out")):
        f.put(f"{src}.attn_1.{t_proj}.weight",
              f"{dst}/attn/{o_proj}/kernel", _conv1x1_to_dense)
        f.put(f"{src}.attn_1.{t_proj}.bias", f"{dst}/attn/{o_proj}/bias")
    _vae_res(f, f"{src}.block_2", f"{dst}/res2", has_skip=False)


def convert_vae(sd: Mapping[str, np.ndarray], enc_template, dec_template,
                config, prefix: str = "first_stage_model.",
                quant_convs: bool = True) -> tuple[dict, dict]:
    """``quant_convs=False`` handles the BFL ``ae.safetensors`` layout
    (FLUX KL-VAE): same encoder/decoder walk, no quant convs in the file —
    identity 1×1 convs are synthesized so the flax modules are unchanged."""
    cfg = config
    p = prefix

    fe = _Filler(sd, enc_template["params"])
    fe.conv(f"{p}encoder.conv_in", "conv_in")
    prev_ch = cfg.base_channels
    for level, mult in enumerate(cfg.channel_mult):
        ch = cfg.base_channels * mult
        for i in range(cfg.num_res_blocks):
            _vae_res(fe, f"{p}encoder.down.{level}.block.{i}",
                     f"down_{level}_res_{i}", has_skip=prev_ch != ch)
            prev_ch = ch
        if level < len(cfg.channel_mult) - 1:
            fe.conv(f"{p}encoder.down.{level}.downsample.conv",
                    f"down_{level}_ds")
    _vae_mid(fe, f"{p}encoder.mid", "mid")
    fe.norm(f"{p}encoder.norm_out", "norm_out/GroupNorm_0")
    fe.conv(f"{p}encoder.conv_out", "conv_out")
    if quant_convs:
        fe.conv(f"{p}quant_conv", "quant_conv")
    else:
        z2 = 2 * cfg.latent_channels
        eye = np.zeros((1, 1, z2, z2), np.float32)
        eye[0, 0] = np.eye(z2)
        fe.put_raw(eye, "quant_conv/kernel")
        fe.put_raw(np.zeros((z2,), np.float32), "quant_conv/bias")
    enc = {"params": fe.finish()}

    fd = _Filler(sd, dec_template["params"])
    if quant_convs:
        fd.conv(f"{p}post_quant_conv", "post_quant_conv")
    else:
        z = cfg.latent_channels
        eye = np.zeros((1, 1, z, z), np.float32)
        eye[0, 0] = np.eye(z)
        fd.put_raw(eye, "post_quant_conv/kernel")
        fd.put_raw(np.zeros((z,), np.float32), "post_quant_conv/bias")
    fd.conv(f"{p}decoder.conv_in", "conv_in")
    _vae_mid(fd, f"{p}decoder.mid", "mid")
    top_ch = cfg.base_channels * cfg.channel_mult[-1]
    prev_ch = top_ch
    for level in reversed(range(len(cfg.channel_mult))):
        ch = cfg.base_channels * cfg.channel_mult[level]
        for i in range(cfg.num_res_blocks + 1):
            _vae_res(fd, f"{p}decoder.up.{level}.block.{i}",
                     f"up_{level}_res_{i}", has_skip=prev_ch != ch)
            prev_ch = ch
        if level > 0:
            fd.conv(f"{p}decoder.up.{level}.upsample.conv", f"up_{level}_us")
    fd.norm(f"{p}decoder.norm_out", "norm_out/GroupNorm_0")
    fd.conv(f"{p}decoder.conv_out", "conv_out")
    dec = {"params": fd.finish()}

    leftover = [k for k in sd if k.startswith(p)
                and k not in fe.used and k not in fd.used
                and "loss" not in k and "model_ema" not in k]
    if leftover:
        raise ConversionError(
            f"unconsumed VAE keys: {leftover[:8]}"
            f"{'…' if len(leftover) > 8 else ''}")
    return enc, dec


# ---------------------------------------------------------------------------
# single-file checkpoint assembly
# ---------------------------------------------------------------------------

SDXL_CLIP_L_PREFIX = "conditioner.embedders.0.transformer.text_model."
SDXL_CLIP_G_PREFIX = "conditioner.embedders.1.model."
SD15_CLIP_PREFIX = "cond_stage_model.transformer.text_model."


def detect_layout(sd: Mapping[str, np.ndarray]) -> str:
    if any(k.endswith("double_blocks.0.img_attn.qkv.weight") for k in sd):
        return "flux"
    if any(k.endswith("joint_blocks.0.x_block.attn.qkv.weight") for k in sd):
        return "sd3"
    if any(k.endswith("blocks.0.self_attn.norm_q.weight") for k in sd):
        return "wan"
    # diffusers repacks: both FLUX and SD3 use transformer_blocks.*, but
    # only FLUX carries a single_transformer_blocks.* tail — check it
    # first so each gets the error naming ITS single-file layout
    if any(FLUX_SINGLE_DIFFUSERS_HINT in k for k in sd):
        raise ConversionError(
            "diffusers-repacked FLUX transformer (transformer_blocks.*/"
            "single_transformer_blocks.*) is not supported — convert from "
            "the BFL single-file layout "
            "(double_blocks.*/single_blocks.*) instead")
    if any(k.startswith(FLUX_DIFFUSERS_HINT) for k in sd):
        raise ConversionError(
            "diffusers-repacked SD3 MMDiT (transformer_blocks.*) is not "
            "supported — convert from the single-file layout "
            "(joint_blocks.*) instead")
    if any(k.startswith(SDXL_CLIP_G_PREFIX) for k in sd):
        return "sdxl"
    if any(k.startswith(SD15_CLIP_PREFIX) for k in sd):
        return "sd15"
    if any(k.startswith("model.diffusion_model.") for k in sd):
        return "unet-only"
    raise ConversionError("unrecognized checkpoint layout")


def convert_checkpoint(path: Path, bundle) -> None:
    """Load a single-file checkpoint into a ``ModelBundle`` in place.

    ``bundle`` must be built from the matching preset (``sdxl``/``sd15``);
    template trees come from its random-init params, so every converted
    tensor is shape-checked against the live architecture.
    """
    sd = load_safetensors(Path(path))
    layout = detect_layout(sd)
    log(f"converting {path} (layout: {layout})")

    if layout == "flux":
        if bundle.kind != "dit":
            raise ConversionError(
                f"FLUX transformer checkpoint needs a dit preset; "
                f"{bundle.preset.name!r} is {bundle.kind!r}")
        prefix = (FLUX_PREFIXED if any(k.startswith(FLUX_PREFIXED)
                                       for k in sd) else "")
        bundle.pipeline.dit_params = convert_flux(
            sd, bundle.pipeline.dit_params, bundle.preset.dit, prefix)
        log("FLUX transformer converted; VAE/text encoders ship separately "
            "and keep their current weights")
        return

    if layout == "sd3":
        if bundle.kind != "dit":
            raise ConversionError(
                f"SD3 MMDiT checkpoint needs a dit preset; "
                f"{bundle.preset.name!r} is {bundle.kind!r}")
        prefix = (FLUX_PREFIXED if any(k.startswith(FLUX_PREFIXED)
                                       for k in sd) else "")
        bundle.pipeline.dit_params = convert_mmdit_sd3(
            sd, bundle.pipeline.dit_params, bundle.preset.dit, prefix)
        log("SD3 MMDiT converted; VAE/text encoders ship separately "
            "and keep their current weights")
        return

    if layout == "wan":
        from .wan import WAN_PREFIXED, WanConfig, convert_wan

        if bundle.kind != "video" or not isinstance(bundle.preset.video,
                                                    WanConfig):
            raise ConversionError(
                f"WAN transformer checkpoint needs a wan video preset; "
                f"{bundle.preset.name!r} is {bundle.kind!r}")
        prefix = (WAN_PREFIXED if any(k.startswith(WAN_PREFIXED)
                                      for k in sd) else "")
        bundle.pipeline.dit_params = convert_wan(
            sd, bundle.pipeline.dit_params, bundle.preset.video, prefix)
        log("WAN transformer converted; VAE/text encoders ship separately "
            "and keep their current weights")
        return

    unet_tmpl = bundle.pipeline.unet_params
    bundle.pipeline.unet_params = convert_unet(
        sd, unet_tmpl, bundle.preset.unet)

    if layout == "unet-only":
        log("unet-only checkpoint: VAE and CLIP keep their current weights")
        return

    enc, dec = convert_vae(sd, bundle.pipeline.vae.enc_params,
                           bundle.pipeline.vae.dec_params, bundle.preset.vae)
    bundle.pipeline.vae.enc_params = enc
    bundle.pipeline.vae.dec_params = dec

    if layout == "sdxl":
        stack = bundle.clip_stack
        stack.clip_l.params = convert_clip_hf(
            {k[len("conditioner.embedders.0.transformer."):]: v
             for k, v in sd.items()
             if k.startswith("conditioner.embedders.0.transformer.")},
            stack.clip_l.params, stack.clip_l.config)
        stack.clip_g.params = convert_clip_openclip(
            {k[len("conditioner.embedders.1."):]: v for k, v in sd.items()
             if k.startswith("conditioner.embedders.1.")},
            stack.clip_g.params, stack.clip_g.config)
    elif layout == "sd15":
        # sd15 presets carry a single CLIPTextModel (no dual stack)
        clip = bundle.clip_stack
        clip.params = convert_clip_hf(
            {k[len("cond_stage_model.transformer."):]: v
             for k, v in sd.items()
             if k.startswith("cond_stage_model.transformer.")},
            clip.params, clip.config)
    log(f"converted {path} into {bundle.preset.name} bundle")


# ---------------------------------------------------------------------------
# ESRGAN-family upscalers (RRDBNet)
# ---------------------------------------------------------------------------

def _upscaler_config_from_sd(sd: Mapping[str, np.ndarray]):
    """Infer the RRDBNet geometry from checkpoint shapes.

    Supports both published layouts: BasicSR/Real-ESRGAN "new arch"
    (``conv_first``/``body.N...``) and original-ESRGAN "old arch"
    (``model.0``/``model.1.sub.N...``) — the layout every community
    checkpoint (4x-UltraSharp, RealESRGAN_x4plus, …) uses.
    """
    from .upscaler import UpscalerConfig

    if "conv_first.weight" in sd:
        arch = "new"
        first = sd["conv_first.weight"]
        blocks = {int(k.split(".")[1]) for k in sd if k.startswith("body.")}
        grow = sd["body.0.rdb1.conv1.weight"].shape[0]
    elif "model.0.weight" in sd:
        arch = "old"
        first = sd["model.0.weight"]
        blocks = {int(k.split(".")[3]) for k in sd
                  if k.startswith("model.1.sub.") and ".RDB" in k}
        grow = sd["model.1.sub.0.RDB1.conv1.0.weight"].shape[0]
    else:
        raise ConversionError("unrecognized upscaler layout "
                              "(no conv_first.* / model.0.*)")
    num_feat, in_total = first.shape[0], first.shape[1]
    # pixel-unshuffle stem encodes the scale in the stem's input width
    scale = {1: 4, 4: 2, 16: 1}.get(in_total // 3)
    if scale is None or in_total % 3:
        raise ConversionError(f"cannot infer scale from stem width {in_total}")
    cfg = UpscalerConfig(scale=scale, num_feat=num_feat,
                         num_block=max(blocks) + 1, grow_ch=grow)
    return cfg, arch


def convert_upscaler(sd: Mapping[str, np.ndarray]):
    """torch RRDBNet state dict → (config, flax params)."""
    from .upscaler import init_upscaler

    cfg, arch = _upscaler_config_from_sd(sd)
    import jax

    template = init_upscaler(cfg, jax.random.key(0), sample_hw=(16, 16)).params
    f = _Filler(sd, template)

    if arch == "new":
        def body_key(i, j, k):
            return f"body.{i}.rdb{j}.conv{k}"
        heads = {"conv_first": "conv_first", "conv_body": "conv_body",
                 "conv_up1": "conv_up1", "conv_up2": "conv_up2",
                 "conv_hr": "conv_hr", "conv_last": "conv_last"}
    else:
        def body_key(i, j, k):
            return f"model.1.sub.{i}.RDB{j}.conv{k}.0"
        heads = {"model.0": "conv_first",
                 f"model.1.sub.{cfg.num_block}": "conv_body",
                 "model.3": "conv_up1", "model.6": "conv_up2",
                 "model.8": "conv_hr", "model.10": "conv_last"}

    for src, dst in heads.items():
        f.conv(src, f"params/{dst}")
    for i in range(cfg.num_block):
        for j in (1, 2, 3):
            for k in (1, 2, 3, 4, 5):
                f.conv(body_key(i, j, k),
                       f"params/body_{i}/rdb{j}/conv{k}")
    params = f.finish()
    leftover = sorted(set(sd) - f.used)
    if leftover:
        raise ConversionError(
            f"unconsumed upscaler keys: {leftover[:8]}"
            f"{'…' if len(leftover) > 8 else ''}")
    return cfg, params


def load_upscaler_checkpoint(path: Path):
    """Published ``.safetensors`` RRDBNet → ``UpscalerBundle``."""
    from .upscaler import RRDBNet, UpscalerBundle

    sd = load_safetensors(Path(path))
    cfg, params = convert_upscaler(sd)
    log(f"converted upscaler {path} "
        f"(x{cfg.scale}, {cfg.num_block} blocks, {cfg.num_feat} feat)")
    return UpscalerBundle(RRDBNet(cfg), params, name=Path(path).stem)


# ---------------------------------------------------------------------------
# ControlNet (LDM ``cldm`` layout — ``control_model.*``)
# ---------------------------------------------------------------------------

_HINT_SRC_INDICES = (0, 2, 4, 6, 8, 10, 12, 14)


def _controlnet_layout(f, cfg, p: str, linear_proj: bool) -> None:
    """``control_model.*`` walk: the trunk is an exact copy of the UNet
    encoder (shared ``_unet_down_layout`` — drift-proof), plus the hint
    stem, one zero-conv per skip, and the middle output zero-conv."""
    _unet_embed_layout(f, cfg, p)
    n_skips = _unet_down_layout(f, cfg, p, linear_proj)
    _unet_mid_layout(f, cfg, p, linear_proj)
    for j, src_idx in enumerate(_HINT_SRC_INDICES):
        f.conv(f"{p}input_hint_block.{src_idx}", f"hint_{j}")
    for i in range(n_skips):
        f.conv(f"{p}zero_convs.{i}.0", f"zero_{i}")
    f.conv(f"{p}middle_block_out.0", "mid_out")


def convert_controlnet(sd: Mapping[str, np.ndarray], template, config,
                       prefix: str = "control_model.") -> dict:
    """LDM ControlNet state dict → ``models.controlnet.ControlNet`` params."""
    f = _Filler(sd, template["params"])
    linear_proj = True
    for k in sd:
        if k.startswith(prefix) and k.endswith("proj_in.weight"):
            linear_proj = len(sd[k].shape) == 2
            break
    _controlnet_layout(f, config, prefix, linear_proj)
    return {"params": f.finish(expect_prefix=prefix)}


# ---------------------------------------------------------------------------
# FLUX-class MMDiT (BFL transformer layout)
# ---------------------------------------------------------------------------

FLUX_DIFFUSERS_HINT = "transformer_blocks."      # diffusers repack: unsupported
# FLUX's diffusers repack alone carries the single-stream tail — the
# discriminator between diffusers-FLUX and diffusers-SD3 in detect_layout
FLUX_SINGLE_DIFFUSERS_HINT = "single_transformer_blocks."
FLUX_PREFIXED = "model.diffusion_model."         # ComfyUI single-file repack


def _flux_patch_perm(p: int, c: int) -> np.ndarray:
    """Patch-token feature permutation BFL→ours.

    BFL patchifies ``(c, ph, pw)``-major (``rearrange "b c (h ph) (w pw) ->
    b (h w) (c ph pw)"``); ``dit.patchify`` flattens ``(ph, pw, c)``.
    ``perm[j]`` is the BFL feature index holding our feature ``j``."""
    idx = np.arange(c * p * p).reshape(c, p, p)
    return idx.transpose(1, 2, 0).reshape(-1)


def convert_flux(sd: Mapping[str, np.ndarray], template, config,
                 prefix: str = "") -> dict:
    """BFL FLUX transformer state dict → ``models/dit.DiT`` params.

    Source layout: the published ``flux1-dev``/``flux1-schnell``
    ``.safetensors`` transformer keys (``img_in``, ``time_in.*``,
    ``double_blocks.N.*``, ``single_blocks.N.*``, ``final_layer.*``), bare
    or under ``model.diffusion_model.`` (single-file repacks). The
    reference runs FLUX through ComfyUI's loader (SURVEY "external
    substrate"); here the mapping is explicit and shape-checked:

    - ``double_blocks.i.{img,txt}_mod.lin`` → ``double_i/{img,txt}_mod/mod``
    - ``…_attn.qkv / …_attn.proj / …_mlp.{0,2}`` →
      ``{img,txt}_qkv/qkv, {img,txt}_proj, {img,txt}_mlp_{up,down}``
    - ``…_attn.norm.{query,key}_norm.scale`` → ``{img,txt}_qkv/{q,k}_scale``
    - ``single_blocks.i.linear1`` (rows ``[3h | 4h]``) row-splits into
      ``qkv/qkv`` + ``mlp_up``; ``linear2`` → ``out`` (our concat order
      ``[attn, gelu(mlp)]`` matches BFL's)
    - ``final_layer.adaLN_modulation.1`` (rows ``[shift | scale]``) maps
      into the first two thirds of ``final_mod/mod``; the gate third the
      flax Modulation also produces (and the final layer discards) is zero
    - ``img_in`` / ``final_layer.linear`` are column/row-permuted for the
      patch-ordering difference (``_flux_patch_perm``)
    """
    p = prefix
    f = _Filler(sd, template["params"])
    h = config.hidden

    def take(key: str) -> np.ndarray:
        if key not in sd:
            raise ConversionError(f"missing source key {key!r}")
        f.used.add(key)
        return np.asarray(sd[key], np.float32)

    perm = _flux_patch_perm(config.patch_size, config.in_channels)
    f.put_raw(take(f"{p}img_in.weight").T[perm], "img_in/kernel")
    f.put(f"{p}img_in.bias", "img_in/bias")
    f.linear(f"{p}txt_in", "txt_in")
    embedders = ["time_in", "vector_in"]
    if config.guidance_embed:
        if f"{p}guidance_in.in_layer.weight" not in sd:
            raise ConversionError(
                "preset expects distilled guidance (guidance_embed=True) "
                "but the checkpoint has no guidance_in.* keys — use a "
                "schnell-style preset with guidance_embed=False")
        embedders.append("guidance_in")
    for name in embedders:
        f.linear(f"{p}{name}.in_layer", f"{name}/in_layer")
        f.linear(f"{p}{name}.out_layer", f"{name}/out_layer")

    for i in range(config.depth_double):
        src, dst = f"{p}double_blocks.{i}", f"double_{i}"
        for s in ("img", "txt"):
            f.linear(f"{src}.{s}_mod.lin", f"{dst}/{s}_mod/mod")
            f.linear(f"{src}.{s}_attn.qkv", f"{dst}/{s}_qkv/qkv")
            f.put(f"{src}.{s}_attn.norm.query_norm.scale",
                  f"{dst}/{s}_qkv/q_scale")
            f.put(f"{src}.{s}_attn.norm.key_norm.scale",
                  f"{dst}/{s}_qkv/k_scale")
            f.linear(f"{src}.{s}_attn.proj", f"{dst}/{s}_proj")
            f.linear(f"{src}.{s}_mlp.0", f"{dst}/{s}_mlp_up")
            f.linear(f"{src}.{s}_mlp.2", f"{dst}/{s}_mlp_down")

    for i in range(config.depth_single):
        src, dst = f"{p}single_blocks.{i}", f"single_{i}"
        w1, b1 = take(f"{src}.linear1.weight"), take(f"{src}.linear1.bias")
        f.put_raw(w1[:3 * h].T, f"{dst}/qkv/qkv/kernel")
        f.put_raw(b1[:3 * h], f"{dst}/qkv/qkv/bias")
        f.put_raw(w1[3 * h:].T, f"{dst}/mlp_up/kernel")
        f.put_raw(b1[3 * h:], f"{dst}/mlp_up/bias")
        f.put(f"{src}.norm.query_norm.scale", f"{dst}/qkv/q_scale")
        f.put(f"{src}.norm.key_norm.scale", f"{dst}/qkv/k_scale")
        f.linear(f"{src}.linear2", f"{dst}/out")
        f.linear(f"{src}.modulation.lin", f"{dst}/mod/mod")

    wf = take(f"{p}final_layer.adaLN_modulation.1.weight")      # [2h, h]
    bf = take(f"{p}final_layer.adaLN_modulation.1.bias")
    f.put_raw(np.concatenate([wf.T, np.zeros((h, h), np.float32)], axis=1),
              "final_mod/mod/kernel")
    f.put_raw(np.concatenate([bf, np.zeros(h, np.float32)]),
              "final_mod/mod/bias")
    wo = take(f"{p}final_layer.linear.weight")
    f.put_raw(wo[perm].T, "img_out/kernel")
    f.put_raw(take(f"{p}final_layer.linear.bias")[perm], "img_out/bias")
    tree = f.finish(expect_prefix=p)
    if not p:
        leftover = [k for k in sd if k not in f.used]
        if leftover:
            raise ConversionError(
                f"unconsumed FLUX keys: {leftover[:8]}"
                f"{'…' if len(leftover) > 8 else ''}")
    return {"params": tree}


def convert_mmdit_sd3(sd: Mapping[str, np.ndarray], template, config,
                      prefix: str = "") -> dict:
    """SD3/SD3.5 MMDiT state dict → ``models/dit.DiT`` params.

    Source layout: the published SAI single-file transformer keys
    (``x_embedder.proj``, ``pos_embed``, ``context_embedder``,
    ``t_embedder.mlp.{0,2}``, ``y_embedder.mlp.{0,2}``,
    ``joint_blocks.N.{x_block,context_block}.*``, ``final_layer.*``), bare
    or under ``model.diffusion_model.``. The reference runs SD3 through
    ComfyUI's loader (SURVEY "external substrate"); here the mapping is
    explicit and shape-checked:

    - ``x_embedder.proj`` is a p×p stride-p conv: its OIHW kernel
      transposes to our patchified-token Dense ordering (row, col, chan)
      — ``w.transpose(2, 3, 1, 0).reshape(p·p·C, hidden)``. SD3's own
      unpatchify uses the same (p, q, c) ordering, so ``final_layer.
      linear`` needs NO row permutation (unlike FLUX, ``_flux_patch_perm``).
    - ``pos_embed`` ([1, m², h] trained table) → ``pos_emb`` verbatim.
    - ``joint_blocks.i.{x,context}_block.{adaLN_modulation.1, attn.qkv,
      attn.proj, mlp.fc1, mlp.fc2}`` → ``double_i/{img,txt}_{mod/mod,
      qkv/qkv, proj, mlp_up, mlp_down}`` (modulation row order
      [shift|scale|gate]×2 matches).
    - SD3.5 qk-norm: ``attn.ln_{q,k}.weight`` → ``{q,k}_scale`` — present
      exactly when ``config.qk_norm``; a mismatch raises with guidance.
    - the LAST ``context_block`` is pre-only (SD3 discards the text
      stream after the final joint attention): its 2h-row adaLN maps into
      the first third of ``txt_mod/mod`` and the text-side output layers
      (``txt_proj``, ``txt_mlp_*``) — which cannot influence the image
      output — fill with zeros.
    - ``final_layer.adaLN_modulation.1`` (rows [shift|scale]) maps into
      the first two thirds of ``final_mod/mod``; the unused gate third is
      zero (same convention as the FLUX converter).
    """
    p = prefix
    f = _Filler(sd, template["params"])
    h = config.hidden

    def take(key: str) -> np.ndarray:
        if key not in sd:
            raise ConversionError(f"missing source key {key!r}")
        f.used.add(key)
        return np.asarray(sd[key], np.float32)

    pp, c_in = config.patch_size, config.in_channels
    wx = take(f"{p}x_embedder.proj.weight")          # [h, C, p, p]
    f.put_raw(wx.transpose(2, 3, 1, 0).reshape(pp * pp * c_in, h),
              "img_in/kernel")
    f.put(f"{p}x_embedder.proj.bias", "img_in/bias")
    m = config.pos_embed_max_size
    f.put_raw(take(f"{p}pos_embed").reshape(m * m, h), "pos_emb")
    f.linear(f"{p}context_embedder", "txt_in")
    for src, dst in (("t_embedder", "time_in"), ("y_embedder", "vector_in")):
        f.linear(f"{p}{src}.mlp.0", f"{dst}/in_layer")
        f.linear(f"{p}{src}.mlp.2", f"{dst}/out_layer")

    qk_keys = f"{p}joint_blocks.0.x_block.attn.ln_q.weight" in sd
    if config.qk_norm and not qk_keys:
        raise ConversionError(
            "preset expects RMS qk-norm (SD3.5-class) but the checkpoint "
            "has no attn.ln_q/ln_k keys — use an SD3-medium-class preset "
            "with qk_norm=False")
    if qk_keys and not config.qk_norm:
        raise ConversionError(
            "checkpoint carries attn.ln_q/ln_k qk-norm scales but the "
            "preset has qk_norm=False — use an SD3.5-class preset")

    last = config.depth_double - 1
    for i in range(config.depth_double):
        dst = f"double_{i}"
        for tag, ours in (("x_block", "img"), ("context_block", "txt")):
            src = f"{p}joint_blocks.{i}.{tag}"
            pre_only = tag == "context_block" and i == last
            wm = take(f"{src}.adaLN_modulation.1.weight")
            bm = take(f"{src}.adaLN_modulation.1.bias")
            if pre_only:
                if wm.shape[0] != 2 * h:
                    raise ConversionError(
                        f"{src}: expected pre-only 2h-row adaLN in the "
                        f"last context block, got {wm.shape[0]} rows")
                wm = np.concatenate(
                    [wm, np.zeros((4 * h, h), np.float32)], axis=0)
                bm = np.concatenate([bm, np.zeros(4 * h, np.float32)])
            f.put_raw(wm.T, f"{dst}/{ours}_mod/mod/kernel")
            f.put_raw(bm, f"{dst}/{ours}_mod/mod/bias")
            f.linear(f"{src}.attn.qkv", f"{dst}/{ours}_qkv/qkv")
            if config.qk_norm:
                f.put(f"{src}.attn.ln_q.weight", f"{dst}/{ours}_qkv/q_scale")
                f.put(f"{src}.attn.ln_k.weight", f"{dst}/{ours}_qkv/k_scale")
            if pre_only:
                f.put_raw(np.zeros((h, h), np.float32), f"{dst}/txt_proj/kernel")
                f.put_raw(np.zeros(h, np.float32), f"{dst}/txt_proj/bias")
                f.put_raw(np.zeros((h, 4 * h), np.float32),
                          f"{dst}/txt_mlp_up/kernel")
                f.put_raw(np.zeros(4 * h, np.float32), f"{dst}/txt_mlp_up/bias")
                f.put_raw(np.zeros((4 * h, h), np.float32),
                          f"{dst}/txt_mlp_down/kernel")
                f.put_raw(np.zeros(h, np.float32), f"{dst}/txt_mlp_down/bias")
            else:
                f.linear(f"{src}.attn.proj", f"{dst}/{ours}_proj")
                f.linear(f"{src}.mlp.fc1", f"{dst}/{ours}_mlp_up")
                f.linear(f"{src}.mlp.fc2", f"{dst}/{ours}_mlp_down")

    wf = take(f"{p}final_layer.adaLN_modulation.1.weight")      # [2h, h]
    bf = take(f"{p}final_layer.adaLN_modulation.1.bias")
    f.put_raw(np.concatenate([wf.T, np.zeros((h, h), np.float32)], axis=1),
              "final_mod/mod/kernel")
    f.put_raw(np.concatenate([bf, np.zeros(h, np.float32)]),
              "final_mod/mod/bias")
    f.linear(f"{p}final_layer.linear", "img_out")
    tree = f.finish(expect_prefix=p)
    if not p:
        leftover = [k for k in sd if k not in f.used]
        if leftover:
            raise ConversionError(
                f"unconsumed SD3 keys: {leftover[:8]}"
                f"{'…' if len(leftover) > 8 else ''}")
    return {"params": tree}
