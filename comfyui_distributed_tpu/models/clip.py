"""Weight-faithful CLIP text encoders (SDXL/SD1.5 conditioning).

The reference free-rides on ComfyUI's CLIP loaders for conditioning
(SURVEY "external substrate"); this module owns it. Unlike
``models/text.py`` (a generic encoder for random-init benchmarks), these
modules reproduce the *exact* CLIP text-transformer computation so
published checkpoints load and match:

- pre-LN residual blocks with a **causal** attention mask,
- ``quick_gelu`` (CLIP-L) or ``gelu`` (CLIP-G) MLP activation,
- EOT pooling at ``argmax(tokens == eot_token_id)``,
- optional ``text_projection`` (CLIP-G pooled output),
- penultimate-layer hidden states (what SDXL/SD conditioning consumes:
  sgm's FrozenCLIPEmbedder uses ``hidden_states[-2]`` with no final LN).

Numerics are validated against ``transformers.CLIPTextModel`` in
``tests/test_clip.py``.

SDXL's conditioning contract (matching sgm/ComfyUI):
``context = concat(L.penultimate[768], G.penultimate[1280]) = 2048``,
``pooled = G.final EOT @ text_projection = 1280``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

NEG_INF = -1e9


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    max_len: int = 77
    width: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    act: str = "quick_gelu"            # CLIP-L; CLIP-G uses "gelu"
    eot_token_id: int = 49407
    projection_dim: int = 0            # 0 = no text_projection head
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"             # conditioning runs once; keep f32

    @classmethod
    def clip_l(cls) -> "CLIPTextConfig":
        """openai/clip-vit-large-patch14 text tower (SD1.5 + SDXL ctx)."""
        return cls()

    @classmethod
    def clip_g(cls) -> "CLIPTextConfig":
        """OpenCLIP bigG-14 text tower (SDXL's second encoder)."""
        return cls(width=1280, layers=32, heads=20, intermediate=5120,
                   act="gelu", projection_dim=1280)

    @classmethod
    def tiny(cls, **kw) -> "CLIPTextConfig":
        base = dict(vocab_size=128, max_len=16, width=32, layers=2, heads=2,
                    intermediate=64, eot_token_id=127)
        base.update(kw)
        return cls(**base)


def quick_gelu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(1.702 * x)


class _CLIPAttention(nn.Module):
    config: CLIPTextConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.config
        head_dim = cfg.width // cfg.heads
        B, N, _ = x.shape
        q = nn.Dense(cfg.width, name="q_proj")(x)
        k = nn.Dense(cfg.width, name="k_proj")(x)
        v = nn.Dense(cfg.width, name="v_proj")(x)
        q = q.reshape(B, N, cfg.heads, head_dim)
        k = k.reshape(B, N, cfg.heads, head_dim)
        v = v.reshape(B, N, cfg.heads, head_dim)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (head_dim ** 0.5)
        s = s + mask[None, None]
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, N, cfg.width)
        return nn.Dense(cfg.width, name="out_proj")(out)


class _CLIPLayer(nn.Module):
    config: CLIPTextConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.config
        # HF/OpenCLIP "gelu" is the exact erf form (flax defaults to tanh)
        act = quick_gelu if cfg.act == "quick_gelu" else (
            lambda x: nn.gelu(x, approximate=False))
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln1")(x)
        x = x + _CLIPAttention(cfg, name="attn")(h, mask)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="ln2")(x)
        h = nn.Dense(cfg.intermediate, name="fc1")(h)
        h = nn.Dense(cfg.width, name="fc2")(act(h))
        return x + h


class CLIPTextTransformer(nn.Module):
    """Returns every view SD-family conditioning needs in one pass."""

    config: CLIPTextConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> dict[str, jax.Array]:
        cfg = self.config
        B, N = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.width, name="tok_emb")(tokens)
        pos = self.param("pos_emb", nn.initializers.normal(0.01),
                         (cfg.max_len, cfg.width))
        x = x + pos[None, :N]
        mask = jnp.triu(jnp.full((N, N), NEG_INF, x.dtype), k=1)

        penultimate = x
        for i in range(cfg.layers):
            if i == cfg.layers - 1:
                penultimate = x            # input of the last layer = output
            x = _CLIPLayer(cfg, name=f"layer_{i}")(x, mask)

        last = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="final_ln")(x)
        eot = jnp.argmax((tokens == cfg.eot_token_id).astype(jnp.int32), axis=1)
        pooled = last[jnp.arange(B), eot]
        out = {"last_hidden": last, "penultimate": penultimate,
               "pooled": pooled}
        if cfg.projection_dim:
            out["projected"] = nn.Dense(cfg.projection_dim, use_bias=False,
                                        name="text_projection")(pooled)
        return out


@dataclasses.dataclass
class CLIPTextModel:
    """Host wrapper: module + params."""

    config: CLIPTextConfig
    params: Optional[dict] = None

    def __post_init__(self):
        self.module = CLIPTextTransformer(self.config)

    def init(self, rng: jax.Array) -> "CLIPTextModel":
        toks = jnp.zeros((1, self.config.max_len), jnp.int32)
        self.params = jax.jit(self.module.init)(rng, toks)
        return self

    def __call__(self, tokens: jax.Array) -> dict[str, jax.Array]:
        from .layers import jit_apply

        return jit_apply(self, self.module)(self.params, tokens)


class SDXLTextStack:
    """The dual-encoder conditioning stack SDXL checkpoints ship.

    ``encode(tokens_l, tokens_g)`` →
    ``context [B,77,2048]`` (concat of both penultimates) and
    ``pooled [B,1280]`` (G's projected EOT) — matching sgm's
    ``GeneralConditioner`` wiring that the reference inherits via ComfyUI.
    """

    def __init__(self, clip_l: CLIPTextModel, clip_g: CLIPTextModel):
        assert clip_g.config.projection_dim, "CLIP-G needs text_projection"
        self.clip_l = clip_l
        self.clip_g = clip_g

    @classmethod
    def init_random(cls, rng: jax.Array, tiny: bool = False) -> "SDXLTextStack":
        k1, k2 = jax.random.split(rng)
        if tiny:
            cfg_l = CLIPTextConfig.tiny()
            cfg_g = CLIPTextConfig.tiny(width=48, heads=2, act="gelu",
                                        projection_dim=48)
        else:
            cfg_l, cfg_g = CLIPTextConfig.clip_l(), CLIPTextConfig.clip_g()
        return cls(CLIPTextModel(cfg_l).init(k1), CLIPTextModel(cfg_g).init(k2))

    def encode_tokens(self, tokens_l: jax.Array,
                      tokens_g: jax.Array) -> tuple[jax.Array, jax.Array]:
        out_l = self.clip_l(tokens_l)
        out_g = self.clip_g(tokens_g)
        context = jnp.concatenate(
            [out_l["penultimate"], out_g["penultimate"]], axis=-1)
        return context, out_g["projected"]


def validate_tokenizer_vocab(tok, cfg: CLIPTextConfig, name: str) -> None:
    """Refuse a CDT_TOKENIZER_DIR vocab that does not match a tower's
    config: a mismatch would not fail loudly downstream — out-of-range ids
    CLAMP in ``nn.Embed`` and a wrong EOT id silently pools position 0."""
    if tok.eot_id != cfg.eot_token_id or len(tok.vocab) > cfg.vocab_size:
        raise ValueError(
            f"CDT_TOKENIZER_DIR vocab does not match the {name} tower: "
            f"vocab has {len(tok.vocab)} entries with EOT id {tok.eot_id}, "
            f"config expects vocab_size<={cfg.vocab_size} / "
            f"eot_token_id={cfg.eot_token_id}")


def _count_hash_tokenization(tower: str) -> None:
    """Export the hash-fallback usage as telemetry: the boot-time warning
    is one log line on one host, but fleet-wide conditioning degradation
    must be visible in ``/distributed/metrics``
    (``cdt_hash_tokenization_total{tower}``)."""
    try:
        from .. import telemetry
        from ..telemetry import metrics as _tm

        if telemetry.enabled():
            _tm.HASH_TOKENIZATION.labels(tower=tower).inc()
    except Exception:  # noqa: BLE001 — telemetry is never load-bearing
        pass


def tokenize_ids(texts, tok, cfg, pad_id: int, tower: str = "clip",
                 count: bool = True) -> jax.Array:
    """Strings → [B, max_len] int32 ids: real BPE when a tokenizer is
    loaded, deterministic hash fallback (correct SOT/EOT framing so EOT
    pooling works) otherwise. ``count=False`` skips the degradation
    counter — key-signature tokenization must not double-count the
    encode that follows it."""
    if tok is not None:
        return jnp.asarray([tok.encode(t) for t in texts], jnp.int32)
    if count:
        _count_hash_tokenization(tower)
    import hashlib

    def fallback(text: str) -> list[int]:
        ids = []
        for w in text.lower().split():
            h = hashlib.blake2s(w.encode(), digest_size=4).digest()
            ids.append(int.from_bytes(h, "little")
                       % (cfg.vocab_size - 2) + 1)
        ids = ids[: cfg.max_len - 2]
        out = [0] + ids + [cfg.eot_token_id]
        return out + [pad_id] * (cfg.max_len - len(out))
    return jnp.asarray([fallback(t) for t in texts], jnp.int32)


class CLIPConditioner:
    """``TextEncoder``-compatible adapter (strings → context, pooled) over
    the weight-faithful CLIP stack, so graph nodes (``CLIPTextEncode``)
    work unchanged whichever encoder a bundle carries.

    Tokenizers come from ``CDT_TOKENIZER_DIR`` (standard vocab.json +
    merges.txt). Without one, a deterministic hash fallback keeps the
    stack runnable (correct SOT/EOT framing so pooling works) — outputs
    are then *not* meaningful text conditioning, and a warning says so.
    """

    def __init__(self, stack, kind: str = "sdxl", tok_l=None, tok_g=None):
        from ..utils.logging import log
        from .tokenizer import load_sd_tokenizers

        self.stack = stack
        self.kind = kind
        if kind == "sdxl" and (tok_l is None) != (tok_g is None):
            # a single explicit tokenizer would crash vocab validation on
            # the None twin (advisor r05) — require the pair, loudly
            raise ValueError(
                "CLIPConditioner(kind='sdxl') needs both tok_l and tok_g "
                "(or neither, to auto-load from CDT_TOKENIZER_DIR); got "
                f"only {'tok_l' if tok_g is None else 'tok_g'}")
        if tok_l is None and tok_g is None:
            # tokenize each tower to ITS context length — the position
            # tables only cover cfg.max_len, so a 77-padded sequence would
            # not even shape-check against a shorter tower (e.g. the tiny
            # test configs at max_len=16)
            from .tokenizer import CLIPBPETokenizer

            cfg_l = stack.clip_l.config if kind == "sdxl" else stack.config
            tok_l, _ = load_sd_tokenizers(max_len=cfg_l.max_len)
            if kind == "sdxl" and tok_l is not None:
                tok_g = CLIPBPETokenizer.from_env(
                    max_len=stack.clip_g.config.max_len, pad_token_id=0)
        self.tok_l, self.tok_g = tok_l, tok_g
        if self.tok_l is not None:
            towers = [("clip_l", self.tok_l,
                       stack.clip_l.config if kind == "sdxl" else stack.config)]
            if kind == "sdxl":
                towers.append(("clip_g", self.tok_g, stack.clip_g.config))
            for name, tok, cfg in towers:
                if tok is None:
                    # env-derived asymmetry (vocab present for one tower
                    # only): that tower falls back to hash tokenization —
                    # say so instead of crashing on None.eot_id
                    log(f"WARNING: no tokenizer for the {name} tower; "
                        "it falls back to hash tokenization")
                    continue
                validate_tokenizer_vocab(tok, cfg, name)
        if self.tok_l is None:
            log("WARNING: no CLIP vocab at CDT_TOKENIZER_DIR — text is "
                "hash-tokenized; conditioning will not reflect the prompt")

    def _ids(self, texts, tok, cfg, pad_id: int, tower: str):
        return tokenize_ids(texts, tok, cfg, pad_id, tower=tower)

    def token_signature(self, texts) -> tuple[list, str]:
        """(token ids per tower, real-vs-hash mode) — the conditioning
        cache's key material (``cluster/cache/conditioning.py``). Keying
        on the MODE is load-bearing: a worker whose vocab failed to load
        computes different keys than a healthy one, so its degraded
        embeddings can never poison the shared tier."""
        texts = [str(t) for t in texts]
        if self.kind == "sdxl":
            l_cfg = self.stack.clip_l.config
            g_cfg = self.stack.clip_g.config
            sig = [
                tokenize_ids(texts, self.tok_l, l_cfg, l_cfg.eot_token_id,
                             count=False).tolist(),
                tokenize_ids(texts, self.tok_g, g_cfg, 0,
                             count=False).tolist(),
            ]
            mode = (f"l={'bpe' if self.tok_l is not None else 'hash'},"
                    f"g={'bpe' if self.tok_g is not None else 'hash'}")
            return sig, mode
        cfg = self.stack.config
        sig = [tokenize_ids(texts, self.tok_l, cfg, cfg.eot_token_id,
                            count=False).tolist()]
        return sig, f"l={'bpe' if self.tok_l is not None else 'hash'}"

    @property
    def tokenization_mode(self) -> str:
        """Degradation summary for the result-cache key: "bpe" when every
        tower has a real tokenizer, "hash" otherwise."""
        toks = [self.tok_l] + ([self.tok_g] if self.kind == "sdxl" else [])
        return "bpe" if all(t is not None for t in toks) else "hash"

    def encode(self, texts) -> tuple[jax.Array, jax.Array]:
        texts = [str(t) for t in texts]
        if self.kind == "sdxl":
            l_cfg = self.stack.clip_l.config
            g_cfg = self.stack.clip_g.config
            toks_l = self._ids(texts, self.tok_l, l_cfg, l_cfg.eot_token_id,
                               tower="clip_l")
            toks_g = self._ids(texts, self.tok_g, g_cfg, 0, tower="clip_g")
            return self.stack.encode_tokens(toks_l, toks_g)
        cfg = self.stack.config
        toks = self._ids(texts, self.tok_l, cfg, cfg.eot_token_id,
                         tower="clip_l")
        out = self.stack(toks)
        # SD1.5 convention: final hidden states + EOT pooled
        return out["last_hidden"], out["pooled"]
