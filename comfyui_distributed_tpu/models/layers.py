"""Shared building blocks for the model zoo.

Design notes (TPU):
- compute in ``bfloat16`` (param storage ``float32``): MXU native dtype;
- GroupNorm in float32 for numerical stability, cast back after;
- attention uses ``jax.nn.dot_product_attention`` so XLA picks the fused
  flash-style lowering;
- all shapes static; no python control flow depends on values.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


def jit_apply(owner, module, attr: str = "_apply", **jit_kwargs):
    """Lazily-jitted ``module.apply`` cached on ``owner`` under ``attr``.

    Params stay an ARGUMENT of the jitted function (never a closure
    constant) and eager per-op dispatch — brutal over a tunneled
    accelerator — is replaced by one compiled program. Shared by every
    encoder/VAE wrapper."""
    fn = getattr(owner, attr, None)
    if fn is None:
        fn = jax.jit(module.apply, **jit_kwargs)
        setattr(owner, attr, fn)
    return fn


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal timestep embedding, [B] -> [B, dim] (DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class GroupNorm32(nn.Module):
    """GroupNorm computed in float32, output cast to the input dtype."""

    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig = x.dtype
        x = x.astype(jnp.float32)
        groups = min(self.num_groups, x.shape[-1])
        x = nn.GroupNorm(num_groups=groups, epsilon=self.epsilon, dtype=jnp.float32)(x)
        return x.astype(orig)


class TimestepEmbedSequential(nn.Module):
    """Apply a list of blocks, feeding time/context only to those that take it."""

    blocks: tuple

    def __call__(self, x, emb=None, context=None):
        for block in self.blocks:
            if isinstance(block, ResBlock):
                x = block(x, emb)
            elif isinstance(block, SpatialTransformer):
                x = block(x, context)
            else:
                x = block(x)
        return x


class ResBlock(nn.Module):
    """GN→SiLU→conv, time-embedding shift, GN→SiLU→conv, residual.

    Matches the standard latent-diffusion ResBlock topology so published
    UNet weights can be mapped onto it.
    """

    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, emb: jax.Array) -> jax.Array:
        h = GroupNorm32()(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype, name="conv1")(h)
        emb_out = nn.Dense(self.out_channels, dtype=self.dtype, name="time_proj")(nn.silu(emb))
        h = h + emb_out[:, None, None, :]
        h = GroupNorm32()(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype, name="skip")(x)
        return x + h


class _ProjKernel(nn.Module):
    """Bare [in, out] projection weight under a Dense-compatible param
    path (``<name>/kernel``, lecun-normal init) — the fused attention
    tier consumes the raw matrix instead of applying the layer, so the
    activations never round-trip HBM, while checkpoints keep loading
    into the exact tree ``nn.Dense(use_bias=False)`` would own."""

    features: int

    @nn.compact
    def __call__(self, in_features: int) -> jax.Array:
        return self.param("kernel", nn.initializers.lecun_normal(),
                          (in_features, self.features))


class Attention(nn.Module):
    """Multi-head attention over [B, N, C] with optional cross context.

    Self-attention sites (no context) are fusable: projection feeds
    attention with nothing in between, so when the kernel dispatcher
    (``ops/attention.select_kernel`` — tuning table > env > defaults)
    picks the fused tier, the QKV matmuls fold into the flash grid
    (``ops/flash_attention.fused_qkv_attention``) and q/k/v never
    materialize in HBM. Either branch owns the identical param tree."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array] = None) -> jax.Array:
        ctx = x if context is None else context
        inner = self.num_heads * self.head_dim
        B, N, C = x.shape
        M = ctx.shape[1]
        from ..ops.attention import select_kernel

        choice = select_kernel(int(N), int(M), self.num_heads,
                               self.head_dim, dtype=self.dtype,
                               fusable=context is None)
        use_fused = choice.tier == "fused" and context is None
        if use_fused:
            # the table/policy validated fused feasibility assuming
            # C == H·D (true for every zoo config); this site's REAL
            # channel width may differ — re-check with it so an
            # infeasible width degrades to the dense path instead of
            # raising mid-forward
            from ..ops.autotune import itemsize_of
            from ..ops.flash_attention import (_DEFAULT_BLOCK_K,
                                               _DEFAULT_BLOCK_Q,
                                               _fused_feasible)

            use_fused = _fused_feasible(
                int(C), self.num_heads, self.head_dim,
                choice.block_q or _DEFAULT_BLOCK_Q,
                choice.block_k or _DEFAULT_BLOCK_K,
                itemsize_of(self.dtype)) is not None
        if use_fused:
            from ..ops.flash_attention import fused_qkv_attention

            wq = _ProjKernel(inner, name="to_q")(C)
            wk = _ProjKernel(inner, name="to_k")(C)
            wv = _ProjKernel(inner, name="to_v")(C)
            out = fused_qkv_attention(
                x.astype(self.dtype), wq.astype(self.dtype),
                wk.astype(self.dtype), wv.astype(self.dtype),
                self.num_heads, block_q=choice.block_q,
                block_k=choice.block_k)
        else:
            q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
            k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(ctx)
            v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(ctx)
            q = q.reshape(B, N, self.num_heads, self.head_dim)
            k = k.reshape(B, M, self.num_heads, self.head_dim)
            v = v.reshape(B, M, self.num_heads, self.head_dim)
            from ..ops.attention import full_attention

            out = full_attention(q, k, v)
        out = out.reshape(B, N, inner)
        return nn.Dense(x.shape[-1], dtype=self.dtype, name="to_out")(out)


class GEGLU(nn.Module):
    mult: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dim = x.shape[-1]
        h = nn.Dense(dim * self.mult * 2, dtype=self.dtype, name="proj_in")(x)
        h, gate = jnp.split(h, 2, axis=-1)
        # LDM's GEGLU uses exact (erf) gelu; flax defaults to tanh approx
        h = h * nn.gelu(gate, approximate=False)
        return nn.Dense(dim, dtype=self.dtype, name="proj_out")(h)


class TransformerBlock(nn.Module):
    """LN→self-attn, LN→cross-attn, LN→GEGLU-FF, all residual (LDM layout)."""

    num_heads: int
    head_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array]) -> jax.Array:
        x = x + Attention(self.num_heads, self.head_dim, self.dtype, name="attn1")(
            nn.LayerNorm(dtype=self.dtype)(x)
        )
        x = x + Attention(self.num_heads, self.head_dim, self.dtype, name="attn2")(
            nn.LayerNorm(dtype=self.dtype)(x), context
        )
        x = x + GEGLU(dtype=self.dtype, name="ff")(nn.LayerNorm(dtype=self.dtype)(x))
        return x


class SpatialTransformer(nn.Module):
    """Project [B,H,W,C] to tokens, run transformer blocks, project back."""

    num_heads: int
    depth: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array]) -> jax.Array:
        B, H, W, C = x.shape
        head_dim = C // self.num_heads
        h = GroupNorm32()(x)
        h = nn.Dense(C, dtype=self.dtype, name="proj_in")(h.reshape(B, H * W, C))
        for i in range(self.depth):
            h = TransformerBlock(self.num_heads, head_dim, self.dtype, name=f"block_{i}")(
                h, context
            )
        h = nn.Dense(C, dtype=self.dtype, name="proj_out")(h)
        return x + h.reshape(B, H, W, C)


class Downsample(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return nn.Conv(self.out_channels, (3, 3), strides=2, padding=1, dtype=self.dtype)(x)


class Upsample(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, H, W, C = x.shape
        x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
        return nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype)(x)
