"""SDXL-class latent UNet in flax.

Architecture follows the latent-diffusion UNet family (what the reference
drives through ComfyUI's ``comfy.samplers``/``common_ksampler`` — SURVEY
"external substrate") with SDXL's layout expressible via config: per-level
transformer depth, cross-attention dim, optional label/ADM embedding for
SDXL micro-conditioning.

Presets: ``UNetConfig.sdxl()`` reproduces SDXL-base's shape
(320·[1,2,4], transformer depths [0,2,10], ctx 2048, adm 2816);
``UNetConfig.tiny()`` is a 2-level toy for tests and CPU dry-runs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from .layers import (
    GroupNorm32,
    ResBlock,
    SpatialTransformer,
    Downsample,
    Upsample,
    timestep_embedding,
)


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    channel_mult: tuple[int, ...] = (1, 2, 4)
    num_res_blocks: int = 2
    # transformer depth per resolution level; 0 = conv-only level
    transformer_depth: tuple[int, ...] = (0, 2, 10)
    num_heads: int = -1            # -1: derive from head_dim
    head_dim: int = 64
    context_dim: int = 2048
    adm_in_channels: int = 0       # SDXL: 2816 (pooled text + size conds)
    dtype: str = "bfloat16"
    # activation rematerialization: recompute block activations in the
    # backward/later passes instead of keeping them in HBM — trades FLOPs
    # for memory headroom on big latents (CDT_REMAT=1 flips the presets)
    remat: bool = False

    @classmethod
    def sdxl(cls) -> "UNetConfig":
        from ..utils import constants

        # 2816 = 1280 pooled CLIP-G + 6×256 Fourier size/crop conds —
        # without label_emb a real SDXL checkpoint cannot convert
        # (label_emb.* keys would be unconsumed) and micro-conds are lost
        return cls(remat=constants.REMAT, adm_in_channels=2816)

    @classmethod
    def sd15(cls) -> "UNetConfig":
        from ..utils import constants

        return cls(
            remat=constants.REMAT,
            channel_mult=(1, 2, 4, 4),
            transformer_depth=(1, 1, 1, 0),
            context_dim=768,
            head_dim=-1,
            num_heads=8,
        )

    @classmethod
    def tiny(cls, dtype: str = "bfloat16") -> "UNetConfig":
        """2-level toy UNet for tests: ~0.5M params, still exercises every
        block type (res, self/cross attention, up/down, skip concat)."""
        return cls(
            model_channels=32,
            channel_mult=(1, 2),
            num_res_blocks=1,
            transformer_depth=(0, 1),
            context_dim=32,
            head_dim=16,
            adm_in_channels=8,
            dtype=dtype,
        )

    @property
    def jnp_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    def heads_for(self, channels: int) -> int:
        if self.num_heads > 0:
            return self.num_heads
        return max(1, channels // self.head_dim)


class UNet2D(nn.Module):
    """Latent UNet: x[B,H,W,C_in], t[B], context[B,N,ctx], y[B,adm] → eps."""

    config: UNetConfig

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        t: jax.Array,
        context: Optional[jax.Array] = None,
        y: Optional[jax.Array] = None,
        control: Optional[tuple] = None,
    ) -> jax.Array:
        """``control``: optional ``(down_residuals, mid_residual)`` from a
        ControlNet (``models/controlnet.py``) — one residual per skip in
        push order, added when each skip is popped, plus one added to the
        middle state (LDM ``cldm`` semantics)."""
        cfg = self.config
        dt = cfg.jnp_dtype
        time_dim = cfg.model_channels * 4

        emb = timestep_embedding(t, cfg.model_channels)
        emb = nn.Dense(time_dim, dtype=dt, name="time_1")(emb.astype(dt))
        emb = nn.Dense(time_dim, dtype=dt, name="time_2")(nn.silu(emb))
        if cfg.adm_in_channels:
            assert y is not None, "config.adm_in_channels set but y not given"
            yemb = nn.Dense(time_dim, dtype=dt, name="label_1")(y.astype(dt))
            yemb = nn.Dense(time_dim, dtype=dt, name="label_2")(nn.silu(yemb))
            emb = emb + yemb

        x = x.astype(dt)
        if context is not None:
            context = context.astype(dt)

        Res = nn.remat(ResBlock) if cfg.remat else ResBlock
        Attn = nn.remat(SpatialTransformer) if cfg.remat else SpatialTransformer

        h = nn.Conv(cfg.model_channels, (3, 3), padding=1, dtype=dt, name="conv_in")(x)
        skips = [h]

        # --- down path ---
        for level, mult in enumerate(cfg.channel_mult):
            ch = cfg.model_channels * mult
            for i in range(cfg.num_res_blocks):
                h = Res(ch, dt, name=f"down_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level]:
                    h = Attn(
                        cfg.heads_for(ch),
                        cfg.transformer_depth[level],
                        dt,
                        name=f"down_{level}_attn_{i}",
                    )(h, context)
                skips.append(h)
            if level < len(cfg.channel_mult) - 1:
                h = Downsample(ch, dt, name=f"down_{level}_ds")(h)
                skips.append(h)

        # --- middle ---
        mid_ch = cfg.model_channels * cfg.channel_mult[-1]
        h = Res(mid_ch, dt, name="mid_res_1")(h, emb)
        if cfg.transformer_depth[-1]:
            h = Attn(
                cfg.heads_for(mid_ch), cfg.transformer_depth[-1], dt, name="mid_attn"
            )(h, context)
        h = Res(mid_ch, dt, name="mid_res_2")(h, emb)

        if control is not None:
            down_res, mid_res = control
            assert len(down_res) == len(skips), (
                f"control carries {len(down_res)} skip residuals, "
                f"UNet has {len(skips)}")
            h = h + mid_res.astype(h.dtype)
            skips = [s + r.astype(s.dtype) for s, r in zip(skips, down_res)]

        # --- up path ---
        for level in reversed(range(len(cfg.channel_mult))):
            ch = cfg.model_channels * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = Res(ch, dt, name=f"up_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level]:
                    h = Attn(
                        cfg.heads_for(ch),
                        cfg.transformer_depth[level],
                        dt,
                        name=f"up_{level}_attn_{i}",
                    )(h, context)
            if level > 0:
                h = Upsample(ch, dt, name=f"up_{level}_us")(h)

        h = GroupNorm32(name="norm_out")(h)
        h = nn.silu(h)
        h = nn.Conv(
            cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32, name="conv_out"
        )(h.astype(jnp.float32))
        return h


def init_unet(
    config: UNetConfig,
    rng: jax.Array,
    sample_shape: tuple[int, int, int] = (64, 64, 4),
    context_len: int = 77,
    abstract: bool = False,
    param_dtype=None,
):
    """Initialize params with a canonical dummy batch; returns (module, params).

    ``abstract=True`` returns a ShapeDtypeStruct tree (conversion template
    — no multi-GB random init when every leaf is about to be replaced).
    ``param_dtype`` (e.g. ``jnp.bfloat16``) casts float params INSIDE the
    init program: XLA fuses the cast per buffer, so peak device memory is
    the cast tree plus one layer — never the full fp32 tree (an SDXL fp32
    init plus a post-hoc cast transiently needs 15.6 GB; fused it's
    ~5.5 GB, and inference weights want bf16 residency anyway)."""
    model = UNet2D(config)
    H, W, C = sample_shape
    x = jnp.zeros((1, H, W, C), jnp.float32)
    t = jnp.zeros((1,), jnp.float32)
    ctx = jnp.zeros((1, context_len, config.context_dim), jnp.float32)
    y = jnp.zeros((1, config.adm_in_channels), jnp.float32) if config.adm_in_channels else None
    # jit the init: eager tracing dispatches each initializer op through a
    # separate tiny XLA executable (~tens of seconds for a full UNet even
    # at toy sizes); one compiled program is an order of magnitude faster
    init_fn = casting_init(model.init, param_dtype)
    if abstract:
        params = jax.eval_shape(init_fn, rng, x, t, ctx, y)
    else:
        params = jax.jit(init_fn)(rng, x, t, ctx, y)
    return model, params


def _cast_float_params(params, dtype):
    """Cast float leaves to ``dtype`` (shared by the init helpers)."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, params)


def casting_init(init_fn, param_dtype):
    """Wrap a flax ``init`` so float params are cast to ``param_dtype``
    inside the same compiled program (fused, per-buffer — the full-size
    fp32 tree never materializes). No-op when ``param_dtype`` is None.
    Shared by init_unet / init_dit / init_wan."""
    if param_dtype is None:
        return init_fn
    return lambda *a: _cast_float_params(init_fn(*a), param_dtype)
