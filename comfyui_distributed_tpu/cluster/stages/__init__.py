"""Disaggregated stage-split serving (ROADMAP item 3, docs/stages.md).

The fused serving path runs every front-door group end-to-end on the one
graph-exec thread: prefix (checkpoint load + text encode), the
microbatched sampler program, VAE decode, suffix. Only the sampler loop
drives the mesh at full MFU — encode and decode are cheap, bursty, and
batchable (``docs/pp-memo.md``), yet they serialize with the denoise
program and hold its queue slot.

This package splits the pipeline into three independently scaled stage
pools behind the existing front door:

- **encode pool** (N host threads): each member's graph prefix — model
  resolve, text encode through the PR 8 conditioning cache (each unique
  prompt encodes once fleet-wide), sampler-input resolution, and the
  completed-result cache probe. Pure host + encoder work.
- **denoise pool** (exactly ONE worker — it owns the mesh): the
  microbatched *latent* program
  (``diffusion/pipeline.latent_microbatch_fn`` — the fused program
  stopped at ``x0``, same unrolled per-request subgraphs). The prompt
  queue's slot frees when this stage finishes, so the next group's
  denoise starts while the previous group decodes.
- **decode pool** (M host threads): coalesces latents across concurrent
  requests into shape buckets and decodes each bucket as ONE batched
  VAE program (``decode_latents``), then runs each member's suffix.

Stage handoffs are :class:`~.latents.LatentHandoff`\\ s — the checksummed
npz wire format (``diffusion/checkpoint.py`` contract). In-process the
decode pool reads the denoise program's device array directly; the
transfer (device→host materialization, plus the full wire round trip
under ``CDT_STAGE_WIRE=1``) happens on the decode worker WHILE the
denoise pool dispatches its next program — the T3-style
compute/transfer overlap (PAPERS.md).

Bit-identity: every stage boundary is a pure program split on
already-materialized values (the PR 14 seg/fin precedent), so the
staged path's outputs are bit-identical to the fused path's — proven,
not approximate (``tests/test_stages_equivalence.py``). ``CDT_STAGES=0``
removes the subsystem and restores the fused path verbatim.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from ... import telemetry
from ...telemetry import metrics as _tm
from ...utils import constants
from ...utils.logging import debug_log, log
from ...lint.lockorder import tracked_lock
from .latents import LatentHandoff, LatentWireError
from .pool import StagePool, StageWorkerDeath

__all__ = ["StageManager", "StagePool", "StageWorkerDeath",
           "LatentHandoff", "LatentWireError", "build_stages",
           "stages_enabled"]


def stages_enabled() -> bool:
    return constants.STAGES.get()


class _EncodeWork:
    __slots__ = ("ticket", "member", "redispatch", "done")

    def __init__(self, ticket, member):
        self.ticket = ticket
        self.member = member
        self.redispatch = 0
        self.done = False

    def fail(self, manager, status: str, error: str = "") -> None:
        self.done = True
        entry = {"status": status}
        if error:
            entry["error"] = error
        manager._complete(self.ticket, self.member, entry)
        # a failed encode item still counts toward the group's encode
        # barrier — without this the denoise stage never dispatches and
        # the queue consumer awaits denoise_done forever
        manager._after_encode(self.ticket)


class _DenoiseWork:
    __slots__ = ("ticket", "redispatch", "done")

    def __init__(self, ticket):
        self.ticket = ticket
        self.redispatch = 0
        self.done = False

    def fail(self, manager, status: str, error: str = "") -> None:
        self.done = True
        for p in self.ticket.take_ready():
            entry = {"status": status}
            if error:
                entry["error"] = error
            manager._complete(self.ticket, p.member, entry)
        self.ticket.resolve_denoise()


class _DecodeWork:
    __slots__ = ("ticket", "p", "latents", "np_latents", "sampler_batch",
                 "redispatch", "done")

    def __init__(self, ticket, prepared, latents, sampler_batch: int):
        self.ticket = ticket
        self.p = prepared
        self.latents = latents          # device array until transferred
        self.np_latents = None
        self.sampler_batch = sampler_batch
        self.redispatch = 0
        self.done = False

    def bucket_key(self) -> tuple:
        from ...diffusion.pipeline import mesh_cache_key

        return (id(self.p.pipeline), mesh_cache_key(self.p.mesh),
                tuple(self.latents.shape))

    def handoff(self) -> LatentHandoff:
        p = self.p
        return LatentHandoff(
            prompt_id=p.member.prompt_id,
            latents=np.asarray(self.latents),
            meta={"model": getattr(getattr(p.model, "preset", None),
                                   "name", None),
                  "height": p.spec.height, "width": p.spec.width,
                  "steps": p.spec.steps, "seed": p.seed,
                  "fingerprint": p.member.fingerprint})

    def fail(self, manager, status: str, error: str = "") -> None:
        self.done = True
        entry = {"status": status}
        if error:
            entry["error"] = error
        manager._complete(self.ticket, self.p.member, entry)


class _GroupTicket:
    """One front-door batch job moving through the stages."""

    def __init__(self, manager, job, members, sampler_node_ids, context,
                 loop, denoise_done, record):
        self.manager = manager
        self.job = job
        self.members = list(members)
        self.sampler_node_ids = dict(sampler_node_ids)
        self.context = context
        self.loop = loop
        self.denoise_done = denoise_done
        self.record = record
        self.pending = len(self.members)
        self.encode_left = len(self.members)
        self.ready: list = []
        self._lock = tracked_lock("stage.ticket")
        self._denoise_resolved = False

    def add_ready(self, prepared) -> None:
        with self._lock:
            self.ready.append(prepared)

    def take_ready(self) -> list:
        with self._lock:
            out, self.ready = self.ready, []
        return out

    def member_done(self) -> bool:
        """Decrement the outstanding-member count; True when this was
        the last one (the runtime observes end-to-end duration then)."""
        with self._lock:
            self.pending -= 1
            return self.pending <= 0

    def encode_done(self) -> "tuple[bool, bool]":
        with self._lock:
            self.encode_left -= 1
            return self.encode_left <= 0, bool(self.ready)

    def resolve_denoise(self) -> None:
        """Free the mesh: tell the runtime the denoise stage is done
        with this group so the queue dispatches the next job while the
        decode pool finishes this one. Idempotent."""
        with self._lock:
            if self._denoise_resolved:
                return
            self._denoise_resolved = True
        self.manager._marshal(self.loop, _resolve, self.denoise_done)


def _resolve(fut) -> None:
    if not fut.done():
        fut.set_result(None)


class StageManager:
    """The three stage pools bound to one controller.

    Built by the controller under ``CDT_STAGES=1`` and attached to the
    prompt queue (``queue.stages``); the queue's consumer routes batch
    jobs here and awaits only the denoise stage before freeing its
    slot. Pools are per-controller, threads are daemons, and nothing
    starts until the first staged group arrives."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.base_encode = max(1, constants.STAGE_ENCODE_WORKERS.get())
        self.base_decode = max(1, constants.STAGE_DECODE_WORKERS.get())
        self.encode = StagePool("encode", self.base_encode,
                                self._run_encode, steal=self._pick_steal,
                                redispatch=self._redispatch_encode,
                                clock=clock)
        # exactly one denoise worker: one mesh, one program at a time —
        # the stage split raises the work per program and what runs
        # AROUND the mesh, never the number of concurrent mesh programs
        self.denoise = StagePool("denoise", 1, self._run_denoise,
                                 clock=clock)
        self.decode = StagePool(
            "decode", self.base_decode, self._run_decode,
            # duck-typed so tests can drive the pool with fake items
            batch_key=lambda item: item.bucket_key(),
            max_batch=constants.STAGE_DECODE_BATCH.get(),
            window_s=constants.STAGE_DECODE_WINDOW_MS.get() / 1000.0,
            steal=self._pick_steal,
            redispatch=self._redispatch_decode, clock=clock)
        # chaos hook: called with the picked decode batch right after
        # transfer, while the worker "holds" the latents (may raise
        # StageWorkerDeath — tests/test_stages.py)
        self._death_hook: Optional[Callable[[list], None]] = None
        self.counts = {"groups": 0, "members": 0, "cache_hits": 0,
                       "fallbacks": 0, "redispatched": 0}
        self._counts_lock = tracked_lock("stage.counts")

    # --- the front half: runtime integration --------------------------------

    def eligible(self, job) -> bool:
        """Only front-door batch jobs ride the stages: solo prompts keep
        the fused path (preemption, progress streaming, ControlNet).
        ``cache: "near"`` members also keep the fused path — the near
        tier's donor/serve machinery (cluster/cache/fleet.py) rides the
        fused preemptible sampler, which has no stage-split analogue."""
        group = getattr(job, "group", None)
        if group is None:
            return False
        return not any(getattr(m, "cache_mode", "use") == "near"
                       for m in group)

    def submit_group(self, job, members, sampler_node_ids, context, loop,
                     denoise_done, record) -> None:
        """Enter one batch job into the encode pool. ``record(member,
        entry, last)`` is invoked ON ``loop`` as each member reaches a
        terminal state; ``denoise_done`` resolves when the mesh is free
        for the next job."""
        ticket = _GroupTicket(self, job, members, sampler_node_ids,
                              context, loop, denoise_done, record)
        with self._counts_lock:
            self.counts["groups"] += 1
            self.counts["members"] += len(ticket.members)
        self.rebalance()
        for m in ticket.members:
            self.encode.put(_EncodeWork(ticket, m))

    def depth(self) -> int:
        """Host-side stage backlog (encode + decode; the denoise queue
        is bounded by the prompt queue itself). Feeds the front door's
        admission depth so freeing queue slots at denoise-done cannot
        admit unbounded work that piles up in decode."""
        return self.encode.depth() + self.decode.depth()

    def depths(self) -> dict:
        return {"encode": self.encode.depth(),
                "denoise": self.denoise.depth(),
                "decode": self.decode.depth()}

    def overloaded(self) -> "str | None":
        """Stage name whose backlog exceeds CDT_STAGE_SHED_DEPTH (the
        load_smoke --stages assertion), or None."""
        shed = constants.STAGE_SHED_DEPTH.get()
        for name, d in self.depths().items():
            if d > shed:
                return name
        return None

    def stop(self) -> None:
        for pool in (self.encode, self.denoise, self.decode):
            for item in pool.stop():
                try:
                    item.fail(self, "interrupted")
                except Exception as e:  # noqa: BLE001 — shutdown barrier
                    debug_log(f"stages: drop at shutdown failed: {e!r}")

    # --- per-pool scaling ----------------------------------------------------

    def rebalance(self) -> None:
        """Size each host-side pool on ITS OWN queue depth — the
        per-pool half of the autoscaler split (the fleet autoscaler
        sizes chips on denoise-facing signals only; docs/stages.md).
        Deterministic: grow by one past CDT_STAGE_SCALE_DEPTH items per
        worker, shrink back to the configured base when idle."""
        per = constants.STAGE_SCALE_DEPTH.get()
        ceiling = constants.STAGE_MAX_WORKERS.get()
        for pool, base in ((self.encode, self.base_encode),
                           (self.decode, self.base_decode)):
            depth = pool.depth()
            if depth > per * pool.workers and pool.workers < ceiling:
                log(f"stages: {pool.name} pool {pool.workers} -> "
                    f"{pool.workers + 1} (depth {depth})")
                pool.resize(pool.workers + 1)
            elif depth == 0 and pool.busy == 0 and pool.workers > base:
                pool.resize(pool.workers - 1)

    def _pick_steal(self, pool) -> Optional[StagePool]:
        """Cross-stage steal victim for an idle host-side worker: the
        deepest sibling stage queue (the PR 7 most-starved-first idiom
        across stages). The denoise pool is never a victim or a thief —
        it owns the mesh."""
        if not constants.STAGE_STEAL.get():
            return None
        sibs = [p for p in (self.encode, self.decode) if p is not pool]
        victim = max(sibs, key=lambda p: p.depth(), default=None)
        if victim is None or victim.depth() == 0:
            return None
        return victim

    # --- encode stage --------------------------------------------------------

    def _run_encode(self, works: list) -> None:
        for w in works:
            self._encode_member(w)
            w.done = True

    def _encode_member(self, w: _EncodeWork) -> None:
        from ..frontdoor.microbatch import _prepare, _serve_cached

        ticket, member = w.ticket, w.member
        cache = ticket.context.get("content_cache")
        # the WHOLE member (prefix, cache probe, cached suffix) runs
        # inside one isolation barrier and the encode barrier advances
        # in a finally: an escaping exception here would otherwise be
        # swallowed by the pool's runner barrier with the group's
        # denoise_done future never resolving — wedging the queue
        # consumer for the life of the process
        try:
            ev = ticket.context.get("interrupt_event")
            if ev is not None and ev.is_set():
                self._complete(ticket, member, {"status": "interrupted"})
                return
            p = _prepare(member, ticket.sampler_node_ids[member.prompt_id],
                         ticket.context)
            results: dict = {}
            if _serve_cached(p, cache, results):
                # completed-result tier answered in the ENCODE stage —
                # the request never touches the mesh at all. The probe
                # inside _serve_cached walks the full fleet ladder
                # (local memory → disk → ring owner), so a remote shard
                # hit also resolves here, before any pool hand-off.
                with self._counts_lock:
                    self.counts["cache_hits"] += 1
                self._complete(ticket, member, results[member.prompt_id])
                return
            if cache is not None and member.fingerprint is not None:
                cache.record_request(hit=False)
            ticket.add_ready(p)
        except InterruptedError:
            self._complete(ticket, member, {"status": "interrupted"})
        except Exception as e:  # noqa: BLE001 — member isolation barrier
            log(f"stages: encode failed for {member.prompt_id}: {e}")
            self._complete(ticket, member,
                           {"status": "error", "error": str(e)})
        finally:
            self._after_encode(ticket)

    def _after_encode(self, ticket: _GroupTicket) -> None:
        done, has_ready = ticket.encode_done()
        if not done:
            return
        if has_ready:
            self.denoise.put(_DenoiseWork(ticket))
        else:
            # every member answered (cache/error) without the mesh
            ticket.resolve_denoise()

    # --- denoise stage -------------------------------------------------------

    def _run_denoise(self, works: list) -> None:
        for w in works:
            try:
                self._denoise_ticket(w.ticket)
            finally:
                w.done = True
                w.ticket.resolve_denoise()

    def _denoise_ticket(self, ticket: _GroupTicket) -> None:
        prepared = ticket.take_ready()
        if not prepared:
            return
        # sub-group by runtime signature exactly like the fused path;
        # the staged lane additionally needs the latent entry points
        groups: dict[tuple, list] = {}
        singles: list = []
        for p in prepared:
            if p.stackable and hasattr(p.pipeline, "generate_latents") \
                    and hasattr(p.pipeline, "decode_latents"):
                groups.setdefault(p.signature(), []).append(p)
            else:
                singles.append(p)
        for p in singles:
            # non-stackable members (control conditioning, no mesh,
            # unsupported pipeline) run the fused solo path on the
            # denoise worker — they hold the mesh anyway
            if telemetry.enabled():
                _tm.BATCH_SIZE.observe(1)
            self._solo_member(ticket, p, batch_size=1)
        for sig, grp in groups.items():
            self._denoise_subgroup(ticket, grp)

    def _denoise_subgroup(self, ticket: _GroupTicket, grp: list) -> None:
        from ..residency import pinned_bundle

        lead = grp[0]
        try:
            with pinned_bundle(lead.model):
                lats = lead.pipeline.generate_latents(
                    lead.mesh, lead.spec,
                    seeds=[p.seed for p in grp],
                    contexts=[p.context for p in grp],
                    uncond_contexts=[p.uncond for p in grp],
                    ys=[p.y for p in grp], uys=[p.uy for p in grp],
                )
            if telemetry.enabled():
                _tm.BATCH_SIZE.observe(len(grp))
        except InterruptedError:
            for p in grp:
                self._complete(ticket, p.member,
                               {"status": "interrupted"})
            return
        except Exception as e:  # noqa: BLE001 — fall back, never lose jobs
            log(f"stages: latent microbatch of {len(grp)} failed ({e}); "
                f"falling back to fused solo execution")
            if telemetry.enabled():
                _tm.BATCH_FALLBACKS.inc()
            with self._counts_lock:
                self.counts["fallbacks"] += 1
            for p in grp:
                if telemetry.enabled():
                    _tm.BATCH_SIZE.observe(1)
                self._solo_member(ticket, p, batch_size=1)
            return
        from ..frontdoor.microbatch import _observe_group_shape

        _observe_group_shape(lead, len(grp))
        for p, lat in zip(grp, lats):
            # the handoff carries the LAZY device array: materialization
            # happens on the decode worker, overlapped with this pool's
            # next program (T3-style; docs/stages.md)
            self.decode.put(_DecodeWork(ticket, p, lat,
                                        sampler_batch=len(grp)))

    def _solo_member(self, ticket: _GroupTicket, p,
                     batch_size: int = 1) -> None:
        """The fused pass-through: the sampler node's own execute +
        suffix, byte-for-byte the solo queue path (shared helpers with
        the fused group executor)."""
        from ..frontdoor.microbatch import _fill_cache, _finish, _solo

        cache = ticket.context.get("content_cache")
        try:
            images = _solo(p)
            _fill_cache(p, cache, images)
            out_cache = _finish(p, images)
            self._complete(ticket, p.member,
                           {"status": "success", "outputs": out_cache,
                            "batch_size": batch_size})
        except InterruptedError:
            self._complete(ticket, p.member, {"status": "interrupted"})
        except Exception as e:  # noqa: BLE001 — member isolation barrier
            log(f"stages: solo member {p.member.prompt_id} failed: {e}")
            self._complete(ticket, p.member,
                           {"status": "error", "error": str(e)})

    # --- decode stage --------------------------------------------------------

    def _run_decode(self, works: list) -> None:
        live: list[_DecodeWork] = []
        for w in works:
            ev = w.ticket.context.get("interrupt_event")
            if ev is not None and ev.is_set():
                w.done = True
                self._complete(w.ticket, w.p.member,
                               {"status": "interrupted"})
            else:
                live.append(w)
        if not live:
            return
        ready: list[_DecodeWork] = []
        for w in live:
            # per-member transfer isolation: a wire-format failure
            # (checksum mismatch, unserializable meta under
            # CDT_STAGE_WIRE=1) must error THAT member terminally, not
            # strand the whole batch without history entries
            try:
                self._transfer(w)
            except Exception as e:  # noqa: BLE001 — member isolation
                log(f"stages: latent transfer failed for "
                    f"{w.p.member.prompt_id}: {e}")
                w.done = True
                self._complete(w.ticket, w.p.member,
                               {"status": "error", "error": str(e)})
            else:
                ready.append(w)
        live = ready
        if not live:
            return
        hook = self._death_hook
        if hook is not None:
            hook(live)              # chaos: may raise StageWorkerDeath
        lead = live[0].p
        from ..residency import pinned_bundle

        try:
            with pinned_bundle(lead.model):
                images = lead.pipeline.decode_latents(
                    lead.mesh, [w.np_latents for w in live],
                    per_device_batch=lead.spec.per_device_batch)
            if telemetry.enabled():
                _tm.DECODE_BATCH_SIZE.observe(len(live))
        except StageWorkerDeath:
            raise
        except InterruptedError:
            for w in live:
                w.done = True
                self._complete(w.ticket, w.p.member,
                               {"status": "interrupted"})
            return
        except Exception as e:  # noqa: BLE001 — fall back per item
            log(f"stages: batched decode of {len(live)} failed ({e}); "
                f"decoding solo")
            for w in live:
                self._decode_solo(w)
            return
        for w, img in zip(live, images):
            self._finish_member(w, img, decode_batch=len(live))

    def _transfer(self, w: _DecodeWork) -> None:
        """Materialize one handoff on the decode side. Under
        ``CDT_STAGE_WIRE=1`` the latent makes the full checksummed wire
        round trip (serialize → sha256 → parse → verify) — the
        cross-worker transport path, validated on every handoff."""
        if w.np_latents is not None:
            return
        # transfer telemetry only — never feeds the program
        t0 = time.perf_counter()
        if constants.STAGE_WIRE.get():
            arr = np.asarray(
                LatentHandoff.from_payload(w.handoff().to_payload())
                .latents)
        else:
            arr = np.asarray(w.latents)
        w.np_latents = arr
        if telemetry.enabled():
            _tm.LATENT_TRANSFER_BYTES.observe(arr.nbytes)
            _tm.LATENT_TRANSFER_SECONDS.observe(time.perf_counter() - t0)

    def _decode_solo(self, w: _DecodeWork) -> None:
        """Decode one latent in its own (batch-of-1) program — the
        fallback when a batched decode program fails; the member's
        admitted work must never be lost to batching."""
        from ..residency import pinned_bundle

        try:
            with pinned_bundle(w.p.model):
                images = w.p.pipeline.decode_latents(
                    w.p.mesh, [w.np_latents],
                    per_device_batch=w.p.spec.per_device_batch)
            if telemetry.enabled():
                _tm.DECODE_BATCH_SIZE.observe(1)
        except Exception as e:  # noqa: BLE001 — member isolation barrier
            log(f"stages: solo decode failed for "
                f"{w.p.member.prompt_id}: {e}")
            w.done = True
            self._complete(w.ticket, w.p.member,
                           {"status": "error", "error": str(e)})
            return
        self._finish_member(w, images[0], decode_batch=1)

    def _finish_member(self, w: _DecodeWork, images,
                       decode_batch: int) -> None:
        from ..frontdoor.microbatch import _fill_cache, _finish

        w.done = True
        cache = w.ticket.context.get("content_cache")
        try:
            _fill_cache(w.p, cache, images)
            out_cache = _finish(w.p, images)
        except InterruptedError:
            self._complete(w.ticket, w.p.member,
                           {"status": "interrupted"})
            return
        except Exception as e:  # noqa: BLE001 — member isolation barrier
            log(f"stages: suffix failed for {w.p.member.prompt_id}: {e}")
            self._complete(w.ticket, w.p.member,
                           {"status": "error", "error": str(e)})
            return
        self._complete(w.ticket, w.p.member,
                       {"status": "success", "outputs": out_cache,
                        "batch_size": w.sampler_batch,
                        "decode_batch": decode_batch})

    def _redispatch_decode(self, items: list) -> None:
        self._redispatch(self.decode, items)

    def _redispatch_encode(self, items: list) -> None:
        self._redispatch(self.encode, items)

    def _redispatch(self, pool: StagePool, items: list) -> None:
        """Bounded re-dispatch of a dead worker's held items to a
        surviving (or respawned) worker. Intentional-departure
        semantics: no dead-letter, no breaker evidence — past the bound
        the member errors LOUDLY instead of ping-ponging."""
        bound = constants.STAGE_MAX_REDISPATCH.get()
        for item in items:
            if getattr(item, "done", False):
                # already terminal (interrupted/errored before the
                # death) — re-dispatching would double-complete it
                continue
            item.redispatch += 1
            if item.redispatch > bound:
                item.fail(self, "error",
                          f"stage worker died {item.redispatch} times "
                          f"holding this item — redispatch bound "
                          f"({bound}) exceeded")
                continue
            with self._counts_lock:
                self.counts["redispatched"] += 1
            pool.put(item)

    # --- completion plumbing -------------------------------------------------

    def _complete(self, ticket: _GroupTicket, member, entry: dict) -> None:
        last = ticket.member_done()
        self._marshal(ticket.loop, ticket.record, member, entry, last)

    @staticmethod
    def _marshal(loop, fn, *args) -> None:
        """Run ``fn`` on the controller's event loop; if the loop is
        already closed (shutdown teardown) run inline so terminal state
        still lands."""
        try:
            loop.call_soon_threadsafe(fn, *args)
        except RuntimeError:
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001 — teardown barrier
                debug_log(f"stages: inline completion failed: {e!r}")

    # --- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._counts_lock:
            counts = dict(self.counts)
        return {
            "enabled": True,
            "pools": {p.name: p.stats()
                      for p in (self.encode, self.denoise, self.decode)},
            "wire": constants.STAGE_WIRE.get(),
            "steal": constants.STAGE_STEAL.get(),
            "decode_batch_max": self.decode.max_batch,
            "decode_window_ms": self.decode.window_s * 1000.0,
            **counts,
        }


def build_stages() -> Optional[StageManager]:
    """Controller hook: the stage manager, or None under CDT_STAGES=0
    (the fused path runs verbatim)."""
    if not stages_enabled():
        log("stage-split serving disabled (CDT_STAGES=0) — fused path")
        return None
    return StageManager()
