"""The latent wire format: one denoise→decode stage handoff.

A :class:`LatentHandoff` is the unit of work the denoise pool hands the
decode pool — the request's final ``x0`` latent plus the identity meta
that ties it to its prompt (conditioning digest, spec geometry, seed,
model preset). The serialization contract is
``diffusion/checkpoint.py``'s, applied to handoffs instead of sampler
carries: one ``.npz`` payload (JSON header + latent array), a SHA-256
that travels WITH the bytes, and a loader that refuses anything it
cannot verify — a flipped bit on the wire must re-dispatch the latent,
never decode into a wrong image.

In-process handoffs skip serialization entirely (the decode pool reads
the device array the denoise program produced); ``CDT_STAGE_WIRE=1``
forces every handoff through the full checksummed round trip (the
cross-worker transport simulation the chaos suite and the decode
import route exercise). Cross-worker movement rides the existing
dispatch transport as a JSON payload, exactly like checkpoint
export/import (docs/stages.md).
"""

from __future__ import annotations

import base64
import dataclasses
import io
import json

import numpy as np

from ...diffusion.checkpoint import checksum

LATENT_WIRE_VERSION = 1


class LatentWireError(Exception):
    """A latent handoff payload is unusable (bad version, checksum
    mismatch, garbled npz). The caller re-dispatches or recomputes —
    corruption is loud and never decoded."""


@dataclasses.dataclass
class LatentHandoff:
    """One request's denoise output in flight to the decode pool.

    ``latents`` is the GLOBAL ``[n_dp · B, h, w, C]`` f32 array (the
    exact bytes the fused program would have fed its VAE); ``meta``
    carries the run identity (model preset, spec geometry, seed, dp
    width, conditioning digest) a receiving decoder validates before
    trusting the shape."""

    prompt_id: str
    latents: np.ndarray
    meta: dict = dataclasses.field(default_factory=dict)
    version: int = LATENT_WIRE_VERSION

    @property
    def nbytes(self) -> int:
        return int(np.asarray(self.latents).nbytes)

    def bucket_key(self) -> tuple:
        """Decode-batching bucket: latents sharing this key may decode
        inside one program (same shape, same dtype)."""
        arr = np.asarray(self.latents)
        return (tuple(arr.shape), str(arr.dtype))

    # --- serialization (the checkpoint.py npz contract) ---------------------

    def to_bytes(self) -> bytes:
        header = {
            "version": self.version,
            "prompt_id": self.prompt_id,
            "meta": self.meta,
        }
        buf = io.BytesIO()
        np.savez(buf, latents=np.asarray(self.latents),
                 header=np.frombuffer(
                     json.dumps(header, sort_keys=True).encode(), np.uint8))
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, payload: bytes) -> "LatentHandoff":
        try:
            with np.load(io.BytesIO(payload)) as z:
                header = json.loads(bytes(z["header"].tobytes()).decode())
                latents = z["latents"]
        except (KeyError, ValueError, OSError, json.JSONDecodeError) as e:
            raise LatentWireError(f"unreadable latent payload: {e}")
        if header.get("version") != LATENT_WIRE_VERSION:
            raise LatentWireError(
                f"latent wire version {header.get('version')!r} != "
                f"{LATENT_WIRE_VERSION} (refusing a cross-version decode)")
        return cls(prompt_id=str(header.get("prompt_id", "")),
                   latents=latents, meta=dict(header.get("meta") or {}))

    def to_payload(self) -> dict:
        """JSON-safe wire form (rides the queue/dispatch transport like
        checkpoint payloads); the sha256 travels WITH the bytes so the
        receiving decoder verifies integrity before a byte is
        trusted."""
        payload = self.to_bytes()
        return {
            "version": LATENT_WIRE_VERSION,
            "prompt_id": self.prompt_id,
            "sha256": checksum(payload),
            "data": base64.b64encode(payload).decode("ascii"),
        }

    @classmethod
    def from_payload(cls, obj: dict) -> "LatentHandoff":
        if not isinstance(obj, dict) or "data" not in obj:
            raise LatentWireError("latent payload must be an object with "
                                  "a base64 'data' field")
        try:
            payload = base64.b64decode(obj["data"], validate=True)
        except Exception as e:  # noqa: BLE001 — any b64 failure is terminal
            raise LatentWireError(f"bad base64 latent data: {e}")
        want = obj.get("sha256")
        if not want:
            # NOT optional: an unverifiable payload is an unusable
            # payload (the checkpoint wire contract)
            raise LatentWireError(
                "latent payload carries no sha256 — refusing an "
                "unverifiable decode")
        if checksum(payload) != want:
            raise LatentWireError(
                "latent CHECKSUM MISMATCH on the wire — rejecting (a "
                "flipped bit must never decode into an image)")
        return cls.from_bytes(payload)


def encode_array_payload(arr: np.ndarray) -> dict:
    """Checksummed JSON-safe form of one array — the remote-decode
    route's response body (``POST /distributed/stages/decode``): same
    npz + sha256 contract as the handoff itself, so the caller verifies
    the decoded images exactly like the decoder verified the latents."""
    buf = io.BytesIO()
    np.savez(buf, array=np.asarray(arr))
    payload = buf.getvalue()
    return {"sha256": checksum(payload),
            "data": base64.b64encode(payload).decode("ascii")}


def decode_array_payload(obj: dict) -> np.ndarray:
    if not isinstance(obj, dict) or "data" not in obj:
        raise LatentWireError("array payload must be an object with a "
                              "base64 'data' field")
    try:
        payload = base64.b64decode(obj["data"], validate=True)
    except Exception as e:  # noqa: BLE001 — any b64 failure is terminal
        raise LatentWireError(f"bad base64 array data: {e}")
    want = obj.get("sha256")
    if not want or checksum(payload) != want:
        raise LatentWireError("array payload checksum missing or "
                              "mismatched — rejecting")
    try:
        with np.load(io.BytesIO(payload)) as z:
            return z["array"]
    except (KeyError, ValueError, OSError) as e:
        raise LatentWireError(f"unreadable array payload: {e}")
