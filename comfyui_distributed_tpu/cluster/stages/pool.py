"""Stage pools: bounded worker-thread pools with per-stage accounting.

One :class:`StagePool` per serving stage (encode / denoise / decode).
Each pool owns its queue, its worker threads, and its telemetry
(``cdt_stage_queue_depth`` / ``cdt_stage_occupancy`` /
``cdt_stage_jobs_total``) — the whole point of the stage split is that
these signals are PER POOL, so each pool scales on its own backlog and
a decode pile-up can never read as denoise pressure (docs/stages.md).

Two take disciplines:

- FIFO (encode, denoise): one item per pickup, arrival order.
- bucketed (decode): items carry a ``bucket_key()``; a worker takes up
  to ``max_batch`` same-bucket items once the bucket is full or its
  oldest item has waited ``window_s`` — the cross-request VAE-decode
  coalescing window.

Worker death is a first-class event: a runner raising
:class:`StageWorkerDeath` kills its worker thread, and the items it
held are re-dispatched to a survivor through the manager's bounded
redispatch path — never dead-lettered, never breaker evidence (the
chaos suite kills a decode worker holding batched latents and asserts
bit-identical completion).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from ... import telemetry
from ...lint.lockorder import tracked_lock
from ...telemetry import metrics as _tm
from ...utils.logging import log


class StageWorkerDeath(Exception):
    """Raised by a runner (or the chaos harness's death hook) to model a
    stage worker dying mid-item: the thread exits, held items
    re-dispatch to survivors."""


class StagePool:
    """Worker-thread pool for one serving stage.

    ``runner(items)`` executes a picked batch (length 1 for FIFO pools).
    Threads start lazily on the first ``put`` and are daemons — a
    controller that never serves a staged group never pays for them.
    """

    IDLE_POLL_S = 0.05

    def __init__(self, name: str, workers: int,
                 runner: Callable[[list], None], *,
                 batch_key: Optional[Callable] = None,
                 max_batch: int = 1, window_s: float = 0.0,
                 steal: Optional[Callable[["StagePool"],
                                          "Optional[StagePool]"]] = None,
                 redispatch: Optional[Callable[[list], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.runner = runner
        self.redispatch = redispatch
        self.batch_key = batch_key
        self.max_batch = max(1, int(max_batch))
        self.window_s = max(0.0, float(window_s))
        self.steal = steal
        self._clock = clock
        self._cond = threading.Condition(tracked_lock(f"stage.{name}"))
        # FIFO pools use _fifo; bucketed pools use _buckets
        # (key -> [first_enqueued_at, deque])
        self._fifo: deque = deque()
        self._buckets: "OrderedDict[tuple, list]" = OrderedDict()
        self._threads: list[threading.Thread] = []
        self._target = max(0, int(workers))
        self._busy = 0
        self._stop = False
        self._seq = 0
        # cumulative busy seconds — the occupancy numerator bench.py
        # integrates over its measurement window (docs/stages.md)
        self.busy_seconds = 0.0
        self.done = 0
        self.errors = 0
        self.started_at: Optional[float] = None

    # --- producer -----------------------------------------------------------

    def put(self, item) -> None:
        with self._cond:
            if self.batch_key is None:
                self._fifo.append(item)
            else:
                key = self.batch_key(item)
                bucket = self._buckets.get(key)
                if bucket is None:
                    bucket = self._buckets[key] = [self._clock(), deque()]
                bucket[1].append(item)
            self._ensure_threads_locked()
            self._cond.notify()
        self._export()

    def depth(self) -> int:
        with self._cond:
            return self._depth_locked()

    def _depth_locked(self) -> int:
        if self.batch_key is None:
            return len(self._fifo)
        return sum(len(b[1]) for b in self._buckets.values())

    @property
    def busy(self) -> int:
        return self._busy

    @property
    def workers(self) -> int:
        return self._target

    # --- sizing -------------------------------------------------------------

    def resize(self, n: int) -> None:
        """Grow/shrink the worker target. Growth spawns immediately when
        work is queued; surplus threads exit at their next pickup."""
        with self._cond:
            self._target = max(0, int(n))
            self._ensure_threads_locked()
            self._cond.notify_all()
        self._export()

    def _ensure_threads_locked(self) -> None:
        if self._stop:
            return
        self._threads = [t for t in self._threads if t.is_alive()]
        while len(self._threads) < self._target:
            self._seq += 1
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"stage-{self.name}-{self._seq}")
            self._threads.append(t)
            t.start()
            if self.started_at is None:
                self.started_at = self._clock()

    def alive_workers(self) -> int:
        with self._cond:
            self._threads = [t for t in self._threads if t.is_alive()]
            return len(self._threads)

    def stop(self) -> list:
        """Stop the pool; returns the items still queued (the manager
        records them interrupted — an admitted member must reach a
        terminal status even through shutdown)."""
        with self._cond:
            self._stop = True
            leftovers = list(self._fifo)
            self._fifo.clear()
            for bucket in self._buckets.values():
                leftovers.extend(bucket[1])
            self._buckets.clear()
            self._cond.notify_all()
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=5.0)
        self._export()
        return leftovers

    # --- consumer -----------------------------------------------------------

    def _take_locked(self) -> Optional[list]:
        if self.batch_key is None:
            if self._fifo:
                return [self._fifo.popleft()]
            return None
        return self._take_bucket_locked(ready_only=True)

    def take_now(self) -> Optional[list]:
        """Non-blocking take for a stealing sibling worker. Bucketed
        pools only release READY buckets — stealing must not defeat the
        coalescing window it exists to serve."""
        with self._cond:
            batch = self._take_locked()
        if batch:
            self._export()
        return batch

    def _take_bucket_locked(self, ready_only: bool) -> Optional[list]:
        now = self._clock()
        best_key, best_age = None, -1.0
        for key, (first_at, items) in self._buckets.items():
            if not items:
                continue
            ready = (len(items) >= self.max_batch
                     or now - first_at >= self.window_s)
            if ready_only and not ready:
                continue
            age = now - first_at
            if age > best_age:
                best_key, best_age = key, age
        if best_key is None:
            return None
        first_at, items = self._buckets[best_key]
        batch = [items.popleft()
                 for _ in range(min(self.max_batch, len(items)))]
        if items:
            # remaining items restart their window (they are a new batch)
            self._buckets[best_key][0] = now
        else:
            del self._buckets[best_key]
        return batch

    def _wait_timeout_locked(self) -> float:
        if self.batch_key is None or not self._buckets:
            return self.IDLE_POLL_S
        now = self._clock()
        nearest = min(max(0.0, b[0] + self.window_s - now)
                      for b in self._buckets.values() if b[1])
        return min(self.IDLE_POLL_S, nearest) or 0.001

    def _worker(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cond:
                if self._stop or me not in self._threads[:self._target]:
                    # shutdown, or a resize made this thread surplus
                    if me in self._threads:
                        self._threads.remove(me)
                    return
                batch = self._take_locked()
                if batch is None:
                    self._cond.wait(timeout=self._wait_timeout_locked())
                    batch = self._take_locked()
            src = self
            if batch is None and self.steal is not None:
                victim = self.steal(self)
                if victim is not None:
                    batch = victim.take_now()
                    if batch:
                        src = victim
                        if telemetry.enabled():
                            _tm.STAGE_STEALS.labels(
                                src=victim.name, dst=self.name).inc()
            if batch is None:
                continue
            try:
                self._run_batch(batch, src)
            except _WorkerExit as death:
                # the worker thread is gone; hand its items to the SRC
                # pool's bounded redispatch path, then exit for real
                if src.redispatch is not None:
                    try:
                        src.redispatch(death.items)
                    except Exception as e:  # noqa: BLE001 — last resort
                        log(f"stage {src.name}: redispatch after worker "
                            f"death failed: {e!r}")
                return

    def _run_batch(self, batch: list, src: "StagePool") -> None:
        me = threading.current_thread()
        with self._cond:
            self._busy += 1
        self._export()
        t0 = self._clock()
        outcome = "ok"
        try:
            src.runner(batch)
        except StageWorkerDeath as e:
            # the worker is gone; its items re-dispatch to a survivor
            # (bounded by the manager). Intentionally NOT an error
            # outcome and never breaker evidence — docs/stages.md.
            log(f"stage {self.name}: worker {me.name} DIED holding "
                f"{len(batch)} item(s) ({e}) — re-dispatching")
            outcome = "redispatch"
            with self._cond:
                self._busy -= 1
                self.busy_seconds += self._clock() - t0
                if me in self._threads:
                    self._threads.remove(me)
            self._count(src.name, outcome, len(batch))
            self._export()
            raise _WorkerExit(batch)
        except Exception as e:  # noqa: BLE001 — runner isolation barrier
            # runners do their own member-level isolation; anything
            # escaping is a stage-infrastructure bug worth a loud log,
            # but one poisoned batch must not kill the worker thread
            log(f"stage {self.name}: runner failed on {len(batch)} "
                f"item(s): {e!r}")
            outcome = "error"
            self.errors += 1
        finally:
            if outcome != "redispatch":
                with self._cond:
                    self._busy -= 1
                    self.busy_seconds += self._clock() - t0
                    self.done += len(batch)
                self._count(src.name, outcome, len(batch))
                self._export()

    def _count(self, src: str, outcome: str, n: int) -> None:
        if telemetry.enabled():
            _tm.STAGE_JOBS.labels(stage=src, outcome=outcome).inc(n)

    # --- telemetry ----------------------------------------------------------

    def _export(self) -> None:
        if not telemetry.enabled():
            return
        with self._cond:
            depth, busy, target = self._depth_locked(), self._busy, \
                self._target
        _tm.STAGE_QUEUE_DEPTH.labels(stage=self.name).set(depth)
        _tm.STAGE_OCCUPANCY.labels(stage=self.name).set(
            busy / max(1, target))

    def stats(self) -> dict:
        with self._cond:
            return {
                "workers": self._target,
                "alive": len([t for t in self._threads if t.is_alive()]),
                "busy": self._busy,
                "depth": self._depth_locked(),
                "busy_seconds": round(self.busy_seconds, 4),
                "done": self.done,
                "errors": self.errors,
            }


class _WorkerExit(BaseException):
    """Internal: unwinds a dying worker out of its loop carrying the
    items to re-dispatch. BaseException so a runner's blanket ``except
    Exception`` member-isolation barriers can't swallow the death."""

    def __init__(self, items: list):
        super().__init__("stage worker death")
        self.items = items
