"""Duplicate-request coalescing: one execution, N waiters.

At production traffic, byte-identical submissions arrive *concurrently*
— seed re-rolls re-submitted by impatient clients, gallery pages
re-requesting the same workflow, load balancers retrying. The result
cache only helps once a computation has finished; the coalescer closes
the window before that: the FIRST submission of a fingerprint becomes
the **leader** and executes normally, every byte-identical submission
that arrives while it is in flight becomes a **waiter** — admitted,
given its own prompt id, but never enqueued. When the leader reaches a
terminal history entry, the front door copies it to every waiter (each
gets its own per-request history row, marked with the leader it rode).

Soundness leans on the same invariant as the result cache: the
classifier only fingerprints the deterministic-batchable request class,
for which PR 6 established bit-identical execution — so the leader's
bytes ARE the waiter's bytes.

Runs entirely on the controller's event loop (submit and the job-done
callback are both loop-side), so no locking is needed; the width
histogram (``cdt_coalesce_width``) records how many requests each
executed program actually answered.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

# history statuses that mean "still in flight" (step-granular
# preemption, docs/preemption.md) — never copied to a waiter
NON_TERMINAL_STATUSES = frozenset(
    {"preempted", "resume_retry", "resume_scratch"})


@dataclasses.dataclass
class _Waiter:
    member: object            # PromptJob
    group_key: object         # classifier.GroupKey (for re-dispatch)
    sampler_node_id: str


@dataclasses.dataclass
class _Flight:
    leader_id: str
    waiters: "list[_Waiter]" = dataclasses.field(default_factory=list)
    opened_at: float = dataclasses.field(default_factory=time.monotonic)


class InflightCoalescer:
    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._flights: dict[str, _Flight] = {}
        self.resolved_flights = 0
        self.coalesced_waiters = 0
        self.redispatched_waiters = 0

    # --- producer side (front door submit) ----------------------------------

    def lead(self, fingerprint: str, prompt_id: str) -> None:
        """Register the leader for a fingerprint. First writer wins — a
        bypass request executing the same bytes concurrently simply is
        not a leader."""
        if fingerprint not in self._flights:
            self._flights[fingerprint] = _Flight(leader_id=prompt_id)

    def join(self, fingerprint: str, member, group_key=None,
             sampler_node_id: str = "") -> bool:
        """Attach ``member`` (a PromptJob) as a waiter on an in-flight
        leader. False = nothing in flight, caller must execute.
        ``group_key``/``sampler_node_id`` let an expired-leader waiter be
        re-dispatched through the batcher instead of inheriting a
        deadline verdict that was never its own."""
        flight = self._flights.get(fingerprint)
        if flight is None:
            return False
        flight.waiters.append(_Waiter(member, group_key, sampler_node_id))
        return True

    # --- consumer side (job-done callback) ----------------------------------

    def resolve(self, history: dict,
                redispatch: Optional[Callable] = None) -> int:
        """Settle every flight whose leader has a terminal history entry.
        Per waiter, in order of precedence:

        - the waiter's OWN deadline already passed → its row is
          ``expired`` (deadline_ms is a freshness contract; a result
          delivered late is exactly what it forbids — a solo submission
          would have been recorded expired too);
        - the leader expired → the waiter did NOT ask for that deadline:
          re-dispatch it through ``redispatch(member, group_key,
          sampler_node_id)`` as a fresh execution (without a redispatch
          hook it errors loudly rather than inheriting the verdict);
        - otherwise (success / error / interrupted — the execution's own
          outcome, identical for a queued solo twin) → copy the leader's
          row with a ``coalesced_with`` marker.

        Returns waiters settled (re-dispatched ones are settled later,
        by their new flight)."""
        settled = 0
        now = self._clock()
        for fp in list(self._flights):
            flight = self._flights[fp]
            entry = history.get(flight.leader_id)
            if entry is None:
                continue
            if entry.get("status") in NON_TERMINAL_STATUSES:
                # a preempted/resuming leader is still in flight — its
                # waiters settle when it reaches a REAL terminal row
                continue
            del self._flights[fp]
            width = 1 + len(flight.waiters)
            for waiter in flight.waiters:
                member = waiter.member
                if getattr(member, "expired", lambda _n: False)(now):
                    history[member.prompt_id] = {
                        "status": "expired", "duration": 0.0,
                        "error": "deadline_ms elapsed before execution",
                        "coalesced_with": flight.leader_id,
                    }
                elif entry.get("status") == "expired":
                    if redispatch is not None:
                        self.redispatched_waiters += 1
                        redispatch(member, waiter.group_key,
                                   waiter.sampler_node_id)
                        continue
                    history[member.prompt_id] = {
                        "status": "error", "duration": 0.0,
                        "error": "coalesced leader expired and no "
                                 "redispatch hook is installed",
                    }
                else:
                    row = dict(entry)
                    row["coalesced_with"] = flight.leader_id
                    history[member.prompt_id] = row
                settled += 1
            self.resolved_flights += 1
            self.coalesced_waiters += len(flight.waiters)
            self._observe_width(width)
        return settled

    def _observe_width(self, width: int) -> None:
        try:
            from ... import telemetry
            from ...telemetry import metrics as _tm

            if telemetry.enabled():
                _tm.COALESCE_WIDTH.observe(width)
        except Exception:  # noqa: BLE001 — telemetry is never load-bearing
            pass

    # --- introspection ------------------------------------------------------

    @property
    def inflight(self) -> int:
        return len(self._flights)

    @property
    def pending_waiters(self) -> int:
        return sum(len(f.waiters) for f in self._flights.values())

    def stats(self) -> dict:
        return {
            "inflight": self.inflight,
            "pending_waiters": self.pending_waiters,
            "resolved_flights": self.resolved_flights,
            "coalesced_waiters": self.coalesced_waiters,
        }
