"""Content-addressed cache keys: same bytes in, same key out — anywhere.

Every tier of the content cache (``cluster/cache``) keys on a SHA-256
digest of a canonical byte encoding of the inputs that determine the
output, and nothing else:

- **conditioning**: (encoder identity, tokenized ids, tokenization
  mode). Keying on the *token ids* rather than the raw string means two
  prompts that tokenize identically share an entry, and — critically —
  a worker whose tokenizer failed to load (hash-tokenization fallback,
  ``models/clip.py``) computes a *different* key than a healthy worker,
  so a degraded host can never poison the shared tier.
- **request fingerprint**: the full canonical prompt graph. The
  classifier's :class:`~..frontdoor.classifier.GroupKey` answers "can
  these share a program?"; the fingerprint answers "are these the SAME
  request?" — it covers the prompt text, negative prompt, seed, LoRA
  nodes, and every other literal in the graph, because they are all
  nodes/inputs of the prompt dict.
- **result**: fingerprint × execution signature (mesh topology + jax
  version). PRs 6–7 established that execution is bit-identical across
  batching and fleet churn *for a fixed program*; a different device
  count or XLA version is a different program, so it is a different key,
  never a wrong hit.

Digests are hex SHA-256 — collision-safe at fleet scale and filesystem-
safe as sidecar file names.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Iterable


def canonical_bytes(obj: Any) -> bytes:
    """Deterministic byte encoding of a JSON-able structure (sorted keys,
    no whitespace). Non-JSON leaves fall back to ``repr`` — stable for
    the literal types that appear in prompt graphs."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=repr).encode()


def digest(*parts: "bytes | str") -> str:
    """SHA-256 over length-prefixed parts (prefixing prevents boundary
    ambiguity: ("ab","c") never collides with ("a","bc"))."""
    # key-sized inputs (canonical JSON of request params, ids, shapes):
    # the hash is µs-scale, so async callers need no executor round-trip
    h = hashlib.sha256()  # cdtlint: disable=A002
    for p in parts:
        b = p.encode() if isinstance(p, str) else p
        h.update(len(b).to_bytes(8, "little"))
        h.update(b)
    return h.hexdigest()


def conditioning_key(encoder_id: str, token_sig: Any, mode: str) -> str:
    return digest("cond", encoder_id, mode, canonical_bytes(token_sig))


def request_fingerprint(prompt: dict) -> str:
    """Identity of one submitted request: the whole (meta-stripped)
    prompt graph, canonically encoded. Two submissions with equal
    fingerprints asked for byte-identical work."""
    return digest("req", canonical_bytes(prompt))


def execution_signature(mesh=None) -> str:
    """The facts that change a compiled program's output without changing
    the request: mesh topology (per-shard seed fold-in depends on it) and
    the jax/XLA version. Computed at the execution site, where the mesh
    is known."""
    import jax

    if mesh is not None:
        axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    else:
        axes = {"dp": len(jax.devices())}
    return digest("exec", canonical_bytes({"axes": axes,
                                           "jax": jax.__version__}))


def result_key(fingerprint: str, execution_sig: str,
               conditioning_mode: str = "", weights_id: str = "") -> str:
    """``conditioning_mode`` (real/hash per the bundle's text stack)
    joins the key so an image computed from degraded hash-tokenized
    conditioning is never served to — or from — a healthy worker;
    ``weights_id`` (bundle provenance: checkpoint path + mtime, or
    seed + jax version — ``ModelRegistry.weights_identity``) so an
    in-place checkpoint swap under the same ``ckpt_name`` invalidates
    rather than serves stale images."""
    return digest("result", fingerprint, execution_sig, conditioning_mode,
                  weights_id)


def near_fingerprint(prompt: dict) -> str:
    """Identity of a request *modulo seed*: the prompt graph with every
    integer ``seed`` input zeroed before canonical encoding. Two re-rolls
    of the same prompt (same graph, different seed) share this value —
    the near tier's notion of "the same work, different noise". Only
    integer ``seed`` literals are masked; a seed wired from another node
    (a list input) is part of the graph structure and stays."""
    import copy

    masked = copy.deepcopy(prompt)
    for node in masked.values():
        if not isinstance(node, dict):
            continue
        inputs = node.get("inputs")
        if isinstance(inputs, dict) and isinstance(inputs.get("seed"), int):
            inputs["seed"] = 0
    return digest("near", canonical_bytes(masked))


def near_key(fingerprint: str, execution_sig: str,
             conditioning_mode: str = "", weights_id: str = "") -> str:
    """Near-tier lookup key: same factors as :func:`result_key` but over
    the seedless :func:`near_fingerprint` — the execution signature,
    conditioning mode, and weights identity still join, because a donor
    trajectory from a different program/weights is a different work."""
    return digest("near-result", fingerprint, execution_sig,
                  conditioning_mode, weights_id)


def token_array_signature(ids) -> list:
    """Token-id array → JSON-able nested lists (the canonical form
    ``conditioning_key`` hashes)."""
    import numpy as np

    return np.asarray(ids).tolist()


def checksum(payload: "bytes | Iterable[bytes]") -> str:
    """Integrity checksum for persisted sidecar bytes."""
    h = hashlib.sha256()
    if isinstance(payload, bytes):
        h.update(payload)
    else:
        for chunk in payload:
            h.update(chunk)
    return h.hexdigest()
