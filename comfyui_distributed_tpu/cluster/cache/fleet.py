"""Fleet tier of the content cache: one keyspace over N workers.

PR 8's :mod:`cluster.cache` is strictly per-host — memory LRU plus a
flock'd local disk — so the fleet's hit rate is capped by which worker a
duplicate request happens to land on. This module promotes that cache to
a fleet tier:

- **Consistent-hash ring** (:class:`HashRing`): virtual nodes with
  seeded SHA-256 placement map the content-addressed keyspace over the
  active workers. Placement is a pure function of (seed, member id,
  vnode index), so every worker that shares ``CDT_FLEET_CACHE_SEED``
  computes the *same* ring from the same membership — no coordination
  round, no gossip. Membership churn is fed by the elastic
  :data:`~..elastic.states.DRAIN` lifecycle registry: a joining worker
  claims only its own vnode arcs (no global rehash), and a draining
  worker hands its shard's hot entries to their post-drain owners
  exactly once (PR 7 handback semantics — intentional departure, never
  breaker evidence).

- **Remote fills and serves** ride the checksummed npz+sha256 wire
  contract (:func:`~..stages.latents.encode_array_payload`) over
  ``GET/PUT /distributed/cache/entry/{key}``, with breaker gating and a
  small retry budget from :mod:`cluster.resilience`. The fallback ladder
  is strict and total: local memory → local disk → ring owner →
  recompute. A dead, slow, or disagreeing owner degrades to a miss —
  the fleet tier can *never* turn a cacheable request into an error.
  Remote failures are also never fed to the owner's breaker: the probe
  is best-effort scavenging, and poisoning a worker's breaker over a
  cache miss would shed serving capacity to save a recompute.

- **Asynchronous fills**: the serve path calls :meth:`FleetCache.fill`
  after a local fill and returns immediately; the PUT propagates on the
  controller loop in the background.

- **Near tier** (:class:`NearTier`, opt-in via ``cache: "near"``): a
  near-duplicate request — same fingerprint *modulo seed* — reuses a
  cached mid-trajectory latent checkpoint (PR 14's
  :class:`~...diffusion.checkpoint.CheckpointStore` + identity meta) as
  its init, cutting the denoise roughly in half for re-roll traffic.
  Near serves are NEVER bit-identical to a from-scratch run and never
  fill the exact result tier — see docs/caching.md for the soundness
  argument.

``CDT_FLEET_CACHE=0`` disables all of it: :func:`build_fleet_cache`
returns None and every call site falls back to PR 8 behavior verbatim.
"""

from __future__ import annotations

import asyncio
import bisect
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ...lint.lockorder import tracked_lock
from ...utils import constants
from ...utils.logging import debug_log, log
from ..elastic.states import DRAIN, DRAINING
from ..resilience import BREAKERS, RetryPolicy
from . import keys as _keys


def _fleet_metrics():
    try:
        from ... import telemetry
        from ...telemetry import metrics as _tm

        return telemetry.enabled(), _tm
    except Exception:  # noqa: BLE001 — telemetry is never load-bearing
        return False, None


def _count_remote(op: str, outcome: str) -> None:
    enabled, _tm = _fleet_metrics()
    if enabled:
        _tm.FLEET_CACHE_REMOTE.labels(op=op, outcome=outcome).inc()


class HashRing:
    """Deterministic consistent-hash ring over worker ids.

    Every vnode position is ``digest("ring", seed, member, i)`` and a
    key's position is ``digest("ring-key", key)`` — pure SHA-256 of the
    inputs, so two processes with the same (members, vnodes, seed)
    agree on every owner without exchanging a byte. Adding or removing
    one member moves only that member's arcs (the consistent-hashing
    property the tests pin down).
    """

    def __init__(self, members, vnodes: Optional[int] = None,
                 seed: Optional[str] = None):
        self.vnodes = (constants.FLEET_CACHE_VNODES.get()
                       if vnodes is None else int(vnodes))
        self.seed = (constants.FLEET_CACHE_SEED.get()
                     if seed is None else str(seed))
        points: list[tuple[int, str]] = []
        for member in sorted(set(str(m) for m in members)):
            for i in range(max(1, self.vnodes)):
                pos = int(_keys.digest("ring", self.seed, member,
                                       str(i))[:16], 16)
                points.append((pos, member))
        points.sort()
        self._points = points
        self._positions = [p for p, _ in points]

    def members(self) -> list:
        return sorted(set(m for _, m in self._points))

    def __len__(self) -> int:
        return len(self.members())

    def owner(self, key: str) -> Optional[str]:
        """The worker owning ``key``'s shard (clockwise-next vnode,
        wrapping), or None on an empty ring."""
        if not self._points:
            return None
        pos = int(_keys.digest("ring-key", str(key))[:16], 16)
        idx = bisect.bisect_right(self._positions, pos) % len(self._points)
        return self._points[idx][1]


class NearTier:
    """Opt-in approximate tier: seedless near-key → donor checkpoint.

    Holds mid-trajectory latent checkpoints parked by exact-path
    executions, keyed by :func:`~.keys.near_key` (the request identity
    with every integer seed masked). A ``cache:"near"`` re-roll that
    matches a donor resumes denoising from the donor's carry instead of
    pure noise — roughly half the steps — under its OWN fresh seed.
    The donor's identity meta (sampler, scheduler, geometry, dp width,
    conditioning digest — everything except seed) is validated before
    reuse; any mismatch is a miss, never a wrong init.
    """

    def __init__(self, max_entries: Optional[int] = None):
        from ...diffusion.checkpoint import CheckpointStore

        # memory-only store: donor carries are bf16/f32 jax leaves whose
        # value is warm-path reuse, not durability
        self.store = CheckpointStore(directory="")
        self.max_entries = (constants.FLEET_CACHE_NEAR_MAX.get()
                            if max_entries is None else int(max_entries))
        self._map: "OrderedDict[str, str]" = OrderedDict()
        self._lock = tracked_lock("cache.fleet.near")
        self.counts = {"donor": 0, "reuse": 0, "steps_saved": 0,
                       "mismatch": 0}

    def offer(self, near_k: str, ckpt) -> Optional[str]:
        """Park a donor under its near key (latest donor wins; LRU cap
        ``CDT_FLEET_CACHE_NEAR_MAX``). Returns the checkpoint id."""
        if self.max_entries <= 0:
            return None
        cid = self.store.park(ckpt)
        dropped: list[str] = []
        with self._lock:
            old = self._map.pop(near_k, None)
            self._map[near_k] = cid
            if old is not None and old != cid:
                dropped.append(old)
            while len(self._map) > self.max_entries:
                _, evicted = self._map.popitem(last=False)
                if evicted != cid:
                    dropped.append(evicted)
            self.counts["donor"] += 1
        for c in dropped:
            self.store.drop(c)
        return cid

    def lookup(self, near_k: str, expect_meta: dict):
        """A donor checkpoint matching ``expect_meta`` (which must NOT
        contain ``seed`` — matching modulo seed is the whole point), or
        None. A meta mismatch or corrupt donor is dropped and counted,
        and the caller computes from scratch."""
        with self._lock:
            cid = self._map.get(near_k)
        if cid is None:
            return None
        ckpt = self.store.get(cid)
        if ckpt is None:
            with self._lock:
                if self._map.get(near_k) == cid:
                    del self._map[near_k]
            return None
        try:
            ckpt.validate_meta(expect_meta)
        except Exception as e:  # noqa: BLE001 — mismatch is a miss
            debug_log(f"fleet.near: donor {cid} rejected: {e}")
            with self._lock:
                self.counts["mismatch"] += 1
                if self._map.get(near_k) == cid:
                    del self._map[near_k]
            self.store.drop(cid)
            return None
        with self._lock:
            if near_k in self._map:
                self._map.move_to_end(near_k)
        return ckpt

    def record_reuse(self, steps_saved: int) -> None:
        with self._lock:
            self.counts["reuse"] += 1
            self.counts["steps_saved"] += int(steps_saved)
        enabled, _tm = _fleet_metrics()
        if enabled:
            _tm.FLEET_NEAR_REUSE.inc()
            _tm.FLEET_NEAR_STEPS_SAVED.inc(int(steps_saved))

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._map),
                    "max_entries": self.max_entries, **self.counts}


class FleetCache:
    """The fleet tier: ring ownership + remote serve/fill/handback.

    ``membership`` is a zero-arg callable returning
    ``{worker_id: base_url_or_None}`` for the configured fleet (the
    controller wires it to its host config); workers the DRAIN registry
    marks as leaving are excluded from the ring here, so call sites
    don't each re-implement lifecycle filtering. ``transport`` lets
    tests inject an async ``(op, owner, url, key, arrays) -> result``
    in place of real HTTP.
    """

    def __init__(self, manager, self_id: str,
                 membership: Callable[[], dict],
                 transport: Optional[Callable] = None):
        self.manager = manager
        self.self_id = str(self_id) or "master"
        self._membership = membership
        self._transport = transport
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._lock = tracked_lock("cache.fleet")
        self._ring_cache: Optional[tuple] = None
        # strong refs to in-flight async fills/handbacks (a bare
        # run_coroutine_threadsafe future is garbage-collectable
        # mid-flight)
        self._pending: set = set()
        self._handed: set = set()
        self.counts = {"remote_hit": 0, "remote_miss": 0,
                       "remote_error": 0, "remote_skipped": 0,
                       "fill": 0, "fill_error": 0, "handback": 0}
        self.near = NearTier()
        # tight budget: the ladder's next rung is a recompute, not an
        # error, so retrying hard buys little and holds the serve path
        self._retry = RetryPolicy(max_attempts=2, base=0.1, cap=0.5)
        DRAIN.subscribe(self._on_lifecycle)

    # --- lifecycle ----------------------------------------------------------

    def attach_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        """Controller startup hands over its running loop; until then
        probes/fills are skipped (ladder degrades to local-only)."""
        self.loop = loop

    def close(self) -> None:
        DRAIN.unsubscribe(self._on_lifecycle)

    def _on_lifecycle(self, worker_id: str, state: str) -> None:
        with self._lock:
            self._ring_cache = None  # any transition can change the ring
        if worker_id == self.self_id and state == DRAINING:
            loop = self.loop
            if loop is not None and loop.is_running():
                fut = asyncio.run_coroutine_threadsafe(self.handback(),
                                                       loop)
                self._track(fut)

    def _track(self, fut) -> None:
        self._pending.add(fut)
        fut.add_done_callback(self._pending.discard)

    # --- ring ---------------------------------------------------------------

    def _raw_members(self) -> dict:
        try:
            members = dict(self._membership() or {})
        except Exception as e:  # noqa: BLE001 — membership must not throw
            debug_log(f"fleet: membership callable failed: {e}")
            members = {}
        members.setdefault(self.self_id, None)
        return {str(k): v for k, v in members.items()}

    def _active_members(self, include_self_drain: bool = False) -> dict:
        members = self._raw_members()
        return {wid: url for wid, url in members.items()
                if (include_self_drain and wid == self.self_id)
                or not DRAIN.is_leaving(wid)}

    def ring(self) -> tuple:
        """(HashRing, {member: url}) over the current active membership.
        The ring is rebuilt only when the sorted member set changes —
        lifecycle transitions invalidate the cache via the DRAIN feed."""
        members = self._active_members()
        signature = tuple(sorted(members))
        with self._lock:
            cached = self._ring_cache
            if cached is not None and cached[0] == signature:
                return cached[1], members
        ring = HashRing(signature)
        with self._lock:
            self._ring_cache = (signature, ring)
        enabled, _tm = _fleet_metrics()
        if enabled:
            _tm.FLEET_RING_SIZE.set(len(ring))
        return ring, members

    def owner_of(self, key: str) -> tuple:
        ring, members = self.ring()
        owner = ring.owner(key)
        return owner, members.get(owner)

    # --- remote serve (ladder rung 3) ---------------------------------------

    def probe(self, key: str) -> Optional[dict]:
        """Ask ``key``'s ring owner for the entry. Called synchronously
        from the graph-exec / encode-pool thread after both local tiers
        missed; every failure mode — no loop, breaker open, timeout,
        checksum reject, owner disagreement — returns None (recompute).
        Never raises, never blocks past ``CDT_FLEET_CACHE_TIMEOUT_S``."""
        try:
            owner, url = self.owner_of(key)
        except Exception:  # noqa: BLE001 — ring trouble is a miss
            return None
        if owner is None or owner == self.self_id or not url:
            return None
        if not BREAKERS.allow(owner):
            self._count("remote_skipped")
            _count_remote("get", "skipped")
            return None
        loop = self.loop
        if loop is None or not loop.is_running():
            self._count("remote_skipped")
            _count_remote("get", "skipped")
            return None
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            # blocking the loop on itself would deadlock; async callers
            # don't exist today (probe sites are worker threads), so
            # degrade to a miss rather than gamble
            self._count("remote_skipped")
            _count_remote("get", "skipped")
            return None
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self._get_remote(owner, url, key), loop)
            arrays = fut.result(constants.FLEET_CACHE_TIMEOUT_S.get())
        except Exception as e:  # noqa: BLE001 — ladder: degrade to miss
            debug_log(f"fleet: probe of {owner} for {key[:12]}… "
                      f"failed: {e}")
            self._count("remote_error")
            _count_remote("get", "error")
            return None
        if arrays is None:
            self._count("remote_miss")
            _count_remote("get", "miss")
            return None
        self._count("remote_hit")
        _count_remote("get", "hit")
        return arrays

    async def _get_remote(self, owner: str, url: str,
                          key: str) -> Optional[dict]:
        if self._transport is not None:
            result = await self._transport("get", owner, url, key, None)
            BREAKERS.record(owner, ok=True)
            return result
        import aiohttp

        from ...utils.network import get_client_session
        from ..stages.latents import decode_array_payload

        timeout = constants.FLEET_CACHE_TIMEOUT_S.get()

        async def _once():
            session = get_client_session()
            async with session.get(
                    f"{url}/distributed/cache/entry/{key}",
                    timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
                if resp.status == 404:
                    return None
                resp.raise_for_status()
                body = await resp.json()

            def _decode():
                payloads = body.get("arrays")
                if not isinstance(payloads, dict) or not payloads:
                    return None
                return {name: decode_array_payload(p)
                        for name, p in payloads.items()}

            # b64+npz+sha256 off the event loop (media-route discipline)
            return await asyncio.get_running_loop().run_in_executor(
                None, _decode)

        result = await self._retry.run(_once, op="fleet.get")
        # success feeds the breaker; failure deliberately does NOT — a
        # cache probe must never accumulate evidence against a worker
        # that is still serving fine (chaos stage 9 pins this down)
        BREAKERS.record(owner, ok=True)
        return result

    # --- async fill (never blocks the serve path) ---------------------------

    def fill(self, key: str, arrays: dict) -> None:
        """Propagate a freshly computed entry to its ring owner,
        fire-and-forget. No-op when this worker owns the shard, the
        owner's breaker is open, or no loop is attached."""
        try:
            owner, url = self.owner_of(key)
        except Exception:  # noqa: BLE001
            return
        if owner is None or owner == self.self_id or not url:
            return
        if not BREAKERS.allow(owner):
            _count_remote("put", "skipped")
            return
        loop = self.loop
        if loop is None or not loop.is_running():
            return
        arrays = {n: np.asarray(a) for n, a in arrays.items()}
        fut = asyncio.run_coroutine_threadsafe(
            self._put_remote(owner, url, key, arrays, op="put"), loop)
        self._track(fut)

    async def _put_remote(self, owner: str, url: str, key: str,
                          arrays: dict, op: str = "put") -> bool:
        try:
            if self._transport is not None:
                await self._transport("put", owner, url, key, arrays)
            else:
                await self._put_http(url, key, arrays)
        except Exception as e:  # noqa: BLE001 — a lost fill is a lost hit
            debug_log(f"fleet: {op} to {owner} for {key[:12]}… "
                      f"failed: {e}")
            self._count("fill_error")
            _count_remote(op, "error")
            return False
        BREAKERS.record(owner, ok=True)
        self._count("fill" if op == "put" else "handback")
        _count_remote(op, "hit")
        return True

    async def _put_http(self, url: str, key: str, arrays: dict) -> None:
        import aiohttp

        from ...utils.network import get_client_session
        from ..stages.latents import encode_array_payload

        timeout = constants.FLEET_CACHE_TIMEOUT_S.get()

        def _encode():
            return {"key": key,
                    "arrays": {n: encode_array_payload(a)
                               for n, a in arrays.items()}}

        body = await asyncio.get_running_loop().run_in_executor(
            None, _encode)

        async def _once():
            session = get_client_session()
            async with session.put(
                    f"{url}/distributed/cache/entry/{key}", json=body,
                    timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
                resp.raise_for_status()

        await self._retry.run(_once, op="fleet.put")

    # --- drain handback (PR 7 semantics on cache shards) --------------------

    async def handback(self) -> list:
        """Move this (draining) worker's shard entries to their
        post-drain owners. Exactly once per key — a repeated drain
        signal or overlapping handback re-sends nothing — and only
        in-memory entries move (disk entries are already durable and
        content-addressed). Returns the moved keys."""
        raw = self._raw_members()
        pre_members = {wid for wid in raw
                       if wid == self.self_id or not DRAIN.is_leaving(wid)}
        pre = HashRing(tuple(sorted(pre_members)))
        post_members = {wid: u for wid, u in raw.items()
                        if wid != self.self_id
                        and not DRAIN.is_leaving(wid) and u}
        if not post_members:
            return []
        post = HashRing(tuple(sorted(post_members)))
        tier = self.manager.results
        moved = []
        for key in tier.keys():
            if pre.owner(key) != self.self_id:
                continue
            with self._lock:
                if key in self._handed:
                    continue
            new_owner = post.owner(key)
            url = post_members.get(new_owner)
            if not url:
                continue
            arrays = tier.peek(key)
            if arrays is None:
                continue
            if await self._put_remote(new_owner, url, key, arrays,
                                      op="handback"):
                with self._lock:
                    self._handed.add(key)
                # stop serving from this LRU so the entry lives in
                # exactly one memory tier (the sidecar stays valid)
                tier.drop_memory(key)
                moved.append(key)
        if moved:
            log(f"fleet: drain handback moved {len(moved)} cache "
                f"entries off {self.self_id}")
        return moved

    # --- bookkeeping --------------------------------------------------------

    def _count(self, outcome: str) -> None:
        with self._lock:
            self.counts[outcome] = self.counts.get(outcome, 0) + 1

    def stats(self) -> dict:
        ring, members = self.ring()
        with self._lock:
            counts = dict(self.counts)
        return {"self": self.self_id, "ring_size": len(ring),
                "members": ring.members(),
                "vnodes": ring.vnodes, **counts,
                "near": self.near.stats()}


def build_fleet_cache(manager, self_id: str,
                      membership: Callable[[], dict],
                      transport: Optional[Callable] = None
                      ) -> Optional[FleetCache]:
    """The fleet tier, or None when disabled (``CDT_FLEET_CACHE=0``) or
    when the per-host cache itself is off — None means every call site
    behaves exactly as PR 8 shipped."""
    if manager is None or not constants.FLEET_CACHE.get():
        return None
    return FleetCache(manager, self_id, membership, transport=transport)
