"""The conditioning cache: text-encode once per unique prompt, fleet-wide.

Text encoding is pure, deterministic, and model-heavy (a T5-XXL forward
per prompt at FLUX/WAN scale), and the request stream repeats itself —
the SAME negative prompt rides almost every request, popular prompts
recur across users and seed re-rolls. This module memoizes the
``encode(texts) -> (context, pooled)`` surface every text stack in the
repo exposes (``models/text.TextEncoder``, ``models/clip.CLIPConditioner``,
the T5 stacks in ``models/t5.py``).

Keying is content-addressed and *tokenization-aware*
(:func:`..cache.keys.conditioning_key`):

- **encoder identity** comes from the bundle that built the encoder
  (``ModelRegistry`` stamps ``_cdt_encoder_id``); an encoder without an
  identity is never cached — unknown identity beats a wrong hit.
- **token signature** is the encoder's actual token ids (its
  ``token_signature(texts)`` hook), so the key captures vocab, padding,
  and truncation exactly.
- **mode** records real-vs-hash tokenization per tower. A worker whose
  BPE vocab failed to load (``models/clip.py`` hash fallback) computes
  ``hash``-mode keys that can never collide with a healthy worker's
  ``bpe``-mode keys — and hash-mode entries are kept memory-only, so a
  degraded worker cannot write garbage into the shared persisted tier.

Round-trips are bit-exact: arrays are stored as the numpy bytes jax
produced and handed back unchanged, so a cached conditioning feeding a
pipeline is indistinguishable from a recomputed one (asserted end-to-end
in ``tests/test_cache_integration.py``).
"""

from __future__ import annotations

from typing import Optional

from ...utils.logging import debug_log
from . import keys as _keys

# the mode component marking a degraded (vocab-less) tower; entries
# computed under it never reach the shared persisted tier. Exact
# component match: "hash-native" (models/text.py — hash BY DESIGN, not a
# fallback) is not degraded.
DEGRADED_COMPONENT = "hash"


def encoder_identity(encoder) -> Optional[str]:
    """The registry-stamped identity, or None (= do not cache)."""
    ident = getattr(encoder, "_cdt_encoder_id", None)
    return ident if isinstance(ident, str) and ident else None


def token_signature(encoder, texts) -> "tuple[list, str]":
    """(canonical token signature, tokenization mode) for ``texts`` under
    ``encoder``. Prefers the encoder's own ``token_signature`` hook (the
    ids that actually enter the forward pass); encoders without one fall
    back to the raw strings under the distinct ``text`` mode."""
    hook = getattr(encoder, "token_signature", None)
    if hook is not None:
        return hook(texts)
    return [str(t) for t in texts], "text"


def encoder_mode(encoder) -> str:
    """Degradation summary for the RESULT-cache key: an image computed
    from hash-tokenized conditioning must never be served to (or from) a
    healthy worker, so the mode joins the execution signature."""
    mode = getattr(encoder, "tokenization_mode", None)
    if isinstance(mode, str):
        return mode
    mode = getattr(encoder, "_tokenize_mode", None)
    return mode if isinstance(mode, str) else "unknown"


def degraded(mode: str) -> bool:
    """True when any tower of a composite mode ("l=bpe,g=hash") fell back
    to hash tokenization."""
    import re

    return DEGRADED_COMPONENT in re.split(r"[,=/]", mode)


def cached_encode(manager, encoder, texts):
    """``encoder.encode(texts)`` through the conditioning tier.

    Falls through to a plain encode whenever caching cannot be sound:
    no manager, unidentified encoder, or a non-roundtrippable dtype
    (the store skips persisting those)."""
    import jax.numpy as jnp
    import numpy as np

    texts = [str(t) for t in texts]
    ident = None if manager is None else encoder_identity(encoder)
    if ident is None:
        return encoder.encode(texts)
    sig, mode = token_signature(encoder, texts)
    key = _keys.conditioning_key(ident, sig, mode)
    hit = manager.conditioning.get(key)
    if hit is not None and "context" in hit and "pooled" in hit:
        return jnp.asarray(hit["context"]), jnp.asarray(hit["pooled"])
    context, pooled = encoder.encode(texts)
    try:
        manager.conditioning.put(
            key,
            {"context": np.asarray(context), "pooled": np.asarray(pooled)},
            persist=not degraded(mode))
    except Exception as e:  # noqa: BLE001 — a cache fill must never sink
        # the request that just computed a perfectly good conditioning
        debug_log(f"conditioning cache: fill failed for {key[:12]}: {e}")
    return context, pooled
