"""Content-addressed inference caching + duplicate-request coalescing
(ROADMAP item 2, docs/caching.md).

At production traffic the request stream is heavily redundant — identical
prompts, shared negative prompts, seed re-rolls of the same workflow —
yet without this package every admitted request pays a full text-encode
and byte-identical submissions pay a full denoise. Three tiers stop the
fleet recomputing what it already knows:

- **conditioning** (:mod:`conditioning`): ``encode()`` memoized on
  (encoder identity, token ids, tokenization mode) — CLIP/T5 text
  encode runs once per unique prompt, fleet-wide via the persisted tier.
- **in-flight coalescing** (:mod:`coalesce`): byte-identical requests
  submitted while their twin executes become waiters on ONE execution,
  each with its own per-request history entry.
- **result** (:mod:`store` via the front door's microbatch executor):
  the sampler-program output (denoise + decode) keyed on the full
  request fingerprint × execution signature. Sound because PRs 6–7
  established bit-identity invariants for batched and churned execution
  of exactly the classifier-proven deterministic request class this
  cache serves.

Every hit frees a TPU slot for non-redundant work, so the hit rate is
wired into the elastic autoscaler's pressure signal
(``cluster/elastic``): a hot cache scales the fleet *down*.

Persistence follows ``utils/jsonio`` atomic-merge plus checksummed
binary sidecars (:mod:`store`); corruption is rejected loudly and
recomputed, never served. ``CDT_CACHE=0`` removes the subsystem;
per-request ``cache: "bypass"`` skips serving (but still fills) for one
request. Eviction is size-capped LRU with pinning, mirroring
``cluster/residency``.
"""

from __future__ import annotations

from collections import deque
from pathlib import Path
from typing import Optional

from ...utils import constants
from ...utils.logging import log
from .coalesce import InflightCoalescer
from .conditioning import cached_encode
from .keys import (conditioning_key, execution_signature, near_fingerprint,
                   near_key, request_fingerprint, result_key)
from .store import CacheTier

__all__ = [
    "CacheManager", "CacheTier", "InflightCoalescer", "build_cache_manager",
    "cache_enabled", "cached_encode", "conditioning_key",
    "execution_signature", "near_fingerprint", "near_key",
    "request_fingerprint", "result_key",
]

# "near" opts one request into the approximate trajectory-reuse tier
# (cluster/cache/fleet.py) — exact tiers still serve it first
CACHE_MODES = ("use", "bypass", "near")


def cache_enabled() -> bool:
    return constants.CACHE.get()


def cache_dir() -> Optional[Path]:
    """Resolved persisted-tier directory: ``CDT_CACHE_DIR``, defaulting
    to a ``content_cache`` sibling of the XLA compile cache (the same
    shared volume a fleet already mounts for warm restarts). Empty
    string = memory-only."""
    env = constants.CACHE_DIR.get()
    if env is not None:
        return Path(env) if env else None
    from ...utils.compile_cache import cache_dir_default

    return Path(cache_dir_default()).parent / "content_cache"


class _HitRateWindow:
    """Sliding window over recent QUEUED-request cache outcomes (a
    fingerprinted member served by the result tier vs executed). Feeds
    the autoscaler's pressure discount — instantaneous, not lifetime, so
    a cold restart doesn't inherit yesterday's optimism. Coalesced joins
    deliberately do NOT count: a waiter never occupies a queue slot, so
    folding the coalesce rate in would discount depth that the
    duplicates already aren't part of (double-counting)."""

    def __init__(self, size: int = 256):
        self._events: deque = deque(maxlen=size)

    def record(self, hit: bool) -> None:
        self._events.append(1 if hit else 0)

    def rate(self) -> float:
        if not self._events:
            return 0.0
        return sum(self._events) / len(self._events)


class CacheManager:
    """One controller's cache surface: both tiers + the coalescer +
    the request-level hit-rate window the autoscaler reads."""

    def __init__(self, directory: "Path | None" = None):
        self.dir = directory
        self.conditioning = CacheTier(
            "conditioning", constants.CACHE_COND_MAX_BYTES,
            directory=directory,
            disk_max_bytes=constants.CACHE_DISK_MAX_BYTES)
        self.results = CacheTier(
            "result", constants.CACHE_RESULT_MAX_BYTES,
            directory=directory,
            disk_max_bytes=constants.CACHE_DISK_MAX_BYTES)
        self.coalescer = InflightCoalescer()
        self._window = _HitRateWindow()
        # fleet tier (cluster/cache/fleet.py), attached by the
        # controller when CDT_FLEET_CACHE=1; None = per-host only.
        # Remote serves go through the same record_request(hit=True)
        # path as local ones, so the autoscaler's hit-rate window
        # discounts work the fleet (not just this host) already has.
        self.fleet = None

    # --- request-level outcomes (autoscaler signal) -------------------------

    def record_request(self, hit: bool) -> None:
        self._window.record(hit)

    def hit_rate(self) -> float:
        """Fraction of recent QUEUED fingerprinted requests the result
        tier answered without a sampler program — the autoscaler's
        queue-depth discount (coalesced joins are excluded; they never
        enter the queue). Fleet-tier REMOTE serves count as hits: the
        serving ladder records them through the same
        ``record_request(hit=True)`` path as local serves, so a fleet
        with a hot remote tier scales down on work it never executes.
        Near-tier serves stay misses — a reduced program still runs."""
        return self._window.rate()

    # --- introspection ------------------------------------------------------

    def stats(self) -> dict:
        return {
            "enabled": True,
            "dir": str(self.dir) if self.dir else None,
            "hit_rate": round(self.hit_rate(), 4),
            "conditioning": self.conditioning.stats(),
            "result": self.results.stats(),
            "coalescer": self.coalescer.stats(),
            "fleet": self.fleet.stats() if self.fleet is not None else None,
        }


def build_cache_manager() -> Optional[CacheManager]:
    """Controller hook: the cache manager, or None under ``CDT_CACHE=0``."""
    if not cache_enabled():
        log("content cache disabled (CDT_CACHE=0)")
        return None
    d = cache_dir()
    if d is not None:
        try:
            d.mkdir(parents=True, exist_ok=True)
        except OSError as e:
            log(f"content cache: persisted tier OFF ({d}: {e}) — "
                "memory-only")
            d = None
    return CacheManager(directory=d)
