"""Size-capped LRU cache tier with pinning and checksummed persistence.

One :class:`CacheTier` instance per tier (conditioning / result). The
policy mirrors ``cluster/residency.ResidencyPlanner`` — least-recently-
used eviction under a byte budget, pinned entries untouchable — applied
to named numpy-array bundles instead of model bundles.

Persistence follows the ``utils/jsonio`` contract the shape catalog and
autotune table established, extended with a binary sidecar per entry:

- the **index** (``<tier>_index.json``) is read-merge-atomic-written, so
  concurrent writers (serving master, bench, a second controller against
  a shared cache dir) union instead of clobbering;
- each **entry** is one ``.npz`` sidecar written tmp+``os.replace``, its
  SHA-256 recorded in the index. A load recomputes the checksum; any
  mismatch is rejected LOUDLY (log + ``cdt_cache_corrupt_total``), the
  entry is deleted, and the caller recomputes — a flipped bit on disk
  can never become a served byte.

Entries whose arrays use non-standard dtypes (e.g. ml_dtypes bfloat16)
are kept memory-only: their ``.npz`` round-trip is not guaranteed
bit-exact across numpy versions, and bit-exactness is the whole point.
"""

from __future__ import annotations

import contextlib
import io
import os
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Optional

import numpy as np

from ...lint.lockorder import tracked_lock
from ...utils.jsonio import atomic_write_json, read_json
from ...utils.logging import debug_log, log
from . import keys as _keys


def _tier_metrics():
    """(enabled, metrics module) — guarded import so the store stays
    usable in processes that never initialize telemetry."""
    try:
        from ... import telemetry
        from ...telemetry import metrics as _tm

        return telemetry.enabled(), _tm
    except Exception:  # noqa: BLE001 — telemetry is never load-bearing
        return False, None


def _persistable(arrays: dict) -> bool:
    """Only standard numeric dtypes round-trip bit-exactly through
    ``.npz`` everywhere; anything else (bf16 et al.) stays memory-only."""
    return all(a.dtype.kind in "fiub" for a in arrays.values())


class _Entry:
    __slots__ = ("arrays", "nbytes", "pins")

    def __init__(self, arrays: dict, nbytes: int):
        self.arrays = arrays
        self.nbytes = nbytes
        self.pins = 0


class CacheTier:
    """Thread-safe LRU tier over ``key -> {name: np.ndarray}`` bundles.

    ``max_bytes`` caps the in-memory tier (0 disables memory caching);
    ``directory``/``disk_max_bytes`` enable the persisted tier shared
    across processes and restarts (None/0 = memory-only).
    """

    def __init__(self, tier: str, max_bytes: int,
                 directory: "Path | str | None" = None,
                 disk_max_bytes: int = 0):
        self.tier = tier
        self.max_bytes = int(max_bytes)
        self.dir = Path(directory) if directory else None
        self.disk_max_bytes = int(disk_max_bytes)
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._lock = tracked_lock(f"cache.tier.{tier}", reentrant=True)
        self.counts = {"hit": 0, "miss": 0, "disk_hit": 0, "put": 0,
                       "evicted": 0, "corrupt": 0, "persisted": 0}

    # --- introspection ------------------------------------------------------

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
            return {
                "entries": len(self._entries),
                "bytes": sum(e.nbytes for e in self._entries.values()),
                "max_bytes": self.max_bytes,
                "persist_dir": str(self.dir) if self.dir else None,
                **counts,
            }

    def keys(self) -> list:
        """In-memory keys, LRU-oldest first (fleet handback enumerates
        these to find the shard's hot entries)."""
        with self._lock:
            return list(self._entries)

    def peek(self, key: str) -> Optional[dict]:
        """Arrays for ``key`` from memory only — no disk consult, no LRU
        touch, no hit/miss accounting. The fleet tier's remote-serve and
        handback paths use it so a neighbor's probe doesn't distort this
        host's local hit-rate window or recency order."""
        with self._lock:
            e = self._entries.get(key)
            return dict(e.arrays) if e is not None else None

    def drop_memory(self, key: str) -> None:
        """Drop one entry from memory only (exactly-once drain handback:
        after a successful move the donor must stop serving the entry
        from its LRU, but the checksummed sidecar stays valid)."""
        with self._lock:
            self._entries.pop(key, None)
        self._export_gauges()

    # --- pinning (mirrors cluster/residency) --------------------------------

    def pin(self, key: str) -> bool:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return False
            e.pins += 1
            return True

    def unpin(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.pins > 0:
                e.pins -= 1

    # --- the cache ----------------------------------------------------------

    def get(self, key: str) -> Optional[dict]:
        """Arrays for ``key``, or None. Memory first; on a memory miss the
        persisted tier is consulted (checksum-verified) and a hit is
        promoted into memory."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                self._count("hit")
                return dict(e.arrays)
        arrays = self._disk_get(key)
        if arrays is not None:
            self._count("disk_hit")
            self._insert(key, arrays, persist=False)
            return dict(arrays)
        self._count("miss")
        return None

    def put(self, key: str, arrays: dict, persist: bool = True) -> None:
        """Insert (or refresh) ``key``. ``persist=False`` keeps the entry
        memory-only even when a directory is configured — the degraded-
        tokenization guard and tests use it."""
        arrays = {n: np.asarray(a) for n, a in arrays.items()}
        self._insert(key, arrays, persist=persist)
        self._count("put")

    def _insert(self, key: str, arrays: dict, persist: bool) -> None:
        nbytes = sum(a.nbytes for a in arrays.values())
        with self._lock:
            old = self._entries.pop(key, None)
            if self.max_bytes > 0 or old is not None:
                self._entries[key] = _Entry(arrays, nbytes)
                if old is not None:
                    self._entries[key].pins = old.pins
                self._evict_over_budget_locked()
        if persist and self.dir is not None and _persistable(arrays):
            self._disk_put(key, arrays)
        self._export_gauges()

    def _evict_over_budget_locked(self) -> None:
        if self.max_bytes <= 0:
            return
        used = sum(e.nbytes for e in self._entries.values())
        for key in list(self._entries):
            if used <= self.max_bytes:
                return
            e = self._entries[key]
            if e.pins > 0:
                continue
            del self._entries[key]
            used -= e.nbytes
            self._count("evicted", export=True)

    # --- persistence --------------------------------------------------------

    def _index_path(self) -> Path:
        return self.dir / f"{self.tier}_index.json"

    def _entry_path(self, key: str) -> Path:
        return self.dir / self.tier / f"{key}.npz"

    @contextlib.contextmanager
    def _index_flock(self):
        """Advisory cross-PROCESS lock around the index read-merge-write
        (the in-process RLock can't serialize a second controller or a
        bench sharing CDT_CACHE_DIR — without this, two writers would
        last-write-win and the loser's row, though its sidecar is on
        disk, silently stops being servable). Degrades to lockless on
        filesystems without flock — same behavior as before, worst case
        a lost index row, never a wrong byte (entries are checksummed)."""
        try:
            import fcntl
        except ImportError:
            yield
            return
        lock_path = self.dir / f"{self.tier}_index.lock"
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
        except OSError:
            yield
            return
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
            except OSError:
                pass
            yield
        finally:
            os.close(fd)

    def _read_index(self) -> dict:
        """Parsed index entries, cached against the file's (mtime_ns,
        size) — a memory miss on the serving hot path must not re-parse
        a multi-thousand-row JSON per request. Writers always go through
        ``_write_index``, which re-reads under the flock."""
        path = self._index_path()
        try:
            st = path.stat()
            stamp = (st.st_mtime_ns, st.st_size)
        except OSError:
            stamp = None
        with self._lock:
            cached = getattr(self, "_index_cache", None)
            if cached is not None and cached[0] == stamp:
                return cached[1]
        entries = self._read_index_uncached()
        with self._lock:
            self._index_cache = (stamp, entries)
        return entries

    def _read_index_uncached(self) -> dict:
        data = read_json(self._index_path())
        entries = (data or {}).get("entries")
        return entries if isinstance(entries, dict) else {}

    def _write_index(self, mutate) -> None:
        """Read-merge-write under both locks (thread + process):
        ``mutate(entries)`` edits the freshly re-read mapping, so
        concurrent writers union."""
        with self._lock, self._index_flock():
            entries = self._read_index_uncached()
            mutate(entries)
            atomic_write_json(self._index_path(),
                              {"version": 1, "tier": self.tier,
                               "entries": entries})
            try:
                st = self._index_path().stat()
                self._index_cache = ((st.st_mtime_ns, st.st_size), entries)
            except OSError:
                self._index_cache = (None, entries)

    def _disk_put(self, key: str, arrays: dict) -> None:
        try:
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            payload = buf.getvalue()
            path = self._entry_path(key)
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(payload)
            os.replace(tmp, path)
            row = {"file": path.name, "sha256": _keys.checksum(payload),
                   "bytes": len(payload), "saved_at": time.time()}
            self._write_index(lambda e: e.__setitem__(key, row))
            # counts is mutated under self._lock everywhere else; a bare
            # dict += here is a lost-update race (lint rule L001)
            with self._lock:
                self.counts["persisted"] += 1
            self._disk_evict_over_budget()
        except OSError as e:
            debug_log(f"cache[{self.tier}]: persist of {key[:12]} "
                      f"failed: {e}")

    def _disk_get(self, key: str) -> Optional[dict]:
        if self.dir is None:
            return None
        row = self._read_index().get(key)
        if not isinstance(row, dict):
            return None
        path = self._entry_path(key)
        try:
            payload = path.read_bytes()
        except OSError:
            return None
        if _keys.checksum(payload) != row.get("sha256"):
            # integrity failure is LOUD and terminal for the entry: drop
            # it everywhere and let the caller recompute — a corrupted
            # sidecar must never become a served byte
            log(f"cache[{self.tier}]: CHECKSUM MISMATCH for entry "
                f"{key[:16]}… — rejecting and deleting (recompute follows)")
            self._count("corrupt", export=True)
            self.invalidate(key)
            return None
        try:
            with np.load(io.BytesIO(payload)) as z:
                return {n: z[n] for n in z.files}
        except (OSError, ValueError) as e:
            log(f"cache[{self.tier}]: unreadable entry {key[:16]}… "
                f"({e}) — deleting")
            self._count("corrupt", export=True)
            self.invalidate(key)
            return None

    def _disk_evict_over_budget(self) -> None:
        if self.disk_max_bytes <= 0:
            return
        entries = self._read_index()
        used = sum(int(r.get("bytes", 0)) for r in entries.values())
        if used <= self.disk_max_bytes:
            return
        victims = []
        for key, row in sorted(entries.items(),
                               key=lambda kv: kv[1].get("saved_at", 0.0)):
            if used <= self.disk_max_bytes:
                break
            victims.append(key)
            used -= int(row.get("bytes", 0))
        # ONE index rewrite for the whole victim set (per-victim
        # invalidate() would pay a flock + full-index read-merge-write
        # each, on the graph-exec thread that just filled the entry)
        def _drop_all(e):
            for key in victims:
                e.pop(key, None)

        self._write_index(_drop_all)
        for key in victims:
            try:
                self._entry_path(key).unlink()
            except OSError:
                pass
            self._count("evicted")
        self._export_gauges()

    def clear_memory(self) -> int:
        """Drop every in-memory entry (operator invalidation route);
        persisted entries are untouched — they are content-addressed and
        stay valid. Returns the number dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
        self._export_gauges()
        return n

    def invalidate(self, key: str, memory: bool = True) -> None:
        """Drop one entry from memory and disk (corruption handling,
        operator invalidation)."""
        if memory:
            with self._lock:
                self._entries.pop(key, None)
        if self.dir is not None:
            self._write_index(lambda e: e.pop(key, None))
            try:
                self._entry_path(key).unlink()
            except OSError:
                pass
        self._export_gauges()

    # --- telemetry ----------------------------------------------------------

    def _count(self, outcome: str, export: bool = False) -> None:
        with self._lock:
            self.counts[outcome] = self.counts.get(outcome, 0) + 1
        enabled, _tm = _tier_metrics()
        if not enabled:
            return
        if outcome in ("hit", "disk_hit"):
            _tm.CACHE_HITS.labels(tier=self.tier).inc()
        elif outcome == "miss":
            _tm.CACHE_MISSES.labels(tier=self.tier).inc()
        elif outcome == "evicted":
            _tm.CACHE_EVICTIONS.labels(tier=self.tier).inc()
        elif outcome == "corrupt":
            _tm.CACHE_CORRUPT.labels(tier=self.tier).inc()

    def _export_gauges(self) -> None:
        enabled, _tm = _tier_metrics()
        if not enabled:
            return
        with self._lock:
            _tm.CACHE_BYTES.labels(tier=self.tier).set(
                sum(e.nbytes for e in self._entries.values()))
            _tm.CACHE_ENTRIES.labels(tier=self.tier).set(
                len(self._entries))
