"""The host controller: one per host, owning that host's chips.

Reference analogue: one ComfyUI instance (master or worker —
``distributed.py:1-51``). Role is determined by ``is_worker`` (env
``CDT_IS_WORKER``, parity with ``COMFYUI_IS_WORKER``, ``distributed.py:48``):
masters orchestrate and collect; workers execute dispatched prompts and
push results back. Both run the same code and the same HTTP app.
"""

from __future__ import annotations

import asyncio
import os
import platform
from pathlib import Path
from typing import Any, Optional

from ..utils import constants
from ..utils.config import ensure_config_exists, load_config, peek_setting
from ..utils.logging import log, set_debug_source
from ..workers.detection import detect_environment, get_machine_id as machine_id
from .collector_bridge import CollectorBridge
from .job_store import JobStore
from .orchestration import Orchestrator
from .runtime import PromptQueue

IS_WORKER_ENV = "CDT_IS_WORKER"


class Controller:
    def __init__(self, config_path: Optional[Path] = None,
                 mesh_devices: Optional[int] = None):
        ensure_config_exists(config_path)
        self.config_path = config_path
        if config_path is not None:
            # outbound peer calls must read the auth token from the SAME
            # config this controller enforces inbound (utils/network.py)
            from ..utils.network import set_auth_config_path

            set_auth_config_path(config_path)
        # wire the config's settings.debug flag into the TTL-cached log
        # gate (reference utils/logging.py:15-39) — without this only the
        # CDT_DEBUG env var could enable debug logging (the gate always
        # honors the env var on top of this source)
        set_debug_source(
            lambda: bool(peek_setting("debug", False, config_path)))
        self.is_worker = constants.IS_WORKER.get()
        self.store = JobStore()
        self.queue = PromptQueue(context_factory=self._execution_context)
        self.orchestrator = Orchestrator(self.store, self.queue,
                                         config_loader=self.load_config)
        # content-addressed cache (cluster/cache): conditioning + result
        # tiers and the in-flight coalescer; None under CDT_CACHE=0
        from .cache import build_cache_manager

        self.cache = build_cache_manager()
        # step-granular preemption (cluster/preemption.py): resumable
        # denoise segments + latent checkpoint parking; None under
        # CDT_PREEMPT=0 (monolithic sampler programs)
        from .preemption import build_preemption

        self.preemption = build_preemption(self.queue)
        self.queue.preemption = self.preemption
        # disaggregated stage-split serving (cluster/stages,
        # docs/stages.md): independent encode/denoise/decode pools for
        # front-door batch jobs; None under CDT_STAGES=0 (fused path)
        from .stages import build_stages

        self.stages = build_stages()
        self.queue.stages = self.stages
        # serving front door (cluster/frontdoor): admission control +
        # cross-user microbatching in front of the queue; None under
        # CDT_FRONTDOOR=0 (the API layer then serves the legacy path)
        from .frontdoor import build_frontdoor

        self.frontdoor = build_frontdoor(self.queue, self.orchestrator,
                                         config_loader=self.load_config,
                                         cache=self.cache,
                                         stages=self.stages)
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.bridge: Optional[CollectorBridge] = None
        self.tile_farm = None
        self._mesh = None
        self._mesh_devices = mesh_devices
        self._registry = None
        self.worker_id = constants.WORKER_ID.get()
        self.worker_index = constants.WORKER_INDEX.get()
        # fleet cache tier (cluster/cache/fleet.py): consistent-hash
        # shards over the configured hosts + drain handback + near tier;
        # None under CDT_FLEET_CACHE=0 or CDT_CACHE=0 (per-host only)
        if self.cache is not None:
            from .cache.fleet import build_fleet_cache

            self.cache.fleet = build_fleet_cache(
                self.cache, self.worker_id or "master",
                self._fleet_membership)
        from .progress import ProgressTracker
        self.progress = ProgressTracker()
        # AOT warmup state machine (diffusion/warmup.py): health probes
        # report cold/warming/ready and the dispatcher prefers hot hosts
        from ..diffusion.warmup import WarmupManager

        self.warmup = WarmupManager(lambda: self.model_registry,
                                    lambda: self.mesh)
        self._warmup_task = None
        # elastic fleet (cluster/elastic): drain coordination always;
        # the autoscaler loop only under CDT_AUTOSCALE=1. Built at
        # startup — the drain coordinator schedules asyncio tasks and
        # needs the serving loop.
        self.elastic = None

    def load_config(self) -> dict:
        return load_config(self.config_path)

    def _fleet_membership(self) -> dict:
        """Fleet-cache ring membership: every configured host id → base
        URL, plus this worker (URL None — it never probes itself). The
        fleet tier itself filters DRAIN-leaving workers, so this stays a
        plain config read."""
        from ..utils.network import build_host_url

        members: dict = {(self.worker_id or "master"): None}
        try:
            for h in self.load_config().get("hosts", []):
                hid = str(h.get("id") or "")
                if hid and hid not in members:
                    members[hid] = build_host_url(h) or None
        except Exception:  # noqa: BLE001 — a bad config is an empty fleet
            pass
        return members

    def host_by_id(self, host_id: str) -> Optional[dict]:
        """Config host entry for a worker/host id (busy-probe resolver)."""
        for h in self.load_config().get("hosts", []):
            if str(h.get("id")) == str(host_id):
                return h
        return None

    # --- lazily-built heavyweight state ------------------------------------

    @property
    def mesh(self):
        if self._mesh is None:
            import jax

            from ..parallel.mesh import mesh_from_config, build_mesh

            if self._mesh_devices:
                self._mesh = build_mesh(
                    {"dp": self._mesh_devices}, jax.devices()[: self._mesh_devices])
            else:
                self._mesh = mesh_from_config(self.load_config())
        return self._mesh

    @property
    def model_registry(self):
        if self._registry is None:
            from ..models.registry import ModelRegistry

            root = constants.CHECKPOINT_ROOT.get()
            self._registry = ModelRegistry(Path(root) if root else None)
            if self._registry.residency is not None:
                # HBM planning must match the mesh that actually shards
                # weights: the tp degree of THIS worker's serving mesh
                # (docs/parallelism.md), not a free-floating knob —
                # planned bytes and held bytes diverge otherwise
                self._registry.residency.tp_shards_fn = (
                    lambda: dict(self.mesh.shape).get(
                        constants.AXIS_TENSOR, 1))
        return self._registry

    def _execution_context(self) -> dict[str, Any]:
        ctx: dict[str, Any] = {
            "mesh": self.mesh,
            "model_registry": self.model_registry,
            "output_dir": constants.OUTPUT_DIR.get(),
            "input_dir": constants.INPUT_DIR.get(),
            "job_store": self.store,
            "is_worker": self.is_worker,
            "worker_id": self.worker_id,
            "worker_index": self.worker_index,
            "progress_tracker": self.progress,
            # content cache (cluster/cache): CLIPTextEncode reads it as a
            # hidden input; the microbatch executor serves/fills the
            # result tier through it
            "content_cache": self.cache,
        }
        if self.bridge is not None:
            ctx["collector_bridge"] = self.bridge
        if self.tile_farm is not None:
            ctx["tile_farm"] = self.tile_farm
        return ctx

    # --- lifecycle ----------------------------------------------------------

    async def startup(self) -> None:
        from .tile_farm import TileFarm

        self.loop = asyncio.get_running_loop()
        self.bridge = CollectorBridge(self.store, self.loop,
                                      host_resolver=self.host_by_id)
        self.tile_farm = TileFarm(self.store, self.loop)
        if self.cache is not None and self.cache.fleet is not None:
            # remote probes/fills bridge from worker threads onto this
            # loop; until attach the ladder degrades to local-only
            self.cache.fleet.attach_loop(self.loop)
        self.queue.start()
        if self.frontdoor is not None:
            self.frontdoor.start()
        from .elastic import build_elastic

        self.elastic = build_elastic(self)
        self.elastic.start()
        role = "worker" if self.is_worker else "master"
        log(f"controller up as {role} (machine {machine_id()})")
        if self.is_worker and self.worker_id:
            # self-report ready → master clears this worker's launching
            # flag (reference handshake, api/worker_routes.py:115-139);
            # reference kept so the task can't be GC'd before running
            self._ready_task = asyncio.ensure_future(self._report_ready())
        if constants.WARMUP.get():
            # AOT warmup off the request path: compiles run in their own
            # thread (NOT the graph-exec pool — a dispatched prompt must
            # not queue behind the whole catalog); health reports
            # "warming" until the pass finishes, so the master's
            # dispatcher steers work to already-hot peers meanwhile
            self._warmup_task = self.loop.run_in_executor(
                None, self.warmup.run)

    async def _report_ready(self) -> None:
        import aiohttp

        from ..utils.network import get_client_session

        master_port = constants.MASTER_PORT.get()
        if not master_port:
            return
        url = (f"http://127.0.0.1:{master_port}"
               "/distributed/worker/clear_launching")
        try:
            session = get_client_session()
            async with session.post(
                url, json={"worker_id": self.worker_id},
                timeout=aiohttp.ClientTimeout(total=constants.PROBE_TIMEOUT),
            ) as resp:
                await resp.read()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            pass                       # master gone or standalone worker

    async def shutdown(self) -> None:
        from ..utils.network import close_client_session

        if self.elastic is not None:
            await self.elastic.stop()
        if self.frontdoor is not None:
            await self.frontdoor.stop()
        if self.stages is not None:
            # stop the stage pools BEFORE the queue: leftover decode
            # items record interrupted history through the queue's
            # callbacks, which must still be alive
            self.stages.stop()
        await self.queue.stop()
        if self.cache is not None and self.cache.fleet is not None:
            self.cache.fleet.close()   # unsubscribe from the DRAIN feed
        self.progress.close()      # release the global progress sink
        await close_client_session()

    # --- health -------------------------------------------------------------

    def health(self) -> dict:
        return {
            "status": "ok",
            "role": "worker" if self.is_worker else "master",
            "queue_remaining": self.queue.queue_remaining,
            "executing": self.queue.executing,
            "machine_id": machine_id(),
            # cold | warming | ready | error — dispatch prefers hosts
            # that are not mid-warmup (cluster/dispatch.py)
            "warmup": self.warmup.state,
            # coalescing + queued depth the admission layer sheds on
            "frontdoor": (None if self.frontdoor is None
                          else {"depth": self.frontdoor.depth(),
                                "coalescing":
                                    self.frontdoor.batcher.pending_count}),
            # content-cache hit rate (cluster/cache, docs/caching.md) —
            # the signal that lets the autoscaler shrink a hot-cache fleet
            "cache": (None if self.cache is None
                      else {"hit_rate": round(self.cache.hit_rate(), 4),
                            "fleet_ring":
                                (len(self.cache.fleet.ring()[0])
                                 if self.cache.fleet is not None
                                 else 0)}),
            # per-stage pool backlog (cluster/stages, docs/stages.md)
            "stages": (None if self.stages is None
                       else self.stages.depths()),
        }

    def system_info_no_devices(self) -> dict:
        """Host facts that never touch the device backend — the degraded
        payload when the accelerator service is unresponsive
        (``utils/deadline.py``)."""
        return {
            "machine_id": machine_id(),
            "platform": platform.system().lower(),
            "path_separator": os.sep,
            "python": platform.python_version(),
            "is_docker": Path("/.dockerenv").exists(),
            "environment": detect_environment(),
        }

    def system_info(self) -> dict:
        """Parity: ``/distributed/system_info``
        (``api/worker_routes.py:393-430``) with TPU topology instead of a
        CUDA census."""
        from ..parallel.mesh import device_census

        info = self.system_info_no_devices()
        info["devices"] = device_census()
        return info

    def clear_memory(self) -> dict:
        """Parity: ``/distributed/clear_memory`` (``api/job_routes.py:160-203``)
        — unload models + drop compiled programs. TPU equivalent: clear the
        model registry cache, JAX compilation caches, and live device
        buffers owned by caches."""
        import gc

        import jax

        self._registry = None
        self._mesh = None
        jax.clear_caches()
        gc.collect()
        return {"status": "cleared"}
