"""Multi-tenant serving front door (ROADMAP item 1).

The cluster used to hand every ``POST /distributed/queue`` request
straight to the orchestrator, which executes one prompt-queue job at a
time per host. Under production traffic — thousands of concurrent
requests against a handful of compiled programs — that serializes the
fleet on Python dispatch overhead and gives no story for overload. The
front door is the subsystem in between:

- :mod:`classifier` decides whether a request is *microbatchable* (one
  ``TPUTxt2Img`` over a statically-known program geometry) and under
  which :class:`~.classifier.GroupKey` same-shape requests coalesce.
- :mod:`admission` gates the doorway: priority classes, per-tenant
  token-bucket fairness, queue-depth backpressure, and explicit
  overload shedding (HTTP 429 + ``Retry-After``) wired into the
  circuit-breaker health signal.
- :mod:`batcher` holds admitted batchable requests in a short per-key
  coalescing window and flushes same-shape groups to the prompt queue
  as one batch job, highest priority first.
- :mod:`microbatch` executes a flushed group: per-member graph prefixes,
  ONE microbatched SPMD program for the sampler stage
  (``diffusion.pipeline.generate_microbatch`` — outputs bit-identical
  to solo runs), then per-member suffixes, with per-member error
  isolation and solo fallback.

Non-batchable requests pass through to the orchestrator unchanged (the
legacy path, still behind admission control). ``CDT_FRONTDOOR=0``
removes the subsystem entirely.

See ``docs/serving.md`` for the request lifecycle and tuning knobs.
"""

from __future__ import annotations

import asyncio
import dataclasses
import secrets
import time
from typing import Optional

from ... import telemetry
from ...telemetry import metrics as _tm
from ...utils import constants
from ...utils.logging import log
from ..runtime import PromptJob, PromptQueue
from .admission import AdmissionController, Decision
from .batcher import CoalescingBatcher
from .classifier import Classification, classify
from .classifier import fingerprint as classifier_fingerprint


def frontdoor_enabled() -> bool:
    return constants.FRONTDOOR.get()


@dataclasses.dataclass
class FrontDoorResult:
    """What ``POST /distributed/queue`` answers with.

    ``outcome``: ``admitted`` | ``queued`` | ``shed``. Shed results carry
    ``retry_after_s`` and never a prompt id; admitted results carry the
    member/orchestration prompt id (or ``node_errors``)."""

    outcome: str
    prompt_id: str = ""
    node_errors: list = dataclasses.field(default_factory=list)
    worker_count: int = 0
    trace_id: str = ""
    batched: bool = False
    reason: str = ""
    retry_after_s: float = 0.0
    # this request joined an in-flight byte-identical execution
    # (cluster/cache/coalesce.py) — it never entered the queue
    coalesced: bool = False


class FrontDoor:
    """The serving front door: admission → classification → coalescing.

    One instance per controller, started on the controller's event loop.
    """

    def __init__(self, queue: PromptQueue, orchestrator,
                 config_loader=None, cache=None, stages=None):
        self.queue = queue
        self.orchestrator = orchestrator
        self.load_config = config_loader
        # content cache (cluster/cache): in-flight coalescing happens
        # HERE, before the batcher — a byte-identical twin of a queued
        # request must never occupy a second queue slot
        self.cache = cache
        # stage-split serving (cluster/stages): queue slots free at
        # denoise-done, so admission must ALSO see the encode/decode
        # backlog or overload would pile up unbounded past the queue
        self.stages = stages
        self.admission = AdmissionController(depth_provider=self.depth)
        # capacity gate = continuous batching: while FD_INFLIGHT batch
        # jobs sit in the queue, ready groups keep absorbing same-shape
        # arrivals instead of fragmenting into singleton flushes
        self.batcher = CoalescingBatcher(
            self._enqueue_group,
            capacity=lambda: queue.queue_remaining < constants.FD_INFLIGHT)
        self._task: Optional[asyncio.Task] = None
        self._classified: dict[str, int] = {}   # reason -> count (stats)

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self.batcher.run())
        # completed jobs free queue slots: wake the batcher so the next
        # ready group flushes immediately instead of on its timer, and
        # settle coalesced waiters whose leader just reached a terminal
        # history entry
        self.queue.add_job_done_callback(self._on_job_done)

    def _on_job_done(self) -> None:
        self.batcher.wake()
        if self.cache is not None:
            self.cache.coalescer.resolve(self.queue.history,
                                         redispatch=self._redispatch)

    def _redispatch(self, member, group_key, sampler_node_id) -> None:
        """An expired-leader waiter gets a FRESH execution (its own
        deadline allowed one): it becomes the new leader for the
        fingerprint and re-enters the batcher."""
        if member.fingerprint is not None:
            self.cache.coalescer.lead(member.fingerprint, member.prompt_id)
        self.batcher.submit(group_key, member,
                            sampler_node_id=sampler_node_id)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # --- signals ------------------------------------------------------------

    def depth(self) -> int:
        """The admission/backpressure signal: everything queued or
        executing on this controller PLUS everything coalescing in the
        front door PLUS the stage pools' host-side backlog (stage-split
        serving frees queue slots at denoise-done — without the stage
        term, overload would pile up unbounded in the decode pool).
        This is the quantity admission sheds on; the FLEET autoscaler
        deliberately reads :meth:`denoise_depth` instead
        (docs/stages.md)."""
        depth = self.queue.queue_remaining + self.batcher.pending_count
        if self.stages is not None:
            depth += self.stages.depth()
        return depth

    def denoise_depth(self) -> int:
        """The DENOISE-facing depth: queued/executing prompts plus the
        coalescing window — what sizing the chip fleet should read. A
        decode/encode backlog is a host-pool problem (the stage
        rebalancer's), never a reason to scale denoise chips — the
        FleetSignals split (cluster/elastic, docs/stages.md)."""
        return self.queue.queue_remaining + self.batcher.pending_count

    # --- the doorway --------------------------------------------------------

    async def submit(self, payload) -> FrontDoorResult:
        """Admission-check, classify, and route one queue request.

        ``payload`` is an ``api.queue_request.QueueRequestPayload``."""
        decision: Decision = self.admission.admit(payload.tenant,
                                                  payload.priority)
        if decision.outcome == "shed":
            return FrontDoorResult(outcome="shed", reason=decision.reason,
                                   retry_after_s=decision.retry_after_s)

        deadline_at = (time.monotonic() + payload.deadline_ms / 1000.0
                       if payload.deadline_ms else None)
        checkpoint_id = self._resolve_resume(payload)
        if checkpoint_id is not None:
            # resume request (docs/preemption.md): the run continues
            # mid-ladder from a parked checkpoint — a solo trajectory by
            # definition, so it bypasses coalescing/batching and rides
            # the orchestration path with its checkpoint id
            cls = Classification(batchable=False, reason="resume")
        else:
            cls = classify(payload.prompt)
        self._classified[cls.reason] = self._classified.get(cls.reason, 0) + 1

        if not cls.batchable:
            # legacy path: full orchestration (fan-out, media sync, …),
            # now carrying the request's admission metadata into the queue
            result = await self.orchestrator.orchestrate(
                payload.prompt,
                client_id=payload.client_id,
                enabled_ids=payload.enabled_worker_ids,
                delegate_master=payload.delegate_master,
                load_balance=payload.load_balance,
                trace_id=payload.trace_id,
                queue_meta={"tenant": payload.tenant,
                            "priority": payload.priority,
                            "deadline_at": deadline_at,
                            "checkpoint_id": checkpoint_id},
            )
            return FrontDoorResult(
                outcome=decision.outcome, prompt_id=result.prompt_id,
                node_errors=result.node_errors,
                worker_count=result.worker_count,
                trace_id=result.trace_id, reason=cls.reason)

        # batchable: validate NOW (the legacy path rejects invalid prompts
        # synchronously; coalescing must not turn that into a deferred
        # history-only error), then coalesce
        from ...graph.executor import strip_meta, validate_prompt

        prompt = strip_meta(payload.prompt)
        errors = validate_prompt(prompt)
        if errors:
            return FrontDoorResult(outcome=decision.outcome,
                                   node_errors=[e.as_dict() for e in errors],
                                   reason=cls.reason)
        from ...utils.logging import new_trace_id

        trace_id = payload.trace_id or new_trace_id()
        fingerprint = classifier_fingerprint(prompt)
        member = PromptJob(
            prompt_id=f"p_{int(time.time()*1000)}_{secrets.token_hex(3)}",
            prompt=prompt, client_id=payload.client_id,
            trace_id=trace_id,
            tenant=payload.tenant, priority=payload.priority,
            deadline_at=deadline_at,
            fingerprint=fingerprint, cache_mode=payload.cache,
        )
        if self.cache is not None and payload.cache != "bypass":
            if self.cache.coalescer.join(fingerprint, member,
                                         group_key=cls.group_key,
                                         sampler_node_id=cls.sampler_node_id):
                # byte-identical twin already in flight: this request
                # rides that ONE execution; its own history entry lands
                # when the leader's does (cluster/cache/coalesce.py).
                # NOT recorded in the autoscaler's hit window — a waiter
                # never occupies a queue slot, so discounting queue
                # depth by the coalesce rate would double-count
                return FrontDoorResult(outcome=decision.outcome,
                                       prompt_id=member.prompt_id,
                                       trace_id=trace_id, batched=True,
                                       coalesced=True, reason=cls.reason)
            self.cache.coalescer.lead(fingerprint, member.prompt_id)
        self.batcher.submit(cls.group_key, member,
                            sampler_node_id=cls.sampler_node_id)
        if telemetry.enabled():
            _tm.FD_QUEUE_DEPTH.labels(
                stage="coalescing", priority=payload.priority).set(
                    self.batcher.pending_by_priority().get(
                        payload.priority, 0))
        return FrontDoorResult(outcome=decision.outcome,
                               prompt_id=member.prompt_id,
                               trace_id=trace_id,
                               batched=True, reason=cls.reason)

    # --- plumbing -----------------------------------------------------------

    def _resolve_resume(self, payload) -> "str | None":
        """Checkpoint id this request resumes from (resume-on-any-
        worker: an inline wire-form checkpoint rode the same queue
        transport as the prompt). One shared policy with the legacy
        route — ``cluster.preemption.resolve_resume``."""
        from ..preemption import resolve_resume

        return resolve_resume(getattr(self.queue, "preemption", None),
                              payload.checkpoint_id, payload.checkpoint)

    def _enqueue_group(self, members: list, sampler_node_ids: dict) -> None:
        self.queue.enqueue_batch(members, sampler_node_ids)
        if telemetry.enabled():
            for prio, n in self.batcher.pending_by_priority().items():
                _tm.FD_QUEUE_DEPTH.labels(stage="coalescing",
                                          priority=prio).set(n)

    def stats(self) -> dict:
        """The ``GET /distributed/frontdoor`` payload (dashboard row +
        operator probe)."""
        return {
            "enabled": True,
            "depth": self.depth(),
            "queue_remaining": self.queue.queue_remaining,
            "coalescing": self.batcher.pending_count,
            "pending_by_priority": self.batcher.pending_by_priority(),
            "groups": self.batcher.group_summary(),
            "admission": self.admission.summary(),
            "classified": dict(self._classified),
            "window_ms": self.batcher.window_ms,
            "max_batch": self.batcher.max_batch,
            "cache": (None if self.cache is None
                      else {"hit_rate": round(self.cache.hit_rate(), 4),
                            **self.cache.coalescer.stats()}),
            "stages": (None if self.stages is None
                       else self.stages.depths()),
        }


def build_frontdoor(queue: PromptQueue, orchestrator,
                    config_loader=None, cache=None,
                    stages=None) -> Optional[FrontDoor]:
    """Controller hook: the front door, or None under CDT_FRONTDOOR=0."""
    if not frontdoor_enabled():
        log("front door disabled (CDT_FRONTDOOR=0) — legacy queue path")
        return None
    return FrontDoor(queue, orchestrator, config_loader, cache=cache,
                     stages=stages)
