"""Admission control: who gets in, who waits, who is told to come back.

Three gates, applied in order, all deterministic (testable with a fake
clock):

1. **Per-tenant token bucket** — a tenant sustaining more than
   ``CDT_FD_TENANT_RATE`` req/s (burst ``CDT_FD_TENANT_BURST``) is shed
   with ``Retry-After`` sized to the bucket's refill, regardless of how
   idle the fleet is. This is the fairness floor: one hot tenant cannot
   monopolize the coalescing windows or starve the queue.
2. **Priority-aware depth shedding** — the controller depth signal
   (queued + executing + coalescing; the quantity
   ``cdt_prompt_queue_depth`` exports, extended by the front-door
   window) is compared against ``CDT_FD_SHED_DEPTH``. The lowest
   priority class sheds at half the threshold, so background load
   drains out of an overloaded fleet first.
3. **Breaker-scaled capacity** — when the circuit-breaker registry
   reports a degraded fleet (workers open/half-open), the shed
   threshold scales down by the healthy fraction: a half-dead fleet
   sheds at half depth instead of queueing work it will time out on
   (docs/resilience.md).

Outcomes map onto ``cdt_admission_total{outcome=admitted|queued|shed}``:
``queued`` is an *accepted* request past the soft high-watermark
(``CDT_FD_SOFT_DEPTH``) — the client proceeds, but the response says the
fleet is busy.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import OrderedDict
from typing import Callable, Optional

from ... import telemetry
from ...telemetry import metrics as _tm
from ...utils import constants


@dataclasses.dataclass(frozen=True)
class Decision:
    outcome: str                 # admitted | queued | shed
    reason: str = ""             # ok | busy | overload | tenant_rate
    retry_after_s: float = 0.0
    depth: int = 0


class TokenBucket:
    """Classic token bucket, clock-injected for determinism."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = max(rate, 1e-9)
        self.burst = burst
        self._level = burst
        self._clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._level = min(self.burst,
                          self._level + (now - self._last) * self.rate)
        self._last = now

    def take(self) -> bool:
        self._refill()
        if self._level >= 1.0:
            self._level -= 1.0
            return True
        return False

    def seconds_until_token(self) -> float:
        self._refill()
        if self._level >= 1.0:
            return 0.0
        return (1.0 - self._level) / self.rate


def breaker_healthy_fraction() -> float:
    """Closed breakers / tracked breakers (half-open counts half); 1.0
    when nothing is tracked (single-host or fresh boot).

    Workers that are intentionally leaving (draining/decommissioned —
    ``cluster/elastic/states``) are excluded from BOTH sides of the
    ratio: a scale-down makes the fleet *smaller*, not *sicker*, and
    shedding admission capacity for a planned departure would turn every
    autoscale event into a synthetic brownout."""
    from ..elastic.states import DRAIN
    from ..resilience import BREAKERS

    states = {w: s for w, s in BREAKERS.states().items()
              if not DRAIN.is_leaving(w)}
    if not states:
        return 1.0
    score = {"closed": 1.0, "half_open": 0.5, "open": 0.0}
    return sum(score.get(s, 0.0) for s in states.values()) / len(states)


class AdmissionController:
    def __init__(
        self,
        depth_provider: Callable[[], int],
        *,
        soft_depth: Optional[int] = None,
        shed_depth: Optional[int] = None,
        tenant_rate: Optional[float] = None,
        tenant_burst: Optional[float] = None,
        healthy_fraction: Callable[[], float] = breaker_healthy_fraction,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.depth_provider = depth_provider
        self.soft_depth = (constants.FD_SOFT_DEPTH if soft_depth is None
                           else soft_depth)
        self.shed_depth = (constants.FD_SHED_DEPTH if shed_depth is None
                           else shed_depth)
        self.tenant_rate = (constants.FD_TENANT_RATE if tenant_rate is None
                            else tenant_rate)
        self.tenant_burst = (constants.FD_TENANT_BURST if tenant_burst is None
                             else tenant_burst)
        self.healthy_fraction = healthy_fraction
        self._clock = clock
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._counts: dict[str, int] = {}

    # --- tenant buckets -----------------------------------------------------

    def _bucket(self, tenant: str) -> TokenBucket:
        b = self._buckets.get(tenant)
        if b is None:
            if len(self._buckets) >= constants.FD_MAX_TENANTS:
                # LRU eviction: an evicted-then-returning tenant simply
                # gets a fresh (full) bucket — bounded memory beats
                # perfect rate memory for the long tail
                self._buckets.popitem(last=False)
            b = TokenBucket(self.tenant_rate, self.tenant_burst,
                            clock=self._clock)
            self._buckets[tenant] = b
        else:
            self._buckets.move_to_end(tenant)
        return b

    # --- the decision -------------------------------------------------------

    def shed_threshold(self, priority: str) -> int:
        """Effective shed depth for one priority class right now:
        breaker-degraded fleets scale it down (never below a quarter —
        a fully-open registry still serves the master's own capacity),
        and the lowest class sheds at half."""
        frac = max(0.25, self.healthy_fraction())
        threshold = max(1, int(self.shed_depth * frac))
        if priority == constants.PRIORITY_CLASSES[-1]:
            threshold = max(1, threshold // 2)
        return threshold

    def admit(self, tenant: str, priority: str) -> Decision:
        depth = int(self.depth_provider())
        threshold = self.shed_threshold(priority)

        # depth shed BEFORE the token bucket: an overload shed must not
        # burn the tenant's rate budget — a client that obeys Retry-After
        # would otherwise drain its bucket on rejected requests and keep
        # shedding (with the wrong reason) after the overload clears
        if depth >= threshold:
            ratio = depth / max(1, threshold)
            retry = min(30.0, math.ceil(constants.FD_RETRY_AFTER_S * ratio))
            decision = Decision("shed", "overload", retry_after_s=retry,
                                depth=depth)
        elif not self._bucket(tenant).take():
            wait = self._bucket(tenant).seconds_until_token()
            decision = Decision("shed", "tenant_rate",
                                retry_after_s=max(1.0, math.ceil(wait)),
                                depth=depth)
        elif depth >= min(self.soft_depth, threshold):
            decision = Decision("queued", "busy", depth=depth)
        else:
            decision = Decision("admitted", "ok", depth=depth)

        self._counts[decision.outcome] = \
            self._counts.get(decision.outcome, 0) + 1
        if telemetry.enabled():
            _tm.ADMISSION_TOTAL.labels(outcome=decision.outcome,
                                       priority=priority).inc()
        return decision

    def summary(self) -> dict:
        return {
            "outcomes": dict(self._counts),
            "tenants_tracked": len(self._buckets),
            "soft_depth": self.soft_depth,
            "shed_depth": self.shed_depth,
            "healthy_fraction": round(self.healthy_fraction(), 3),
        }
