"""Group execution: N coalesced prompts, ONE sampler program.

A flushed group executes in the prompt queue's graph-exec thread as a
single unit:

1. **Prefix** — each member's graph runs normally up to (excluding) its
   sampler node: checkpoint load, text encode, seed derivation. Members
   share the model registry, so the checkpoint builds once.
2. **Stack** — each member's sampler inputs are resolved exactly as the
   executor would (``graph.executor.node_kwargs``) and sub-grouped by
   execution signature (pipeline identity, spec, conditioning shapes) —
   the classifier's static key is re-checked against *runtime* facts, so
   a tokenizer emitting a different context length degrades that member
   to solo instead of corrupting the stack.
3. **One program** — each sub-group of ≥2 runs
   ``pipeline.generate_microbatch`` (bit-identical demux; see
   ``diffusion/pipeline.py``); singletons run the sampler node's own
   ``execute`` — the *pass-through path*, byte-for-byte the solo code.
4. **Suffix** — each member's remaining nodes run with the demuxed
   images injected as the sampler's output.

Error isolation: a member failing in prefix/suffix fails alone; a failed
*batched program* falls every member of that sub-group back to a full
solo execution — an admitted job is never lost to batching.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from ... import telemetry
from ...graph.executor import GraphExecutor, node_kwargs, topo_order
from ...telemetry import metrics as _tm
from ...utils.logging import debug_log, log


def downstream_nodes(prompt: dict, root: str) -> set:
    """Transitive consumers of ``root``'s outputs (not including it)."""
    consumers: dict[str, set] = {}
    for nid, node in prompt.items():
        for v in node.get("inputs", {}).values():
            if isinstance(v, (list, tuple)) and len(v) == 2:
                consumers.setdefault(str(v[0]), set()).add(nid)
    out: set = set()
    frontier = [root]
    while frontier:
        for nxt in consumers.get(frontier.pop(), ()):
            if nxt not in out:
                out.add(nxt)
                frontier.append(nxt)
    return out


class _Prepared:
    """One member after prefix execution, ready to stack."""

    def __init__(self, member, sampler_id: str, executor: GraphExecutor,
                 cache: dict, order: list, kwargs: dict):
        self.member = member
        self.sampler_id = sampler_id
        self.executor = executor
        self.cache = cache
        self.order = order
        self.kwargs = kwargs
        self.spec = None
        self.seed = None
        self.context = None
        self.uncond = None
        self.y = None
        self.uy = None
        self.pipeline = None
        self.model = None
        self.mesh = None
        self.stackable = False
        self.why_solo = ""
        self.result_key = None   # content-cache identity (cluster/cache)
        self.near_key = None     # seedless identity (fleet near tier)

    def signature(self) -> tuple:
        return (id(self.pipeline), self.spec,
                tuple(self.context.shape), tuple(self.uncond.shape),
                None if self.y is None else tuple(self.y.shape))


def _prepare(member, sampler_id: str, base_context: dict) -> _Prepared:
    """Run one member's prefix and resolve its sampler-call inputs."""
    from ...diffusion.pipeline import GenerationSpec
    from ...graph import nodes_builtin as nb

    prompt = member.prompt
    context = dict(base_context)
    context["prompt_id"] = member.prompt_id
    executor = GraphExecutor(context)
    order = topo_order(prompt)
    # set used for MEMBERSHIP only (`n not in down` below); iteration
    # order is never observed, so set-order nondeterminism can't leak
    # into the executed prefix
    down = downstream_nodes(prompt, sampler_id)  # cdtlint: disable=D002
    prefix = [n for n in order if n != sampler_id and n not in down]
    cache: dict[str, tuple] = {}
    executor.execute_nodes(prompt, prefix, cache)

    kwargs = node_kwargs(prompt, sampler_id, cache, context)
    prep = _Prepared(member, sampler_id, executor, cache, order, kwargs)

    model = kwargs["model"]
    prep.model = model
    positive, negative = kwargs["positive"], kwargs["negative"]
    prep.spec = GenerationSpec(
        height=int(kwargs["height"]), width=int(kwargs["width"]),
        steps=int(kwargs["steps"]),
        sampler=kwargs.get("sampler_name", "euler"),
        scheduler=kwargs.get("scheduler", "karras"),
        guidance_scale=float(kwargs["cfg"]),
        per_device_batch=int(kwargs.get("batch_per_device", 1)),
    )
    prep.seed = int(kwargs["seed"])
    prep.pipeline = model.pipeline
    prep.mesh = context.get("mesh")
    if isinstance(positive, dict) and positive.get("control"):
        # classifier can't see control riding the conditioning dict
        prep.why_solo = "control_conditioning"
        return prep
    adm = model.pipeline.unet.config.adm_in_channels
    prep.context = positive["context"]
    prep.uncond = negative["context"]
    prep.y = nb._adm_from_cond(positive, adm) if adm else None
    prep.uy = nb._adm_from_cond(negative, adm) if adm else None
    if prep.mesh is None:
        prep.why_solo = "no_mesh"
        return prep
    if not hasattr(prep.pipeline, "generate_microbatch"):
        prep.why_solo = "pipeline_unsupported"
        return prep
    prep.stackable = True
    return prep


def _finish(prep: _Prepared, images) -> dict:
    """Inject the sampler output, run the suffix, return the full cache."""
    prep.cache[prep.sampler_id] = (images,)
    suffix = [n for n in prep.order if n not in prep.cache]
    prep.executor.execute_nodes(prep.member.prompt, suffix, prep.cache)
    return prep.cache


def _solo(prep: _Prepared) -> Any:
    """Pass-through: the sampler node's OWN execute (identical to a solo
    queue job — same compiled program, same progress streaming)."""
    from ...graph.node import get_node

    cls = get_node(prep.member.prompt[prep.sampler_id]["class_type"])
    return cls().execute(**prep.kwargs)[0]


def execute_group(members: list, sampler_node_ids: dict,
                  base_context: dict) -> dict:
    """Execute one flushed group. Returns ``{prompt_id: entry}`` where
    each entry mirrors a PromptQueue history record
    (``status``/``outputs``/``error`` + ``batch_size``). On interrupt
    the PARTIAL results are returned — members that already finished
    keep their success entries; the runtime marks the missing ones
    interrupted (solo jobs that finish before an interrupt keep their
    history too; batch members must not be worse off)."""
    results: dict[str, dict] = {}
    try:
        _execute_group_inner(members, sampler_node_ids, base_context,
                             results)
    except InterruptedError:
        pass
    return results


def _cache_key_for(p: _Prepared, cache) -> "str | None":
    """Result-tier key for one prepared member: request fingerprint ×
    execution signature × conditioning-degradation mode × weights
    provenance — or None when the member is uncacheable (no
    fingerprint, no manager, or a bundle that can't state its weights
    provenance — an unknown-weights bundle must never share entries)."""
    if cache is None or p.member.fingerprint is None:
        return None
    from ..cache import execution_signature, result_key
    from ..cache.conditioning import encoder_mode

    weights_fn = getattr(p.model, "weights_identity", None)
    if weights_fn is None:
        return None
    mode = encoder_mode(getattr(p.model, "text_encoder", None))
    return result_key(p.member.fingerprint, execution_signature(p.mesh),
                      mode, weights_fn())


def _serve_cached(p: _Prepared, cache, results: dict) -> bool:
    """Serve one member from the completed-result tier; the member still
    runs its suffix (SaveImage et al. side effects are real), only the
    sampler program is skipped. ``cache: "bypass"`` members never serve
    (they re-execute and refresh the entry). The fallback ladder is
    local memory → local disk (both inside ``results.get``) → fleet
    ring owner (``fleet.probe``) → recompute."""
    p.result_key = _cache_key_for(p, cache)
    if p.result_key is None or p.member.cache_mode == "bypass":
        return False
    hit = cache.results.get(p.result_key)
    if hit is None:
        fleet = getattr(cache, "fleet", None)
        if fleet is not None:
            hit = fleet.probe(p.result_key)
            if hit is not None and "images" in hit:
                # promote memory-only: the entry's durable home is its
                # ring owner's shard, not every prober's disk
                cache.results.put(p.result_key, hit, persist=False)
    if hit is None or "images" not in hit:
        return False
    import jax.numpy as jnp

    try:
        out_cache = _finish(p, jnp.asarray(hit["images"]))
    except InterruptedError:
        raise
    except Exception as e:  # noqa: BLE001 — member isolation barrier
        results[p.member.prompt_id] = {"status": "error", "error": str(e)}
        log(f"front door: cached-suffix failed for "
            f"{p.member.prompt_id}: {e}")
        return True
    results[p.member.prompt_id] = {"status": "success",
                                   "outputs": out_cache,
                                   "cache": "hit", "batch_size": 0}
    cache.record_request(hit=True)
    return True


def _fill_cache(p: _Prepared, cache, images) -> None:
    """Record a freshly computed sampler output (miss or bypass refresh);
    a fill failure must never sink the request that just computed it."""
    if cache is None or p.result_key is None:
        return
    import numpy as np

    try:
        arrays = {"images": np.asarray(images)}
        cache.results.put(p.result_key, arrays)
        fleet = getattr(cache, "fleet", None)
        if fleet is not None:
            # fire-and-forget to the ring owner — the serve path never
            # blocks on a remote PUT
            fleet.fill(p.result_key, arrays)
    except Exception as e:  # noqa: BLE001
        debug_log(f"result cache: fill failed for "
                  f"{p.result_key[:12]}: {e}")


def _filled_adm(p: _Prepared) -> tuple:
    """(y, uy) with the same zero-ADM defaults ``generate_preemptible``
    applies before computing the checkpoint identity — the near tier's
    ``expect`` meta must hash the SAME conditioning tuple the donor's
    identity hashed, or every lookup is a spurious mismatch."""
    import jax.numpy as jnp

    y, uy = p.y, p.uy
    if y is None:
        adm = p.pipeline.unet.config.adm_in_channels
        y = jnp.zeros((1, max(adm, 1)), jnp.float32)
    if uy is None:
        uy = jnp.zeros_like(y)
    return y, uy


def _near_key_for(p: _Prepared, cache) -> "str | None":
    """Seedless near-tier identity: the same factors as
    :func:`_cache_key_for` over the seed-masked fingerprint — or None
    when the member didn't opt in (``cache: "near"``), the fleet tier is
    off, or the member can't stack (the donor path needs the pipeline
    APIs stackability proves)."""
    if cache is None or getattr(cache, "fleet", None) is None:
        return None
    if not p.stackable or p.member.cache_mode != "near":
        return None
    from ..cache import execution_signature, near_fingerprint, near_key
    from ..cache.conditioning import encoder_mode

    weights_fn = getattr(p.model, "weights_identity", None)
    if weights_fn is None:
        return None
    mode = encoder_mode(getattr(p.model, "text_encoder", None))
    return near_key(near_fingerprint(p.member.prompt),
                    execution_signature(p.mesh), mode, weights_fn())


def _serve_near(p: _Prepared, cache, results: dict) -> bool:
    """Serve one opted-in member from a donor mid-trajectory checkpoint:
    the donor's carry becomes the init of a partial-ladder re-roll under
    the member's OWN seed (roughly half the steps). The output is
    approximate BY DESIGN (docs/caching.md) and never fills the exact
    result tier; any failure degrades to a full compute."""
    p.near_key = _near_key_for(p, cache)
    if p.near_key is None or not hasattr(p.pipeline, "generate_near"):
        return False
    import dataclasses

    import numpy as np

    fleet = cache.fleet
    y, uy = _filled_adm(p)
    expect = p.pipeline.checkpoint_identity(
        p.mesh, p.spec, p.seed,
        conditioning=(p.context, p.uncond, y, uy))
    expect.pop("seed", None)       # near = the same work modulo seed
    ckpt = fleet.near.lookup(p.near_key, expect)
    if ckpt is None:
        return False
    remaining = int(ckpt.total_steps) - int(ckpt.step)
    if remaining <= 0 or remaining >= int(ckpt.total_steps):
        return False
    lat = next((np.asarray(leaf) for leaf in ckpt.carry
                if np.asarray(leaf).ndim == 4), None)
    if lat is None:
        return False
    try:
        spec_near = dataclasses.replace(
            p.spec, denoise=remaining / int(ckpt.total_steps))
        images = p.pipeline.generate_near(
            p.mesh, spec_near, p.seed,
            lat[: p.spec.per_device_batch], p.context, p.uncond, y, uy)
        out_cache = _finish(p, images)
    except InterruptedError:
        raise
    except Exception as e:  # noqa: BLE001 — member isolation barrier
        log(f"front door: near-tier serve failed for "
            f"{p.member.prompt_id} ({e}); computing from scratch")
        return False
    results[p.member.prompt_id] = {"status": "success",
                                   "outputs": out_cache,
                                   "cache": "near", "batch_size": 0}
    fleet.near.record_reuse(int(ckpt.step))
    return True


def _run_near_donor(p: _Prepared, cache):
    """Run a near-mode miss through the preemptible sampler, parking the
    midpoint carry as a donor for future re-rolls. Completion is
    bit-identical to the plain program (PR 14's invariant), so the
    caller fills the exact tier with the result as usual. Returns the
    images, or None to fall back to the plain solo path."""
    fleet = getattr(cache, "fleet", None)
    if fleet is None or not hasattr(p.pipeline, "generate_preemptible"):
        return None
    half = int(p.spec.steps) // 2
    if half < 1 or half >= int(p.spec.steps):
        return None                # a 1-step run has no midpoint
    fired = []

    def _once():
        if fired:
            return None
        fired.append(1)
        return "near_donor"

    out = p.pipeline.generate_preemptible(
        p.mesh, p.spec, p.seed, p.context, p.uncond, p.y, p.uy,
        segment_steps=half, should_preempt=_once)
    if "images" in out:
        return out["images"]
    ckpt = out["checkpoint"]
    try:
        fleet.near.offer(p.near_key, ckpt)
    except Exception as e:  # noqa: BLE001 — donor parking is best-effort
        debug_log(f"fleet.near: donor park failed: {e}")
    out = p.pipeline.generate_preemptible(
        p.mesh, p.spec, p.seed, p.context, p.uncond, p.y, p.uy,
        segment_steps=max(1, int(p.spec.steps)), resume=ckpt)
    return out.get("images")


def _execute_group_inner(members: list, sampler_node_ids: dict,
                         base_context: dict, results: dict) -> None:
    # telemetry wall-clock only: never feeds keys/outputs
    t0 = time.monotonic()  # cdtlint: disable=D001
    cache = base_context.get("content_cache")
    prepared: list[_Prepared] = []

    for m in members:
        try:
            prepared.append(_prepare(m, sampler_node_ids[m.prompt_id],
                                     base_context))
        except InterruptedError:
            raise
        except Exception as e:  # noqa: BLE001 — member isolation barrier
            results[m.prompt_id] = {"status": "error", "error": str(e)}
            log(f"front door: prefix failed for {m.prompt_id}: {e}")

    # completed-result cache (cluster/cache): a byte-identical request
    # the fleet has already answered skips its sampler program entirely
    served = [p for p in prepared if _serve_cached(p, cache, results)]
    prepared = [p for p in prepared if p not in served]
    if cache is not None:
        for p in prepared:
            if p.member.fingerprint is not None:
                cache.record_request(hit=False)

    # opt-in near tier (cluster/cache/fleet.py): a cache:"near" re-roll
    # that missed the exact tiers resumes a donor mid-trajectory
    # checkpoint instead of denoising from pure noise. A reduced program
    # still runs, so near serves stay misses in the autoscaler window
    # (counted above). Near misses are forced solo so the donor path
    # can park their midpoint for the next re-roll.
    near_served = [p for p in prepared if _serve_near(p, cache, results)]
    prepared = [p for p in prepared if p not in near_served]
    for p in prepared:
        if p.near_key is not None and p.stackable:
            p.stackable = False
            p.why_solo = "near_donor"

    # sub-group by runtime signature; order within a sub-group is
    # submission order (members arrive FIFO from the batcher)
    groups: dict[tuple, list[_Prepared]] = {}
    singles: list[_Prepared] = []
    for p in prepared:
        if p.stackable:
            groups.setdefault(p.signature(), []).append(p)
        else:
            singles.append(p)

    def run_solo(p: _Prepared, batch_size: int = 1) -> None:
        try:
            images = None
            if p.near_key is not None:
                try:
                    images = _run_near_donor(p, cache)
                except InterruptedError:
                    raise
                except Exception as e:  # noqa: BLE001 — plain solo next
                    debug_log(f"front door: near donor path failed for "
                              f"{p.member.prompt_id}: {e}")
            if images is None:
                images = _solo(p)
            _fill_cache(p, cache, images)
            out_cache = _finish(p, images)
            results[p.member.prompt_id] = {
                "status": "success", "outputs": out_cache,
                "batch_size": batch_size}
        except InterruptedError:
            raise
        except Exception as e:  # noqa: BLE001 — member isolation barrier
            results[p.member.prompt_id] = {"status": "error",
                                           "error": str(e)}
            log(f"front door: solo member {p.member.prompt_id} "
                f"failed: {e}")

    for p in singles:
        if telemetry.enabled():
            _tm.BATCH_SIZE.observe(1)
        run_solo(p)

    for sig, grp in groups.items():
        if len(grp) == 1:
            if telemetry.enabled():
                _tm.BATCH_SIZE.observe(1)
            run_solo(grp[0])
            continue
        lead = grp[0]
        try:
            # same residency discipline as the solo node path: with an
            # HBM budget set, a concurrent acquire must not evict this
            # bundle mid-program (cluster/residency.pinned_bundle)
            from ..residency import pinned_bundle

            # mesh-tier placement: a tp axis in the worker's mesh routes
            # the group to the weight-sharded dp×tp program inside
            # generate_microbatch (microbatch_tp_fn, gated by
            # CDT_MESH_TIER); the worker mesh's dp width stays
            # authoritative — it fixes each request's image count
            with pinned_bundle(lead.model):
                outs = lead.pipeline.generate_microbatch(
                    lead.mesh, lead.spec,
                    seeds=[p.seed for p in grp],
                    contexts=[p.context for p in grp],
                    uncond_contexts=[p.uncond for p in grp],
                    ys=[p.y for p in grp], uys=[p.uy for p in grp],
                )
            if telemetry.enabled():
                _tm.BATCH_SIZE.observe(len(grp))
        except InterruptedError:
            raise
        except Exception as e:  # noqa: BLE001 — fall back, never lose jobs
            log(f"front door: microbatch of {len(grp)} failed ({e}); "
                f"falling back to solo execution")
            if telemetry.enabled():
                _tm.BATCH_FALLBACKS.inc()
            for p in grp:
                if telemetry.enabled():
                    _tm.BATCH_SIZE.observe(1)
                run_solo(p)
            continue
        _observe_group_shape(lead, len(grp))
        for p, images in zip(grp, outs):
            try:
                _fill_cache(p, cache, images)
                out_cache = _finish(p, images)
                results[p.member.prompt_id] = {
                    "status": "success", "outputs": out_cache,
                    "batch_size": len(grp)}
            except InterruptedError:
                raise
            except Exception as e:  # noqa: BLE001 — member isolation
                results[p.member.prompt_id] = {"status": "error",
                                               "error": str(e)}
                log(f"front door: suffix failed for "
                    f"{p.member.prompt_id}: {e}")

    debug_log(f"front door: group of {len(members)} done in "
              # cdtlint: disable=D001 -- telemetry duration only
              f"{time.monotonic() - t0:.2f}s "
              f"({len(groups)} stack(s), {len(singles)} solo)")


def _observe_group_shape(lead: _Prepared, n: int) -> None:
    """Feed the shape catalog like the solo node path does — a batched
    program the fleet serves is a program the next restart should warm."""
    from ..shape_catalog import observe

    name = getattr(getattr(lead.kwargs.get("model"), "preset", None),
                   "name", None)
    if name:
        try:
            observe("txt2img", name, lead.spec.height, lead.spec.width,
                    lead.spec.steps, batch=lead.spec.per_device_batch)
        except Exception:  # noqa: BLE001 — observation must never sink a job
            pass
