"""Request classification: which queue requests can ride one microbatch.

Two requests may share a compiled program — and therefore a microbatch —
exactly when they resolve to the same :class:`GroupKey`: same model,
geometry, step count, guidance, sampler family, and per-device batch.
The classifier derives that key *statically* from the prompt graph (the
same literal-derivation discipline as ``cluster/shape_catalog``), and is
deliberately conservative: anything it cannot prove batchable passes
through to the legacy orchestration path untouched. A wrong "not
batchable" costs a solo execution; a wrong "batchable" could corrupt a
user's image — so the allowlist below names every node class whose
semantics are known to be safe alongside cross-request batching.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ...diffusion.pipeline import DETERMINISTIC_SAMPLERS
from ..shape_catalog import ProgramKey

# The one sampler node the microbatch executor knows how to stack.
BATCHABLE_SAMPLER = "TPUTxt2Img"

# Node classes that may appear ANYWHERE in a batchable prompt. Everything
# else — other samplers (their programs differ), tile/video machinery,
# collector fan-out (needs the job-store lifecycle), LoRA/ControlNet
# (mutate the model/conditioning in ways the group key cannot see) —
# routes to the legacy path.
BATCHABLE_NODE_ALLOWLIST = frozenset({
    BATCHABLE_SAMPLER,
    "CheckpointLoader",
    "CLIPTextEncode",
    "DistributedSeed",
    "DistributedValue",
    "EmptyLatentImage",
    "ImageScale",
    "ImageScaleBy",
    "ImageFromBatch",
    "SaveImage",
    "PreviewImage",
    "PrimitiveInt",
    "PrimitiveFloat",
    "PrimitiveString",
})


@dataclasses.dataclass(frozen=True, order=True)
class GroupKey:
    """Identity of the compiled program a request needs — requests with
    equal keys coalesce into one microbatch. Mirrors
    ``shape_catalog.ProgramKey`` plus the sampler knobs that change the
    traced program (cfg toggles the CFG branch; sampler/scheduler change
    the step body/ladder)."""

    model: str
    height: int
    width: int
    steps: int
    cfg: float
    sampler: str
    scheduler: str
    batch_per_device: int = 1

    def program_key(self) -> ProgramKey:
        """The shape-catalog identity this group lands on (warmup/telemetry
        join on it)."""
        return ProgramKey(pipeline="txt2img", model=self.model,
                          height=self.height, width=self.width,
                          steps=self.steps, batch=self.batch_per_device)

    def label(self) -> str:
        """Low-cardinality telemetry/debug label."""
        return (f"{self.model}/{self.height}x{self.width}"
                f"/s{self.steps}/{self.sampler}")


@dataclasses.dataclass(frozen=True)
class Classification:
    batchable: bool
    reason: str
    group_key: Optional[GroupKey] = None
    sampler_node_id: Optional[str] = None


def fingerprint(prompt: dict) -> str:
    """The GroupKey extended to a FULL content fingerprint
    (``cluster/cache/keys.py``): where the group key answers "can these
    requests share a compiled program?" (model/geometry/steps/sampler),
    the fingerprint answers "did these requests ask for byte-identical
    work?" — it digests the entire canonical prompt graph, so the prompt
    text, negative prompt, seed, LoRA set, and every other literal are
    all covered. Equal fingerprints coalesce in flight and share
    completed-result cache entries (docs/caching.md)."""
    from ..cache.keys import request_fingerprint

    return request_fingerprint(prompt)


def _literal_num(v):
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return v
    return None


def _not(reason: str) -> Classification:
    return Classification(batchable=False, reason=reason)


def classify(prompt: dict) -> Classification:
    """Statically classify one prompt. Never raises on malformed input —
    malformed prompts are "not batchable" and fail loudly downstream on
    the legacy path's validation."""
    if not isinstance(prompt, dict) or not prompt:
        return _not("empty")
    nodes = {k: v for k, v in prompt.items()
             if isinstance(v, dict) and v.get("class_type")}
    if len(nodes) != len(prompt):
        return _not("malformed_nodes")

    samplers = [nid for nid, n in nodes.items()
                if n["class_type"] == BATCHABLE_SAMPLER]
    if not samplers:
        return _not("no_batchable_sampler")
    if len(samplers) > 1:
        return _not("multiple_samplers")
    outside = sorted({n["class_type"] for n in nodes.values()
                      if n["class_type"] not in BATCHABLE_NODE_ALLOWLIST})
    if outside:
        return _not(f"node_outside_allowlist:{outside[0]}")

    nid = samplers[0]
    inputs = nodes[nid].get("inputs", {})
    height = _literal_num(inputs.get("height"))
    width = _literal_num(inputs.get("width"))
    steps = _literal_num(inputs.get("steps"))
    cfg = _literal_num(inputs.get("cfg"))
    if None in (height, width, steps, cfg):
        return _not("dynamic_geometry")

    sampler = inputs.get("sampler_name", "euler")
    scheduler = inputs.get("scheduler", "karras")
    if not isinstance(sampler, str) or not isinstance(scheduler, str):
        return _not("dynamic_sampler")
    if sampler not in DETERMINISTIC_SAMPLERS:
        # stochastic step noise is shaped by the whole batch — a
        # microbatched run could not reproduce the solo trajectories
        return _not(f"stochastic_sampler:{sampler}")
    bpd = inputs.get("batch_per_device", 1)
    bpd = _literal_num(bpd)
    if bpd is None or int(bpd) != bpd:
        return _not("dynamic_batch")

    model = _resolve_checkpoint(inputs.get("model"), nodes)
    if model is None:
        return _not("unresolvable_model")

    key = GroupKey(model=model, height=int(height), width=int(width),
                   steps=int(steps), cfg=float(cfg), sampler=sampler,
                   scheduler=scheduler, batch_per_device=int(bpd))
    return Classification(batchable=True, reason="batchable",
                          group_key=key, sampler_node_id=nid)


def _resolve_checkpoint(link, nodes: dict) -> Optional[str]:
    """``model`` must link (one hop, the shipped-workflow idiom the shape
    catalog also assumes) to a ``CheckpointLoader`` with a literal
    ``ckpt_name`` — model identity must be knowable without executing
    anything."""
    if not (isinstance(link, (list, tuple)) and len(link) == 2):
        return None
    src = nodes.get(str(link[0]))
    if src is None or src.get("class_type") != "CheckpointLoader":
        return None
    if link[1] != 0:
        return None
    name = src.get("inputs", {}).get("ckpt_name")
    return name if isinstance(name, str) and name else None
