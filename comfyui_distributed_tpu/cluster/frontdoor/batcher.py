"""Per-program-key coalescing: hold batchable requests briefly, flush
same-shape groups as one batch job.

The window trades a bounded latency cost (``CDT_FD_WINDOW_MS``, default
25 ms — noise against a multi-second diffusion program) for batch
occupancy. Flushing is *continuous-batching* shaped: groups only drain
while the prompt queue has capacity (``CDT_FD_INFLIGHT`` batch slots),
so under load a waiting group keeps absorbing same-shape arrivals up to
``CDT_FD_MAX_BATCH`` instead of fragmenting into singleton jobs — the
queue-depth signal *is* the batching signal. A safety valve
(``CDT_FD_MAX_WAIT_MS``) force-flushes any group whose oldest member has
waited too long, so a wedged queue degrades to bounded latency, never to
an unbounded hold.

Flush order is strict priority (``constants.PRIORITY_CLASSES`` rank of
the group's most urgent member), then group age — interactive traffic
boards first, background batch rides the remaining slots.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable, Optional

from ...utils import constants
from ...utils.logging import debug_log
from .classifier import GroupKey


def _max_wait_ms() -> float:
    env = constants.FD_MAX_WAIT_MS.get()
    if env is not None:
        return env
    return constants.FD_WINDOW_MS * 20.0


@dataclasses.dataclass
class _Group:
    key: GroupKey
    members: list = dataclasses.field(default_factory=list)
    sampler_node_ids: dict = dataclasses.field(default_factory=dict)
    opened_at: float = 0.0

    def priority_rank(self) -> int:
        ranks = [
            constants.PRIORITY_CLASSES.index(m.priority)
            if m.priority in constants.PRIORITY_CLASSES
            else len(constants.PRIORITY_CLASSES)
            for m in self.members
        ]
        return min(ranks) if ranks else len(constants.PRIORITY_CLASSES)


class CoalescingBatcher:
    """Holds admitted batchable members per :class:`GroupKey` and flushes
    ready groups through ``enqueue`` (one call per microbatch)."""

    def __init__(
        self,
        enqueue: Callable[[list, dict], None],
        *,
        window_ms: Optional[float] = None,
        max_batch: Optional[int] = None,
        capacity: Optional[Callable[[], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.enqueue = enqueue
        self.window_ms = (constants.FD_WINDOW_MS if window_ms is None
                          else window_ms)
        self.max_batch = max(1, constants.FD_MAX_BATCH if max_batch is None
                             else max_batch)
        self.capacity = capacity or (lambda: True)
        self._clock = clock
        self._groups: dict[GroupKey, _Group] = {}
        self._wake = asyncio.Event()
        self.flushed_groups = 0
        self.flushed_members = 0

    # --- producer side ------------------------------------------------------

    def submit(self, key: GroupKey, member, sampler_node_id: str) -> None:
        group = self._groups.get(key)
        if group is None:
            group = self._groups[key] = _Group(key=key,
                                               opened_at=self._clock())
        group.members.append(member)
        group.sampler_node_ids[member.prompt_id] = sampler_node_id
        self.wake()

    def wake(self) -> None:
        self._wake.set()

    # --- introspection ------------------------------------------------------

    @property
    def pending_count(self) -> int:
        return sum(len(g.members) for g in self._groups.values())

    def pending_by_priority(self) -> dict[str, int]:
        out = {p: 0 for p in constants.PRIORITY_CLASSES}
        for g in self._groups.values():
            for m in g.members:
                out[m.priority] = out.get(m.priority, 0) + 1
        return out

    def group_summary(self) -> list[dict]:
        now = self._clock()
        return [{"key": g.key.label(), "size": len(g.members),
                 "age_ms": round((now - g.opened_at) * 1000.0, 1)}
                for g in sorted(self._groups.values(),
                                key=lambda g: g.opened_at)]

    # --- scheduler ----------------------------------------------------------

    def _ready(self, group: _Group, now: float) -> bool:
        return (len(group.members) >= self.max_batch
                or (now - group.opened_at) * 1000.0 >= self.window_ms)

    def _overdue(self, group: _Group, now: float) -> bool:
        return (now - group.opened_at) * 1000.0 >= _max_wait_ms()

    def _next_deadline(self) -> Optional[float]:
        """The next moment flush_ready could change its answer on a
        TIMER: a pending group's window expiry, or a capacity-blocked
        ready group's overdue valve. Already-ready groups waiting only
        on capacity have no earlier timer — their wake signal is the
        job-done callback — so using their (expired) window here would
        spin the loop at the 1 ms clamp for the whole duration of the
        running program."""
        if not self._groups:
            return None
        now = self._clock()
        deadlines = []
        for g in self._groups.values():
            if self._ready(g, now):
                deadlines.append(g.opened_at + _max_wait_ms() / 1000.0)
            else:
                deadlines.append(g.opened_at + self.window_ms / 1000.0)
        return min(deadlines)

    def flush_ready(self) -> int:
        """Flush every ready group the queue has capacity for (overdue
        groups flush regardless — each is checked, so a blocked
        high-priority group can't starve an overdue lower one). Returns
        members flushed. Called from the scheduler loop and directly by
        tests."""
        flushed = 0
        while True:
            now = self._clock()
            ready = [g for g in self._groups.values() if self._ready(g, now)]
            if not ready:
                return flushed
            ready.sort(key=lambda g: (g.priority_rank(), g.opened_at))
            if self.capacity():
                group = ready[0]
            else:
                overdue = [g for g in ready if self._overdue(g, now)]
                if not overdue:
                    return flushed
                group = overdue[0]
            take = group.members[:self.max_batch]
            rest = group.members[self.max_batch:]
            ids = {m.prompt_id: group.sampler_node_ids[m.prompt_id]
                   for m in take}
            if rest:
                group.members = rest
                group.sampler_node_ids = {
                    m.prompt_id: group.sampler_node_ids[m.prompt_id]
                    for m in rest}
                # leftovers missed this bus but keep their seniority:
                # the window they already served counts
                group.opened_at = min(m.enqueued_at for m in rest)
            else:
                del self._groups[group.key]
            debug_log(f"front door: flushing {len(take)} member(s) "
                      f"for {group.key.label()}")
            self.enqueue(take, ids)
            self.flushed_groups += 1
            self.flushed_members += len(take)
            flushed += len(take)

    async def run(self) -> None:
        """The coalescing loop: sleep until the earliest window expires or
        someone wakes us (new member, job completed), then flush."""
        while True:
            deadline = self._next_deadline()
            timeout = (None if deadline is None
                       else max(0.001, deadline - self._clock()))
            try:
                await asyncio.wait_for(self._wake.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            self.flush_ready()
