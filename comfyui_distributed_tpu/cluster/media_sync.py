"""Content-addressed media sync: master → remote host controllers.

Parity: reference ``api/orchestration/media_sync.py`` — find media file
references in prompt inputs (``:70-81``), md5-check each against the remote
host via ``/distributed/check_file`` and upload through ``/upload/image``
only on miss or mismatch (``:146-193``), and convert path separators for
cross-platform workers keyed off the remote ``/distributed/system_info``
(``:36-67,127-143``).

TPU note: this only runs for *remote* host controllers reached over DCN/WAN.
On-pod participants share the master's filesystem view (or object store) and
never enter this module — the reference pays this cost per worker because
every GPU is a separate process with its own input directory.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
from pathlib import Path
from typing import Any, Optional

import aiohttp

from ..telemetry import enabled as _tm_enabled, metrics as _tm
from ..utils import constants
from ..utils.logging import debug_log, trace_info
from ..utils.network import build_host_url, fetch_system_info, get_client_session

# Input field names that carry a media filename (reference ``:70-81`` scans
# image/video/audio/file inputs).
MEDIA_INPUT_KEYS = frozenset({"image", "video", "audio", "file", "filename"})

MEDIA_EXTENSIONS = (
    ".png", ".jpg", ".jpeg", ".webp", ".gif", ".bmp",
    ".mp4", ".webm", ".mov", ".avi",
    ".wav", ".mp3", ".flac", ".ogg",
    ".npy", ".npz",
)


@dataclasses.dataclass(frozen=True)
class MediaRef:
    """One media-file reference inside a prompt graph."""
    node_id: str
    input_key: str
    value: str


def looks_like_media(value: Any) -> bool:
    return (
        isinstance(value, str)
        and value.lower().endswith(MEDIA_EXTENSIONS)
        and "\n" not in value
    )


def find_media_refs(prompt: dict) -> list[MediaRef]:
    """Scan node inputs for media filenames (reference ``:70-81``).

    Only media-typed input keys are considered, so a STRING prompt that
    merely *mentions* ``foo.png`` is never synced.
    """
    refs: list[MediaRef] = []
    for node_id, node in prompt.items():
        inputs = node.get("inputs", {}) if isinstance(node, dict) else {}
        for key, value in inputs.items():
            if key.lower() in MEDIA_INPUT_KEYS and looks_like_media(value):
                refs.append(MediaRef(node_id, key, value))
    return refs


def convert_paths_for_platform(prompt: dict, remote_sep: str) -> dict:
    """Rewrite media-path separators to the remote host's convention
    (reference ``:36-67`` — Windows workers need ``\\``, Unix ``/``)."""
    if remote_sep not in ("/", "\\"):
        return prompt
    local_sep = "\\" if remote_sep == "/" else "/"
    out = {k: (dict(v) if isinstance(v, dict) else v) for k, v in prompt.items()}
    for ref in find_media_refs(out):
        if local_sep in ref.value:
            node = dict(out[ref.node_id])
            inputs = dict(node.get("inputs", {}))
            inputs[ref.input_key] = ref.value.replace(local_sep, remote_sep)
            node["inputs"] = inputs
            out[ref.node_id] = node
    return out


async def fetch_host_path_separator(host: dict, timeout: float = 10.0) -> str:
    """Remote ``/distributed/system_info`` → path separator
    (reference ``:127-143``); defaults to ``/`` when unreachable."""
    info = await fetch_system_info(host, timeout)
    sep = (info or {}).get("path_separator", "/")
    return sep if sep in ("/", "\\") else "/"


def local_input_dir() -> Path:
    return Path(constants.INPUT_DIR.get())


def _md5_file(path: Path) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _media_policy():
    """Small bounded policy for media sync: both operations are idempotent
    (check is read-only; upload is a content-addressed overwrite), so a
    transient drop shouldn't skip a dispatch-blocking file — but a dead
    host must fail the whole host quickly, hence 3 attempts not 5."""
    from .resilience import RetryPolicy

    return RetryPolicy(max_attempts=3, base=constants.SEND_BACKOFF_BASE,
                       cap=constants.RETRY_CAP_S)


async def _check_remote_file(host: dict, rel: str, md5: str,
                             timeout: float) -> bool:
    """True iff the remote already has ``rel`` with matching content
    (reference ``:146-166`` fast path)."""
    url = build_host_url(host, "/distributed/check_file")

    async def attempt() -> bool:
        session = get_client_session()
        async with session.post(
            url, json={"path": rel, "md5": md5},
            timeout=aiohttp.ClientTimeout(total=timeout),
        ) as resp:
            if resp.status != 200:
                return False
            body = await resp.json()
            return bool(body.get("exists")) and bool(body.get("matches", True))

    try:
        return await _media_policy().run(attempt, op="media")
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
        debug_log(f"check_file {rel} on {host.get('id')} failed: {e}")
        return False


async def _upload_file(host: dict, rel: str, path: Path,
                       timeout: float) -> bool:
    """Upload one file via the ComfyUI-compatible ``/upload/image`` route
    (reference ``:168-193``). The file object is handed to aiohttp so the
    body streams from disk — video inputs are multi-GB and must not be
    buffered in the controller's RAM. The file is reopened per attempt:
    a half-streamed body can't be rewound."""
    from ..utils.exceptions import WorkerError

    url = build_host_url(host, "/upload/image")

    async def attempt() -> bool:
        with open(path, "rb") as f:
            form = aiohttp.FormData()
            form.add_field("image", f, filename=rel,
                           content_type="application/octet-stream")
            session = get_client_session()
            async with session.post(
                url, data=form, timeout=aiohttp.ClientTimeout(total=timeout),
                headers={"X-CDT-Client": "1"},
            ) as resp:
                if resp.status >= 500:
                    # transient server-side failure: idempotent re-upload
                    err = WorkerError(f"upload {rel}: {resp.status}")
                    err.retry_safe = True
                    raise err
                return resp.status == 200

    try:
        return await _media_policy().run(attempt, op="media")
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            WorkerError) as e:
        # transient transport trio + the retry-exhausted 5xx wrapper; a
        # programming error in the upload path must still raise loudly
        debug_log(f"upload {rel} to {host.get('id')} failed: {e}")
        return False


@dataclasses.dataclass
class SyncReport:
    checked: int = 0
    uploaded: int = 0
    skipped: int = 0       # already present with matching md5
    missing: int = 0       # absent locally — left untouched
    failed: list = dataclasses.field(default_factory=list)


async def sync_host_media(
    host: dict,
    prompt: dict,
    input_dir: Optional[Path] = None,
    concurrency: int = constants.MEDIA_SYNC_CONCURRENCY,
    timeout: float = constants.MEDIA_SYNC_TIMEOUT,
    trace_id: str = "",
) -> tuple[dict, SyncReport]:
    """Ensure every media file the prompt references exists (content-
    identical) on the remote host; returns the prompt with path separators
    converted for the remote platform plus a sync report
    (reference ``sync_worker_media``, ``:196-256``).
    """
    base = input_dir or local_input_dir()
    report = SyncReport()
    refs = find_media_refs(prompt)
    if not refs:
        return prompt, report

    sep = await fetch_host_path_separator(host, timeout)
    sem = asyncio.Semaphore(max(1, concurrency))

    def count(outcome: str) -> None:
        if _tm_enabled():
            _tm.MEDIA_SYNC_FILES.labels(outcome=outcome).inc()

    async def sync_one(ref: MediaRef) -> None:
        async with sem:
            report.checked += 1
            local = base / ref.value.replace("\\", "/")
            if not local.is_file():
                report.missing += 1
                count("missing")
                debug_log(f"media sync: {local} absent locally; skipping")
                return
            md5 = await asyncio.get_running_loop().run_in_executor(
                None, _md5_file, local)
            rel = ref.value.replace("\\", "/")
            if await _check_remote_file(host, rel, md5, timeout):
                report.skipped += 1
                count("skipped")
                return
            if await _upload_file(host, rel, local, timeout):
                report.uploaded += 1
                count("uploaded")
                if _tm_enabled():
                    try:
                        _tm.MEDIA_SYNC_BYTES.inc(local.stat().st_size)
                    except OSError:
                        pass
            else:
                report.failed.append(rel)
                count("failed")

    await asyncio.gather(*(sync_one(r) for r in refs))
    if trace_id:
        trace_info(trace_id,
                   f"media sync → {host.get('id')}: {report.checked} checked, "
                   f"{report.uploaded} uploaded, {report.skipped} up-to-date, "
                   f"{report.missing} missing, {len(report.failed)} failed")
    return convert_paths_for_platform(prompt, sep), report
