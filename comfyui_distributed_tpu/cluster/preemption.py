"""Step-granular preemption: strict interactive latency under mixed load.

The front door has priority classes but — before ISSUE 14 — no
preemption: a 200-step video-class job held its slot end-to-end and an
interactive request behind it ate the full residual. The denoise loop
has natural preemption points at step boundaries, so the serving sampler
runs in resumable K-step segments (``diffusion/pipeline.py
generate_preemptible``) and THIS controller decides, between segments,
whether the running job should yield:

- **priority**: a strictly higher priority class is waiting in the
  prompt queue (evaluated on every enqueue and execution start);
- **drain**: the worker is leaving the fleet (``cluster/elastic`` wires
  the drain coordinator to :meth:`preempt_executing`) — a scale-down no
  longer waits out a long job;
- **manual**: an operator asked via the API.

A preempted job parks its :class:`~..diffusion.checkpoint.LatentCheckpoint`
in the :class:`~..diffusion.checkpoint.CheckpointStore` and is requeued
at its original queue position — intentional departure in the PR 7
handback sense: **no poison count, no breaker evidence, nothing lost**.
Resume happens on the next dequeue (this worker) or, via the checkpoint
routes / an inline ``checkpoint`` queue payload, on ANY worker with the
same dp width — bit-identically, per the determinism invariants. Restore
failures are bounded: ``CDT_PREEMPT_RESUME_RETRIES`` attempts, then the
checkpoint dead-letters and the job restarts from scratch.

Starvation guard: a job preempted ``CDT_PREEMPT_MAX`` times stops
yielding to priority traffic (drain still preempts — the slot must
free). See ``docs/preemption.md``.
"""

from __future__ import annotations

from typing import Optional

from ..diffusion.checkpoint import CheckpointStore, LatentCheckpoint
from ..lint.lockorder import tracked_lock
from ..utils import constants
from ..utils.logging import log


def preempt_enabled() -> bool:
    return constants.PREEMPT.get()


def _priority_rank(priority: str) -> int:
    # the ONE rank definition — queue ordering and preemption triggering
    # must never disagree about what "higher priority" means
    from .runtime import _priority_rank as rank

    return rank(priority)


class PreemptionToken:
    """Per-execution handle the sampler node reads from the execution
    context (hidden input ``preemption``): the segment length, the
    checkpoint to resume from (if any), and the cheap between-segments
    ``should_preempt()`` probe (called from the graph-exec thread)."""

    def __init__(self, controller: "PreemptionController", job,
                 resume: Optional[LatentCheckpoint],
                 preemptible: bool):
        self._controller = controller
        self._job = job
        self.resume = resume
        self.preemptible = preemptible
        self.segment_steps = constants.PREEMPT_SEGMENT_STEPS.get()
        # set by the sampler node when it actually feeds ``resume`` into
        # the segmented path — a graph that ignores the token (img2img,
        # ControlNet) must not be reported as a successful resume
        self.resume_consumed = False

    def should_preempt(self) -> Optional[str]:
        reason = self._controller.requested_reason(self._job.prompt_id)
        if reason is None:
            return None
        if not self.preemptible and reason != "drain":
            # starvation guard: past CDT_PREEMPT_MAX the job runs to
            # completion — except for a drain, where the slot MUST free
            return None
        return reason


class PreemptionController:
    """One per controller; bound to the prompt queue by
    ``queue.preemption = controller`` (``cluster/controller.py``)."""

    def __init__(self, queue, store: Optional[CheckpointStore] = None):
        self.queue = queue
        self.store = store if store is not None else CheckpointStore()
        self._lock = tracked_lock("preemption", reentrant=True)
        # prompt_id -> reason; read between segments from the exec thread
        self._requests: dict[str, str] = {}
        # prompt_ids currently parked mid-denoise (gauge bookkeeping)
        self._parked: set[str] = set()
        self.counts = {"preempted": 0, "resumed": 0, "restore_failed": 0,
                       "dead_lettered": 0, "preempt_requests": 0}

    # --- execution lifecycle (called by PromptQueue) ------------------------

    def begin(self, job) -> Optional[PreemptionToken]:
        """Token for a starting solo job (None = run monolithic: knob
        off, or a batch group — those are one compiled program)."""
        if not preempt_enabled() or job.group is not None:
            return None
        resume = None
        if job.checkpoint_id:
            resume = self.store.get(job.checkpoint_id)
            if resume is None:
                # lost/corrupt checkpoint: LOUD, then from scratch —
                # never a wrong byte, never a hang
                log(f"preemption: checkpoint {job.checkpoint_id} for "
                    f"{job.prompt_id} is gone — restarting from scratch")
                job.checkpoint_id = None
        preemptible = job.preempt_count < constants.PREEMPT_MAX.get()
        return PreemptionToken(self, job, resume, preemptible)

    def end(self, job) -> None:
        with self._lock:
            self._requests.pop(job.prompt_id, None)

    def resolve_success(self, job) -> None:
        """Terminal success: the parked state (if any) is spent."""
        if job.checkpoint_id:
            self.store.mark_restored(job.checkpoint_id)
            if self.store.drop(job.checkpoint_id):
                with self._lock:
                    self.counts["resumed"] += 1
            job.checkpoint_id = None
        self._unpark(job.prompt_id)

    def discard(self, job) -> None:
        """A parked job left the queue WITHOUT resuming (interrupt,
        deadline expiry): release its checkpoint and gauge slot — a
        dropped job must not leak store bytes or a forever-nonzero
        ``cdt_jobs_preempted``."""
        if getattr(job, "checkpoint_id", None):
            self.store.drop(job.checkpoint_id)
            job.checkpoint_id = None
        self._unpark(job.prompt_id)

    # --- preemption verdicts ------------------------------------------------

    def requested_reason(self, prompt_id: str) -> Optional[str]:
        with self._lock:
            return self._requests.get(prompt_id)

    def reevaluate(self) -> None:
        """Priority policy, run on every queue mutation (enqueue,
        execution start): preempt the running solo job iff a STRICTLY
        higher priority class is waiting."""
        job = getattr(self.queue, "executing_job", None)
        if job is None or job.group is not None:
            return
        rank_exec = _priority_rank(job.priority)
        best = self.queue.pending_best_rank()
        if best is None or best >= rank_exec:
            return
        self._request(job.prompt_id, "priority")

    def preempt_executing(self, reason: str = "manual") -> Optional[str]:
        """Unconditional request against the running solo job (drain /
        operator path). Returns the targeted prompt_id or None."""
        job = getattr(self.queue, "executing_job", None)
        if job is None or job.group is not None:
            return None
        self._request(job.prompt_id, reason)
        return job.prompt_id

    def _request(self, prompt_id: str, reason: str) -> None:
        with self._lock:
            if self._requests.get(prompt_id) == reason:
                return
            # drain outranks priority (the slot must free either way,
            # and drain bypasses the starvation guard)
            if self._requests.get(prompt_id) == "drain":
                return
            self._requests[prompt_id] = reason
            self.counts["preempt_requests"] += 1

    # --- parking / resume bookkeeping ---------------------------------------

    def park(self, job, ckpt: LatentCheckpoint, reason: str) -> str:
        """A job yielded at a segment boundary: park the checkpoint,
        count the preemption, mark the job for resume."""
        ckpt.meta.setdefault("prompt_id", job.prompt_id)
        if job.checkpoint_id:
            # re-preempted after a resume: the superseded (already
            # consumed) checkpoint must not leak in the store
            self.store.drop(job.checkpoint_id)
        cid = self.store.park(ckpt)
        job.checkpoint_id = cid
        job.preempt_count += 1
        with self._lock:
            self._requests.pop(job.prompt_id, None)
            self._parked.add(job.prompt_id)
            self.counts["preempted"] += 1
        self._telemetry_preempted(reason)
        log(f"preempted {job.prompt_id} at step {ckpt.step}/"
            f"{ckpt.total_steps} ({reason}) -> checkpoint {cid}")
        return cid

    def restore_failed(self, job, error: str) -> str:
        """A resume attempt failed. Returns ``"retry"`` (requeue with
        the checkpoint) or ``"scratch"`` (checkpoint dead-lettered —
        requeue without it)."""
        job.resume_attempts += 1
        with self._lock:
            self.counts["restore_failed"] += 1
        attempts = self.store.record_restore_failure(
            job.checkpoint_id or "?", error)
        if (job.checkpoint_id is None
                or attempts >= self.store.resume_retries):
            with self._lock:
                self.counts["dead_lettered"] += 1
            job.checkpoint_id = None
            job.resume_attempts = 0
            self._unpark(job.prompt_id)
            return "scratch"
        return "retry"

    def _unpark(self, prompt_id: str) -> None:
        with self._lock:
            self._parked.discard(prompt_id)
        self._export_gauge()

    def _telemetry_preempted(self, reason: str) -> None:
        try:
            from .. import telemetry
            from ..telemetry import metrics as _tm

            if telemetry.enabled():
                _tm.PREEMPTIONS_TOTAL.labels(reason=reason).inc()
        except Exception:  # noqa: BLE001 — telemetry is never load-bearing
            pass
        self._export_gauge()

    def _export_gauge(self) -> None:
        try:
            from .. import telemetry
            from ..telemetry import metrics as _tm

            if telemetry.enabled():
                with self._lock:
                    n = len(self._parked)
                _tm.JOBS_PREEMPTED.set(n)
        except Exception:  # noqa: BLE001 — telemetry is never load-bearing
            pass

    # --- surfaces -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
            requests = dict(self._requests)
            parked = sorted(self._parked)
        return {
            "enabled": preempt_enabled(),
            "segment_steps": constants.PREEMPT_SEGMENT_STEPS.get(),
            "parked_jobs": parked,
            "requests": requests,
            "store": self.store.stats(),
            **counts,
        }


def resolve_resume(preemption: Optional[PreemptionController],
                   checkpoint_id: Optional[str],
                   checkpoint_payload: Optional[dict]) -> Optional[str]:
    """The ONE resume-import policy both queue entrances share (front
    door and the CDT_FRONTDOOR=0 legacy route): returns the checkpoint
    id to resume from, importing an inline wire-form checkpoint first
    (checksum-verified). Loud errors — a resume request against a
    preemption-disabled worker, or a corrupt inline payload, must never
    silently run from scratch."""
    if checkpoint_id is None and checkpoint_payload is None:
        return None
    from ..utils.exceptions import ValidationError

    if preemption is None:
        raise ValidationError(
            "this worker has preemption disabled (CDT_PREEMPT=0); it "
            "cannot resume checkpoints", field="checkpoint_id")
    cid = checkpoint_id
    if checkpoint_payload is not None:
        from ..diffusion.checkpoint import (CheckpointError,
                                            LatentCheckpoint)

        try:
            ckpt = LatentCheckpoint.from_payload(checkpoint_payload)
        except CheckpointError as e:
            raise ValidationError(str(e), field="checkpoint")
        cid = preemption.store.park(ckpt)
    return cid


def build_preemption(queue) -> Optional[PreemptionController]:
    """Controller hook (mirrors build_frontdoor/build_cache_manager):
    the preemption controller, or None under CDT_PREEMPT=0."""
    if not preempt_enabled():
        log("preemption disabled (CDT_PREEMPT=0) — monolithic sampler "
            "programs")
        return None
    return PreemptionController(queue)
