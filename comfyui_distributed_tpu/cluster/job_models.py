"""Typed job state (parity: reference ``upscale/job_models.py:10-49`` and
the collector's per-job asyncio queue, ``nodes/collector.py:321-327``)."""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Optional


@dataclasses.dataclass
class CollectorJob:
    """One collector gather: workers push result envelopes, master drains."""

    job_id: str
    expected_workers: tuple[str, ...] = ()
    results: asyncio.Queue = dataclasses.field(default_factory=asyncio.Queue)
    # worker_id → done flag (worker sent its is_last envelope)
    completed_workers: dict[str, bool] = dataclasses.field(default_factory=dict)
    created_at: float = dataclasses.field(default_factory=time.monotonic)

    def all_done(self) -> bool:
        return all(self.completed_workers.get(w) for w in self.expected_workers)


@dataclasses.dataclass
class TileTask:
    """A unit of tile-engine work at host granularity: one shard-range of
    the global tile batch (the reference assigns single tile indices,
    ``upscale/job_store.py:34-80``; the TPU build assigns contiguous
    ranges so each grant is one SPMD program run)."""

    task_id: int
    start: int                  # global tile index range [start, end)
    end: int

    def as_dict(self) -> dict:
        return {"task_id": self.task_id, "start": self.start, "end": self.end}


@dataclasses.dataclass
class TileJob:
    """Pull-based tile job (parity: ``TileJobState``/``ImageJobState``)."""

    job_id: str
    total_tasks: int
    mode: str = "static"                       # "static" | "dynamic"
    # creation order (process-unique, assigned by the store): the steal
    # scheduler's deterministic tie-break key (cluster/elastic/scheduler)
    seq: int = 0
    # task_id → task, for the whole job lifetime (requeue needs ranges back)
    tasks: dict[int, TileTask] = dataclasses.field(default_factory=dict)
    pending: list[TileTask] = dataclasses.field(default_factory=list)
    # task_id → worker_id currently assigned
    assigned: dict[int, str] = dataclasses.field(default_factory=dict)
    # task_id → result payload
    completed: dict[int, Any] = dataclasses.field(default_factory=dict)
    # worker_id → last heartbeat (monotonic)
    worker_status: dict[str, float] = dataclasses.field(default_factory=dict)
    results: asyncio.Queue = dataclasses.field(default_factory=asyncio.Queue)
    created_at: float = dataclasses.field(default_factory=time.monotonic)
    # task_id → times this task was requeued (eviction or processing
    # failure); past MAX_TILE_REQUEUES the task dead-letters instead
    requeue_counts: dict[int, int] = dataclasses.field(default_factory=dict)
    # poison tasks: task_id → {task_id, worker_id, reason, requeues}
    dead_letter: dict[int, dict] = dataclasses.field(default_factory=dict)

    def remaining(self) -> int:
        return self.total_tasks - len(self.completed) - len(self.dead_letter)

    def is_complete(self) -> bool:
        """Every task reached a terminal state — completed or
        dead-lettered. A poison tile must never hang the job."""
        return self.remaining() <= 0

    def heartbeat(self, worker_id: str, now: Optional[float] = None) -> None:
        self.worker_status[worker_id] = time.monotonic() if now is None else now
