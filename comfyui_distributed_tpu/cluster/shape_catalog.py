"""Shape-catalog registry: the set of compiled-program keys a worker
should be hot for.

Every distinct (pipeline family, model, resolution, step count, batch,
mesh) tuple is a separate XLA program — and every one a cold worker
meets on the request path costs a full compile (64.8 s at the seed,
13.9 s with the packed flash kernel, still fatal for rolling restarts).
The catalog makes that set *explicit* so the AOT warmup pass
(``diffusion/warmup.py``) can pre-compile it off the request path:

- **seeded** from the shipped ``workflows/`` catalog (the shapes the
  product demonstrably serves),
- **grown** from shapes observed at runtime (the sampler nodes call
  :func:`observe` on every execution),
- **persisted** next to the XLA compilation cache and merged across
  restarts/processes (union on load, atomic tmp+rename on save), so a
  fleet image pre-baked with ``scripts/warmup_catalog.py`` and a
  long-lived worker accumulate into the same file.

Reference analogue: none — ComfyUI's torch kernels are pre-built, so the
reference never needs to know its shape population. An XLA server does.

Knobs: ``CDT_SHAPE_CATALOG`` (path; default
``<CDT_COMPILE_CACHE_DIR>/shape_catalog.json``), ``CDT_SHAPE_OBSERVE=0``
disables runtime observation.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from pathlib import Path
from typing import Iterable, Optional

from ..lint.lockorder import tracked_lock
from ..utils import constants
from ..utils.jsonio import atomic_write_json, read_json
from ..utils.logging import debug_log, log

CATALOG_VERSION = 1

# pipeline-family names match the telemetry ``pipeline`` label
# (telemetry/metrics.py) so warmup counters and step-time histograms
# join on the same vocabulary. flow_sp / flow_tp are the executed mesh
# tier's programs (docs/parallelism.md): same model, sequence-sharded
# (ring attention) and weight-sharded (Megatron dp×tp) placements.
PIPELINES = ("txt2img", "flow_dp", "video_dp", "flow_sp", "flow_tp")


@dataclasses.dataclass(frozen=True, order=True)
class ProgramKey:
    """One compiled program's identity, as the warmup pass sees it.

    ``mesh`` is a sorted tuple of (axis, size) pairs; the empty tuple
    means "this host's default mesh" — workflow-seeded entries use it so
    one catalog file serves fleets of different slice sizes. ``frames``
    is 0 for image pipelines.
    """

    pipeline: str
    model: str
    height: int
    width: int
    steps: int
    batch: int = 1
    frames: int = 0
    mesh: tuple = ()

    def __post_init__(self):
        if self.pipeline not in PIPELINES:
            raise ValueError(
                f"unknown pipeline family {self.pipeline!r}; "
                f"have {PIPELINES}")

    def to_dict(self) -> dict:
        return {"pipeline": self.pipeline, "model": self.model,
                "height": self.height, "width": self.width,
                "steps": self.steps, "batch": self.batch,
                "frames": self.frames,
                "mesh": [list(ax) for ax in self.mesh]}

    @classmethod
    def from_dict(cls, d: dict) -> "ProgramKey":
        return cls(pipeline=str(d["pipeline"]), model=str(d["model"]),
                   height=int(d["height"]), width=int(d["width"]),
                   steps=int(d["steps"]), batch=int(d.get("batch", 1)),
                   frames=int(d.get("frames", 0)),
                   mesh=tuple((str(a), int(n))
                              for a, n in d.get("mesh", ())))


def default_catalog_path() -> Path:
    """Next to the XLA cache by default: the two artifacts are one unit —
    the catalog names the programs, the cache holds their binaries."""
    env = constants.SHAPE_CATALOG.get()
    if env:
        return Path(env)
    from ..utils.compile_cache import cache_dir_default

    return Path(cache_dir_default()) / "shape_catalog.json"


class ShapeCatalog:
    """Deduplicated, persisted set of :class:`ProgramKey`.

    Thread-safe: runtime observation happens on the graph-executor
    thread while the warmup pass reads from an asyncio executor.
    """

    def __init__(self, path: "Path | str | None" = None,
                 autoload: bool = True):
        self.path = Path(path) if path is not None else default_catalog_path()
        self._keys: set[ProgramKey] = set()
        self._lock = tracked_lock("shape_catalog")
        if autoload:
            self.load()

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, key: ProgramKey) -> bool:
        return key in self._keys

    def entries(self) -> list[ProgramKey]:
        """Deterministic order (sorted dataclass) — the warmup pass and
        tests must walk the catalog identically on every host."""
        with self._lock:
            return sorted(self._keys)

    def add(self, key: ProgramKey) -> bool:
        """Add one key; returns True when it was new."""
        with self._lock:
            if key in self._keys:
                return False
            self._keys.add(key)
            return True

    def update(self, keys: Iterable[ProgramKey]) -> int:
        added = 0
        for k in keys:
            added += self.add(k)
        return added

    # --- persistence --------------------------------------------------------

    def load(self) -> int:
        """Merge the on-disk entries into memory (union — another process
        may have written since our last save). Unreadable/garbled files
        degrade to an empty load, never a crash."""
        raw = read_json(self.path)
        try:
            entries = raw.get("entries", [])
        except AttributeError:
            return 0
        added = 0
        for d in entries:
            try:
                added += self.add(ProgramKey.from_dict(d))
            except (KeyError, TypeError, ValueError):
                debug_log(f"shape catalog: skipping malformed entry {d!r}")
        return added

    def save(self) -> bool:
        """Merge-write: re-load the file first so concurrent writers
        (master + warmup CLI) union rather than clobber, then write
        atomically (tmp+rename). Never fatal."""
        self.load()
        with self._lock:
            payload = {"version": CATALOG_VERSION,
                       "entries": [k.to_dict() for k in sorted(self._keys)]}
        if atomic_write_json(self.path, payload):
            return True
        debug_log(f"shape catalog: save to {self.path} failed")
        return False

    # --- workflow seeding ---------------------------------------------------

    def seed_from_workflows(self, workflows_dir: "Path | str | None" = None
                            ) -> int:
        """Derive keys from the shipped workflow JSONs. Returns the number
        of NEW keys added."""
        if workflows_dir is None:
            env = constants.WORKFLOWS_DIR.get()
            workflows_dir = (Path(env) if env
                             else Path(__file__).resolve().parents[2]
                             / "workflows")
        d = Path(workflows_dir)
        if not d.is_dir():
            return 0
        added = 0
        for path in sorted(d.glob("*.json")):
            try:
                prompt = json.loads(path.read_text())
            except (OSError, ValueError):
                debug_log(f"shape catalog: unreadable workflow {path}")
                continue
            for key in keys_from_prompt(prompt):
                added += self.add(key)
        return added


# node class → (pipeline family, needs frames). TPUImg2Img/USDU tiles
# compile their own programs too, but their shapes derive from inputs
# the catalog can't know statically; runtime observation covers them.
_SAMPLER_NODES = {
    "TPUTxt2Img": ("txt2img", False),
    "TPUFlowTxt2Img": ("flow_dp", False),
    "TPUTxt2Video": ("video_dp", True),
}


def _literal_int(v, default=None) -> Optional[int]:
    """Workflow inputs may be node links (``[src_id, out_idx]``) — only
    literals are statically usable."""
    if isinstance(v, bool):
        return default
    if isinstance(v, (int, float)):
        return int(v)
    return default


def keys_from_prompt(prompt: dict) -> list[ProgramKey]:
    """Program keys statically derivable from one workflow/prompt dict.
    Sampler nodes whose geometry rides a link (dynamic width/steps) are
    skipped — runtime observation picks those up instead."""
    out = []
    nodes = {k: v for k, v in prompt.items()
             if isinstance(v, dict) and "class_type" in v}
    for node in nodes.values():
        family = _SAMPLER_NODES.get(node.get("class_type", ""))
        if family is None:
            continue
        pipeline, has_frames = family
        inputs = node.get("inputs", {})
        model = _resolve_model_name(inputs.get("model"), nodes)
        h = _literal_int(inputs.get("height"))
        w = _literal_int(inputs.get("width"))
        steps = _literal_int(inputs.get("steps"))
        if not model or None in (h, w, steps):
            continue
        frames = _literal_int(inputs.get("frames"), 0) if has_frames else 0
        batch = _literal_int(inputs.get("batch_per_device"), 1) or 1
        out.append(ProgramKey(pipeline=pipeline, model=model, height=h,
                              width=w, steps=steps, batch=batch,
                              frames=frames or 0))
    return out


def _resolve_model_name(link, nodes: dict) -> Optional[str]:
    """Follow a ``model`` input link to its CheckpointLoader's
    ``ckpt_name`` (one hop — the shipped workflows connect them
    directly)."""
    if not (isinstance(link, (list, tuple)) and len(link) == 2):
        return None
    src = nodes.get(str(link[0]))
    if src is None or src.get("class_type") != "CheckpointLoader":
        return None
    name = src.get("inputs", {}).get("ckpt_name")
    return name if isinstance(name, str) and name else None


# --- runtime observation ----------------------------------------------------

_default: "ShapeCatalog | None" = None
_default_lock = tracked_lock("shape_catalog.default")


def default_catalog() -> ShapeCatalog:
    """Process-global catalog instance (lazy; path re-resolved only at
    first use)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ShapeCatalog()
        return _default


def reset_default_catalog() -> None:
    """Test isolation: drop the cached instance so env-var paths
    re-resolve."""
    global _default
    with _default_lock:
        _default = None


def observe_cap() -> int:
    """Max catalog size runtime observation may grow to (workflow
    seeding and the CLI are exempt — they are operator-driven). Every
    entry costs an AOT compile on every future worker boot, so an
    unbounded user-driven (or hostile) resolution sweep must not turn
    the warmup pass into the new cold start."""
    return constants.SHAPE_CATALOG_MAX.get()


def observe(pipeline: str, model: str, height: int, width: int,
            steps: int, batch: int = 1, frames: int = 0) -> None:
    """Record a shape served on the request path. New keys persist
    immediately (one small JSON write) so the NEXT restart warms them;
    repeat shapes are a set lookup. Growth is capped
    (``CDT_SHAPE_CATALOG_MAX``, first-observed-wins). Never fatal, and
    a no-op under ``CDT_SHAPE_OBSERVE=0``."""
    if not constants.SHAPE_OBSERVE.get():
        return
    try:
        cat = default_catalog()
        cap = observe_cap()
        if cap and len(cat) >= cap:
            debug_log(f"shape catalog: at cap ({cap}); not observing "
                      f"({pipeline}, {model}, {height}x{width}, "
                      f"steps={steps}) — raise CDT_SHAPE_CATALOG_MAX or "
                      "add it via scripts/warmup_catalog.py --shape")
            return
        if cat.add(ProgramKey(pipeline=pipeline, model=model,
                              height=int(height), width=int(width),
                              steps=int(steps), batch=int(batch),
                              frames=int(frames))):
            cat.save()
            log(f"shape catalog: observed new program "
                f"({pipeline}, {model}, {height}x{width}, "
                f"steps={steps}) → {cat.path}")
    except Exception as e:  # noqa: BLE001 — observation must never sink a job
        debug_log(f"shape catalog: observe failed: {e}")
