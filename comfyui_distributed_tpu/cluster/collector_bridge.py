"""Cross-host collector transport.

Parity: reference ``nodes/collector.py`` both roles —

- worker: PNG-encode each image, POST canonical envelopes
  ``{job_id, worker_id, batch_idx, image, is_last[, audio]}`` to the
  master's ``/distributed/job_complete`` (``:143-178``);
- master: drain the job's asyncio queue with sliced timeouts until every
  expected worker sent ``is_last``, then combine master-first/worker-order
  (``:252-295,381-499``).

On-pod gathers never touch this path (they're all_gather inside the SPMD
program); this bridge carries results **between hosts** over DCN/WAN where
a serialized envelope is genuinely required.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Optional, Sequence

import aiohttp
import numpy as np

from ..utils import constants
from ..utils.async_helpers import run_in_loop
from ..utils.audio_payload import decode_audio, encode_audio
from ..utils.exceptions import TileCollectionError, WorkerError
from ..utils.image import decode_image_b64, encode_image_b64, to_uint8, from_uint8
from ..utils.logging import debug_log, log
from ..utils.network import get_client_session, normalize_host_url, probe_host
from .job_store import JobStore


class CollectorBridge:
    """Bound to a controller's job store + event loop; node code calls the
    sync methods from the executor thread.

    ``host_resolver`` maps a worker id to its config host dict (or None);
    when provided, the master-side drain loop probes silent workers on
    timeout and extends the deadline while they are verifiably busy
    (reference busy-probe grace, ``nodes/collector.py:414-470``)."""

    def __init__(self, store: JobStore, loop: asyncio.AbstractEventLoop,
                 host_resolver=None):
        self.store = store
        self.loop = loop
        self.host_resolver = host_resolver

    # --- worker role -------------------------------------------------------

    def send(self, job_id: str, worker_id: str, images, audio,
             master_url: str) -> None:
        run_in_loop(
            self.send_async(job_id, worker_id, images, audio, master_url),
            self.loop,
            timeout=constants.DISPATCH_TIMEOUT * 4,
        )

    async def send_async(self, job_id: str, worker_id: str, images, audio,
                         master_url: str) -> None:
        arr = to_uint8(images) if images is not None else np.zeros((0, 1, 1, 3), np.uint8)
        n = arr.shape[0]
        session = get_client_session()
        if n and await self._send_frames(session, normalize_host_url(master_url),
                                         job_id, worker_id, arr, audio):
            return
        url = normalize_host_url(master_url) + "/distributed/job_complete"
        loop = asyncio.get_running_loop()
        for i in range(n):
            image_b64 = await loop.run_in_executor(
                None, encode_image_b64, arr[i])
            envelope: dict[str, Any] = {
                "job_id": job_id,
                "worker_id": worker_id,
                "batch_idx": i,
                "image": image_b64,
                "is_last": i == n - 1,
            }
            if i == n - 1 and audio is not None:
                envelope["audio"] = await loop.run_in_executor(
                    None, encode_audio, audio)
            await self._post_with_retry(session, url, envelope)
        if n == 0:
            # audio-only contribution (e.g. DistributedEmptyImage feeding
            # the image input): the completion envelope still carries the
            # AUDIO payload — dropping it here loses the worker's clip
            envelope = {
                "job_id": job_id, "worker_id": worker_id, "batch_idx": -1,
                "image": "", "is_last": True,
            }
            if audio is not None:
                envelope["audio"] = await loop.run_in_executor(
                    None, encode_audio, audio)
            await self._post_with_retry(session, url, envelope)
        debug_log(f"collector[{job_id}] worker {worker_id} sent {n} images")

    async def _send_frames(self, session, base_url: str, job_id: str,
                           worker_id: str, arr: np.ndarray, audio) -> bool:
        """Preferred transport: ONE multipart POST of crc-checked binary
        frames (native codec) instead of per-image base64-PNG JSON — the
        reference pays PNG+base64+HTTP per image (``collector.py:152-174``).
        Returns False if the master doesn't accept frames (legacy peer);
        caller falls back to the envelope protocol."""
        from .. import native

        url = base_url + "/distributed/job_complete_frames"
        loop = asyncio.get_running_loop()
        form = aiohttp.FormData()
        meta: dict[str, Any] = {"job_id": job_id, "worker_id": worker_id,
                                "count": int(arr.shape[0])}
        if audio is not None:
            meta["audio"] = await loop.run_in_executor(
                None, encode_audio, audio)
        form.add_field("metadata", json.dumps(meta),
                       content_type="application/json")
        # pack the whole batch in ONE executor hop — zlib deflate + crc
        # per multi-MB frame must not run on the event loop
        packed = await loop.run_in_executor(
            None,
            lambda: [native.pack_frame(arr[i], level=1)
                     for i in range(arr.shape[0])])
        for i, blob in enumerate(packed):
            form.add_field(f"frame_{i}", blob,
                           filename=f"frame_{i}.cdtf",
                           content_type="application/x-cdt-frame")
        try:
            async with session.post(url, data=form,
                                    headers={"X-CDT-Client": "1"}) as resp:
                if resp.status in (404, 405):
                    return False          # legacy master: use envelopes
                if resp.status < 400:
                    debug_log(f"collector[{job_id}] worker {worker_id} sent "
                              f"{arr.shape[0]} frames")
                    return True
                # any error (transient 5xx included) falls back to the
                # envelope path, which retries with exponential backoff —
                # a fire-and-forget send must never drop a finished job's
                # results on a single failed POST
                body = await resp.text()
                log(f"frame send {resp.status} ({body[:200]}); "
                    "using envelope fallback")
                return False
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            debug_log(f"frame send failed ({e}); using envelope fallback")
            return False

    async def _post_with_retry(self, session, url: str, payload: dict) -> None:
        """SEND_MAX_RETRIES attempts through the unified RetryPolicy
        (reference ``worker_comms.py:88-104``); safe to re-send because
        the master's collector drain keys envelopes by (worker_id,
        batch_idx) and duplicate is_last flags are idempotent."""
        from .resilience import send_policy

        async def attempt() -> None:
            async with session.post(url, json=payload) as resp:
                if resp.status >= 400:
                    body = await resp.text()
                    err = WorkerError(f"{resp.status}: {body[:200]}")
                    err.retry_safe = True
                    raise err

        try:
            await send_policy().run(attempt, op="collect")
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                WorkerError) as e:
            raise WorkerError(f"send to {url} failed after retries: {e}") from e

    # --- master role -------------------------------------------------------

    def collect(self, job_id: str, local_images, local_audio,
                enabled_worker_ids: Sequence[str] = (),
                delegate_only: bool = False,
                timeout: float | None = None):
        return run_in_loop(
            self.collect_async(job_id, local_images, local_audio,
                               enabled_worker_ids, delegate_only, timeout),
            self.loop,
            timeout=None,
        )

    async def collect_async(self, job_id: str, local_images, local_audio,
                            enabled_worker_ids: Sequence[str] = (),
                            delegate_only: bool = False,
                            timeout: float | None = None):
        job = await self.store.prepare_collector_job(
            job_id, tuple(enabled_worker_ids))
        overall = timeout or constants.HEARTBEAT_TIMEOUT * 4
        deadline = time.monotonic() + overall
        per_worker: dict[str, dict[int, np.ndarray]] = {w: {} for w in job.expected_workers}
        audio_parts: dict[str, dict] = {}
        # Completion is judged on the DRAIN side (is_last envelopes actually
        # consumed), never on arrival flags — otherwise the loop could exit
        # with envelopes still queued (same discipline as the reference's
        # drain loop, ``nodes/collector.py:381-499``).
        drained_done: set[str] = set()
        grace_rounds = 0

        while not drained_done >= set(job.expected_workers):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                missing = [w for w in job.expected_workers if w not in drained_done]
                busy = await self._probe_busy(missing)
                if busy and grace_rounds < constants.COLLECT_MAX_GRACE_ROUNDS:
                    grace_rounds += 1
                    deadline = time.monotonic() + constants.COLLECT_GRACE_S
                    log(f"collector[{job_id}] workers {busy} still busy; "
                        f"extending deadline (grace {grace_rounds})")
                    continue
                log(f"collector[{job_id}] timed out waiting for {missing}")
                break
            try:
                envelope = await asyncio.wait_for(
                    job.results.get(),
                    timeout=min(constants.COLLECT_POLL_TIMEOUT, remaining),
                )
            except asyncio.TimeoutError:
                continue
            w = envelope.get("worker_id", "")
            loop = asyncio.get_running_loop()
            if envelope.get("image_arr") is not None:
                per_worker.setdefault(w, {})[int(envelope.get("batch_idx", 0))] = (
                    from_uint8(envelope["image_arr"])
                )
            elif envelope.get("image"):
                per_worker.setdefault(w, {})[int(envelope.get("batch_idx", 0))] = (
                    await loop.run_in_executor(
                        None, decode_image_b64, envelope["image"])
                )
            if envelope.get("audio"):
                audio_parts[w] = await loop.run_in_executor(
                    None, decode_audio, envelope["audio"])
            if envelope.get("is_last"):
                drained_done.add(w)

        images = self._combine_images(local_images, per_worker, job.expected_workers,
                                      delegate_only)
        audio = self._combine_audio(local_audio, audio_parts, job.expected_workers)
        await self.store.cleanup_job(job_id)
        return images, audio

    async def _probe_busy(self, missing: Sequence[str]) -> list[str]:
        """Probe silent workers' health; return those with work still
        queued/executing. A dead host (probe None) or an idle one gets no
        grace — only a verifiably busy worker extends the drain deadline."""
        if self.host_resolver is None or not missing:
            return []
        resolvable = [(w, self.host_resolver(w)) for w in missing]
        resolvable = [(w, h) for w, h in resolvable if h]
        statuses = await asyncio.gather(
            *(probe_host(h) for _, h in resolvable))
        return [
            w for (w, _), status in zip(resolvable, statuses)
            if status and int(status.get("queue_remaining", 0) or 0) > 0
        ]

    @staticmethod
    def _combine_images(local_images, per_worker, expected: Sequence[str],
                        delegate_only: bool):
        """Master first, then workers in enabled order, batch_idx order
        within each worker (``nodes/collector.py:252-295``). A delegate-only
        master contributes nothing (``:329-333``)."""
        batches: list[np.ndarray] = []
        if local_images is not None and not delegate_only:
            local = np.asarray(local_images, dtype=np.float32)
            if local.size:
                batches.append(local)
        for w in expected:
            imgs = per_worker.get(w, {})
            for idx in sorted(imgs):
                batches.append(imgs[idx][None])
        if not batches:
            return local_images
        hw = batches[0].shape[1:3]
        kept = [b for b in batches if b.shape[1:3] == hw]
        if len(kept) != len(batches):
            log(f"collector: dropping {len(batches)-len(kept)} mismatched-size results")
        return np.concatenate(kept, axis=0)

    @staticmethod
    def _combine_audio(local_audio, audio_parts, expected: Sequence[str]):
        """Concatenate waveforms along samples (``:180-233``)."""
        parts = []
        if local_audio is not None:
            parts.append(local_audio)
        parts.extend(audio_parts[w] for w in expected if w in audio_parts)
        if not parts:
            return None
        sr = parts[0]["sample_rate"]
        wfs = [np.asarray(p["waveform"]) for p in parts]
        ch = min(w.shape[1] for w in wfs)
        wfs = [w[:, :ch, :] for w in wfs]
        return {"waveform": np.concatenate(wfs, axis=-1), "sample_rate": sr}
