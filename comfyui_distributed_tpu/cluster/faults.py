"""Deterministic fault injection for the cluster control plane.

The reference system's failure handling was only ever exercised by
killing real processes (slow, racy, unreproducible). This harness makes
every failure path reproducible under plain pytest: a seeded
:class:`FaultPlan` wraps the shared aiohttp session (and optionally the
job store) and injects faults at chosen **call indices** per operation —
same seed, same spec, same failures, every run.

Fault kinds:

- ``drop``      — connection never opens (``aiohttp.ClientConnectionError``)
- ``latency``   — delay the call by ``value`` seconds, then proceed
- ``http500``   — synthetic 5xx response (``value`` overrides the status)
- ``corrupt``   — flip one byte of the outbound payload (CDTF frames are
  crc-checked, so the receiver rejects it and the sender's RetryPolicy
  re-sends intact bytes)
- ``truncate``  — send only the first half of the outbound payload
- ``silence``   — swallow the call, return a fake 200 (heartbeat loss
  without connection errors — exactly what the timeout monitor detects)

Spec grammar (``CDT_FAULTS`` env var or test fixture)::

    spec    := clause (";" clause)*
    clause  := "seed=" int
             | op "@" sel ":" kind ["=" value]
    op      := probe | dispatch | request_work | submit | heartbeat
             | collect | media | http | *          (http = any unmatched)
    sel     := "*"                                 (every call)
             | int ("," int)* | int "-" int        (0-based call indices)
             | "%" float                           (seeded probability)

Example: ``seed=42;probe@0-1:drop;submit@3:corrupt;heartbeat@*:silence``
kills the first two probes, corrupts the 4th tile submit, and silences
every heartbeat — deterministically. Operations are classified by URL
path (``op_for_url``). Disabled (zero overhead beyond one ``is None``
check) unless a plan is active. See docs/resilience.md.
"""

from __future__ import annotations

import asyncio
import random
import re
import threading
from typing import Any, Optional

from ..telemetry import enabled as _tm_enabled, metrics as _tm
from ..utils.logging import debug_log, log

FAULTS_ENV = "CDT_FAULTS"

_KINDS = ("drop", "latency", "http500", "corrupt", "truncate", "silence")

# URL path suffix → operation name, first match wins (order matters:
# more specific prefixes first).
_OP_ROUTES: tuple[tuple[str, str], ...] = (
    ("/distributed/health", "probe"),
    ("/distributed/worker_ws", "dispatch"),
    ("/prompt", "dispatch"),
    ("/distributed/request_image", "request_work"),
    ("/distributed/submit_tiles", "submit"),
    ("/distributed/submit_image", "submit"),
    ("/distributed/heartbeat", "heartbeat"),
    ("/distributed/job_complete_frames", "collect"),
    ("/distributed/job_complete", "collect"),
    ("/distributed/job_status", "job_status"),
    ("/distributed/check_file", "media"),
    ("/upload/image", "media"),
)


def op_for_url(url: str) -> str:
    path = str(url).split("?", 1)[0]
    for suffix, op in _OP_ROUTES:
        if path.endswith(suffix):
            return op
    return "http"


class FaultSpecError(ValueError):
    """Malformed CDT_FAULTS spec."""


class Fault:
    """One injection rule: operation, selector, kind, optional value."""

    __slots__ = ("op", "kind", "indices", "prob", "value")

    def __init__(self, op: str, kind: str,
                 indices: Optional[frozenset[int]] = None,
                 prob: Optional[float] = None, value: float = 0.0):
        if kind not in _KINDS:
            raise FaultSpecError(f"unknown fault kind {kind!r} "
                                 f"(one of {', '.join(_KINDS)})")
        self.op = op
        self.kind = kind
        self.indices = indices        # None + prob None => every call
        self.prob = prob
        self.value = value

    def matches(self, op: str, index: int, rng: random.Random) -> bool:
        if self.op not in ("*", op):
            return False
        if self.prob is not None:
            return rng.random() < self.prob
        if self.indices is None:
            return True
        return index in self.indices

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        sel = ("*" if self.indices is None and self.prob is None
               else f"%{self.prob}" if self.prob is not None
               else ",".join(map(str, sorted(self.indices))))
        return f"Fault({self.op}@{sel}:{self.kind}={self.value})"


def _parse_selector(sel: str) -> tuple[Optional[frozenset[int]],
                                       Optional[float]]:
    sel = sel.strip()
    if sel == "*":
        return None, None
    if sel.startswith("%"):
        try:
            p = float(sel[1:])
        except ValueError:
            raise FaultSpecError(f"bad probability selector {sel!r}")
        if not 0.0 <= p <= 1.0:
            raise FaultSpecError(f"probability out of [0,1]: {sel!r}")
        return None, p
    indices: set[int] = set()
    for part in sel.split(","):
        part = part.strip()
        m = re.fullmatch(r"(\d+)-(\d+)", part)
        if m:
            lo, hi = int(m.group(1)), int(m.group(2))
            if hi < lo:
                raise FaultSpecError(f"empty index range {part!r}")
            indices.update(range(lo, hi + 1))
        elif part.isdigit():
            indices.add(int(part))
        else:
            raise FaultSpecError(f"bad index selector {part!r}")
    return frozenset(indices), None


class FaultPlan:
    """A seeded, ordered set of faults plus per-operation call counters.

    ``next_fault(op)`` consumes one call index for ``op`` and returns the
    matching fault (or None). All randomness (probability selectors,
    corruption byte choice) flows from the plan's seed, so a failing
    chaos run replays exactly with the same spec.
    """

    def __init__(self, faults: list[Fault], seed: int = 0):
        self.faults = list(faults)
        self.seed = seed
        self.rng = random.Random(seed)
        self._lock = threading.Lock()
        self.calls: dict[str, int] = {}
        self.injected: list[tuple[str, int, str]] = []   # (op, index, kind)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults: list[Fault] = []
        seed = 0
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                try:
                    seed = int(clause[5:])
                except ValueError:
                    raise FaultSpecError(f"bad seed clause {clause!r}")
                continue
            m = re.fullmatch(
                r"([\w.*]+)@([^:]+):([a-z0-9]+)(?:=([\d.]+))?", clause)
            if not m:
                raise FaultSpecError(
                    f"bad fault clause {clause!r} "
                    "(want op@sel:kind[=value])")
            op, sel, kind, value = m.groups()
            indices, prob = _parse_selector(sel)
            faults.append(Fault(op, kind, indices, prob,
                                float(value) if value else 0.0))
        return cls(faults, seed=seed)

    def next_fault(self, op: str) -> Optional[Fault]:
        with self._lock:
            index = self.calls.get(op, 0)
            self.calls[op] = index + 1
            for f in self.faults:
                if f.matches(op, index, self.rng):
                    self.injected.append((op, index, f.kind))
                    if _tm_enabled():
                        _tm.FAULTS_INJECTED.labels(op=op, kind=f.kind).inc()
                    debug_log(f"faults: injecting {f.kind} into "
                              f"{op}[{index}]")
                    return f
        return None

    # -- payload mutation (seeded) ------------------------------------------

    def corrupt_bytes(self, data: bytes) -> bytes:
        if not data:
            return data
        i = self.rng.randrange(len(data))
        return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]

    @staticmethod
    def truncate_bytes(data: bytes) -> bytes:
        return data[: max(1, len(data) // 2)] if data else data


# ---------------------------------------------------------------------------
# activation (env or test fixture)
# ---------------------------------------------------------------------------

_active: Optional[FaultPlan] = None
_env_checked = False


def activate(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or clear, with None) the process-wide plan. Returns it."""
    global _active, _env_checked
    _active = plan
    _env_checked = True     # explicit activation overrides the env
    if plan is not None:
        log(f"faults: plan active (seed={plan.seed}, "
            f"{len(plan.faults)} rules)")
    return plan


def deactivate() -> None:
    global _active, _env_checked
    _active = None
    _env_checked = False    # re-read CDT_FAULTS on next use


def active_plan() -> Optional[FaultPlan]:
    global _active, _env_checked
    if not _env_checked:
        _env_checked = True
        from ..utils.constants import FAULTS

        spec = FAULTS.get()
        if spec:
            _active = FaultPlan.parse(spec)
            log(f"faults: {FAULTS_ENV} plan active (seed={_active.seed}, "
                f"{len(_active.faults)} rules)")
    return _active


# ---------------------------------------------------------------------------
# aiohttp session wrapper
# ---------------------------------------------------------------------------

class _FakeResponse:
    """Minimal synthetic response for http500/silence injections."""

    def __init__(self, status: int, body: str = ""):
        self.status = status
        self._body = body or ('{"error": "injected fault"}'
                              if status >= 400 else '{"status": "ok"}')
        self.headers: dict[str, str] = {"Content-Type": "application/json"}

    async def text(self) -> str:
        return self._body

    async def json(self, content_type: Any = None) -> Any:
        import json as _json

        return _json.loads(self._body)

    async def read(self) -> bytes:
        return self._body.encode()

    async def release(self) -> None:
        pass

    async def __aenter__(self) -> "_FakeResponse":
        return self

    async def __aexit__(self, *exc) -> None:
        pass


def _mutate_payload(kw: dict, fault: Fault, plan: FaultPlan) -> dict:
    """Corrupt/truncate the outbound body: raw bytes directly; FormData by
    rebuilding it with mutated bytes fields (the largest bytes field — the
    CDTF frame — is the intended target; JSON metadata stays intact)."""
    import aiohttp

    mutate = (plan.corrupt_bytes if fault.kind == "corrupt"
              else plan.truncate_bytes)
    data = kw.get("data")
    if isinstance(data, (bytes, bytearray)):
        kw = {**kw, "data": mutate(bytes(data))}
        return kw
    if isinstance(data, aiohttp.FormData):
        fields = getattr(data, "_fields", None)
        if not fields:
            return kw
        # pick the largest bytes field (the frame, not the metadata)
        target = None
        for i, (opts, headers, value) in enumerate(fields):
            if isinstance(value, (bytes, bytearray)) and (
                    target is None
                    or len(value) > len(fields[target][2])):
                target = i
        if target is None:
            return kw
        rebuilt = aiohttp.FormData()
        for i, (opts, headers, value) in enumerate(fields):
            v = (mutate(bytes(value)) if i == target else value)
            rebuilt.add_field(
                opts.get("name", f"field_{i}"), v,
                filename=opts.get("filename"),
                content_type=headers.get("Content-Type"))
        kw = {**kw, "data": rebuilt}
    return kw


class _FaultRequestCtx:
    """Async-CM shim around a (possibly faulted) request."""

    def __init__(self, session, method: str, url: str, kw: dict,
                 plan: FaultPlan):
        self._session = session
        self._method = method
        self._url = url
        self._kw = kw
        self._plan = plan
        self._inner = None

    async def __aenter__(self):
        import aiohttp

        fault = self._plan.next_fault(op_for_url(self._url))
        kw = self._kw
        if fault is not None:
            if fault.kind == "drop":
                raise aiohttp.ClientConnectionError(
                    f"injected drop ({self._url})")
            if fault.kind == "silence":
                return _FakeResponse(200)
            if fault.kind == "http500":
                return _FakeResponse(int(fault.value) or 500)
            if fault.kind == "latency":
                await asyncio.sleep(fault.value or 0.05)
            elif fault.kind in ("corrupt", "truncate"):
                kw = _mutate_payload(dict(kw), fault, self._plan)
        self._inner = getattr(self._session, self._method)(self._url, **kw)
        return await self._inner.__aenter__()

    async def __aexit__(self, *exc):
        if self._inner is not None:
            return await self._inner.__aexit__(*exc)
        return False


class _FaultWSCtx:
    def __init__(self, session, url: str, kw: dict, plan: FaultPlan):
        self._session = session
        self._url = url
        self._kw = kw
        self._plan = plan
        self._inner = None

    async def __aenter__(self):
        import aiohttp

        fault = self._plan.next_fault(op_for_url(self._url))
        if fault is not None:
            if fault.kind == "drop":
                raise aiohttp.ClientConnectionError(
                    f"injected ws drop ({self._url})")
            if fault.kind == "latency":
                await asyncio.sleep(fault.value or 0.05)
        self._inner = self._session.ws_connect(self._url, **self._kw)
        return await self._inner.__aenter__()

    async def __aexit__(self, *exc):
        if self._inner is not None:
            return await self._inner.__aexit__(*exc)
        return False


class FaultSession:
    """aiohttp-session proxy injecting the active plan's faults on
    get/post/ws_connect; everything else passes through untouched."""

    def __init__(self, session, plan: FaultPlan):
        self._session = session
        self._plan = plan

    def get(self, url, **kw):
        return _FaultRequestCtx(self._session, "get", url, kw, self._plan)

    def post(self, url, **kw):
        return _FaultRequestCtx(self._session, "post", url, kw, self._plan)

    def ws_connect(self, url, **kw):
        return _FaultWSCtx(self._session, url, kw, self._plan)

    def __getattr__(self, name):
        return getattr(self._session, name)


def wrap_session(session):
    """Return the session wrapped with the active plan, or unchanged when
    no plan is active (the production fast path: one None check)."""
    plan = active_plan()
    if plan is None:
        return session
    return FaultSession(session, plan)


# ---------------------------------------------------------------------------
# job-store wrapper (in-process fault tests without HTTP)
# ---------------------------------------------------------------------------

class FaultyJobStore:
    """JobStore proxy for in-process chaos tests: ``request_work`` /
    ``submit_result`` / ``heartbeat`` consult the plan (ops are prefixed
    ``store.``), everything else passes through."""

    def __init__(self, store, plan: FaultPlan):
        self._store = store
        self._plan = plan

    async def request_work(self, job_id, worker_id):
        fault = self._plan.next_fault("store.request_work")
        if fault is not None:
            if fault.kind == "drop":
                return None
            if fault.kind == "latency":
                await asyncio.sleep(fault.value or 0.05)
            elif fault.kind == "http500":
                from ..utils.exceptions import JobQueueError

                raise JobQueueError("injected store failure", job_id=job_id)
        return await self._store.request_work(job_id, worker_id)

    async def submit_result(self, job_id, worker_id, task_id, payload):
        fault = self._plan.next_fault("store.submit")
        if fault is not None:
            if fault.kind in ("drop", "silence"):
                return False
            if fault.kind == "latency":
                await asyncio.sleep(fault.value or 0.05)
            elif fault.kind == "http500":
                from ..utils.exceptions import JobQueueError

                raise JobQueueError("injected store failure", job_id=job_id)
        return await self._store.submit_result(job_id, worker_id, task_id,
                                               payload)

    async def heartbeat(self, job_id, worker_id):
        fault = self._plan.next_fault("store.heartbeat")
        if fault is not None and fault.kind in ("drop", "silence"):
            return False
        return await self._store.heartbeat(job_id, worker_id)

    def __getattr__(self, name):
        return getattr(self._store, name)
