"""The orchestration pipeline behind ``POST /distributed/queue``.

Parity: reference ``api/queue_orchestration.py:200-418`` — resolve enabled
workers → bounded probe → (optional) least-busy single selection →
job-ID map → pre-create collector queues → per-participant payload prep
under a semaphore → parallel dispatch → queue the master's own prompt.
Delegate-only auto-disables when no worker is reachable (``:247-252``).

TPU note: "workers" here are *host controllers* (each owning chips/a pod
slice), not per-GPU processes; a single-host deployment never enters this
module's fan-out path — the mesh handles its chips inside one program.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any, Optional, Sequence

from ..graph.transform import (
    apply_participant_overrides,
    generate_job_id_map,
    prepare_delegate_master_prompt,
    prune_prompt_for_worker,
)
from ..utils import constants
from ..utils.config import load_config
from ..utils.exceptions import WorkerError
from ..utils.logging import new_trace_id, trace_info
from ..utils.network import build_master_callback_url
from .dispatch import dispatch_prompt, select_active_hosts, select_least_busy_host
from .job_store import JobStore
from .media_sync import sync_host_media
from .runtime import PromptQueue


@dataclasses.dataclass
class OrchestrationResult:
    prompt_id: str
    node_errors: list
    worker_count: int
    dispatched_to: list[str]
    trace_id: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Orchestrator:
    def __init__(self, store: JobStore, queue: PromptQueue,
                 config_loader=load_config):
        self.store = store
        self.queue = queue
        self.load_config = config_loader

    @staticmethod
    def _normalized_hosts(config: dict) -> list[dict]:
        """Full config host list as copies with a guaranteed UNIQUE ``id``
        (synthetic ``host{config_position}`` when absent, skipping names an
        explicit id already claims). Copies survive the probe layer's dict
        rebuilding, so the same name reaches every site — stable indexing
        must never depend on object identity."""
        hosts = config.get("hosts", [])
        taken = {h.get("id") for h in hosts if h.get("id")}
        out = []
        for i, h in enumerate(hosts):
            if h.get("id"):
                out.append(h)
                continue
            name = f"host{i}"
            while name in taken:
                name += "_"
            taken.add(name)
            out.append({**h, "id": name})
        return out

    def _resolve_enabled_hosts(
        self, all_hosts: list[dict], enabled_ids: Optional[Sequence[str]]
    ) -> list[dict]:
        """Explicit ids win; else config-enabled hosts
        (reference ``:63-93`` incl. the legacy ``workers`` alias handled in
        the API layer)."""
        if enabled_ids is not None:
            by_id = {h["id"]: h for h in all_hosts}
            return [by_id[i] for i in enabled_ids if i in by_id]
        return [h for h in all_hosts if h.get("enabled")]

    async def orchestrate(
        self,
        prompt: dict,
        client_id: str = "",
        enabled_ids: Optional[Sequence[str]] = None,
        delegate_master: Optional[bool] = None,
        load_balance: bool = False,
        trace_id: str | None = None,
        queue_meta: Optional[dict] = None,
    ) -> OrchestrationResult:
        from ..graph.executor import strip_meta
        from ..telemetry import span as _tm_span

        prompt = strip_meta(prompt)
        trace_id = trace_id or new_trace_id()
        # the orchestration trace id (exec_…) doubles as the telemetry
        # trace id: probe/dispatch spans open underneath, dispatched hosts
        # join via X-CDT-Trace, and /distributed/trace/{trace_id} shows
        # the whole fan-out as one timeline
        with _tm_span("orchestrate", trace_id=trace_id, job_id=trace_id):
            return await self._orchestrate_inner(
                prompt, client_id, enabled_ids, delegate_master,
                load_balance, trace_id, queue_meta or {})

    async def _orchestrate_inner(
        self,
        prompt: dict,
        client_id: str,
        enabled_ids: Optional[Sequence[str]],
        delegate_master: Optional[bool],
        load_balance: bool,
        trace_id: str,
        queue_meta: dict,
    ) -> OrchestrationResult:
        config = self.load_config()
        all_hosts = self._normalized_hosts(config)
        candidates = self._resolve_enabled_hosts(all_hosts, enabled_ids)
        if delegate_master is None:
            delegate_master = bool(
                config.get("settings", {}).get("master_delegate_only")
            )
        trace_info(trace_id, f"orchestrating over {len(candidates)} candidate hosts "
                             f"(delegate={delegate_master})")

        online, offline = await select_active_hosts(
            candidates,
            probe_concurrency=config.get("settings", {}).get(
                "worker_probe_concurrency", constants.WORKER_PROBE_CONCURRENCY),
            trace_id=trace_id,
        )
        if load_balance and online:
            chosen = select_least_busy_host(online)
            online = [chosen] if chosen else []
        if not online and delegate_master:
            # nobody to delegate to → master must compute after all (:247-252)
            trace_info(trace_id, "no online workers; delegate mode disabled")
            delegate_master = False

        job_ids = generate_job_id_map(prompt, trace_id)
        # worker_index is the host's position in the FULL config host list
        # (one numbering scheme for every host, unique by construction, and
        # the exact list the dashboard's widget layer keys its 1-indexed
        # worker_values by) — never the online survivors or a
        # caller-supplied enabled_ids subset: DistributedSeed offsets and
        # per-worker overrides stay pinned to the same host across outages,
        # load-balance picks, partial dispatches, and enable-flag flips
        # (reference parity: worker_N's offset comes from its config
        # number, nodes/utilities.py:52-75). Every host carries a
        # guaranteed id from _normalized_hosts, so names match across the
        # probe layer's dict copies.
        stable_index = {h["id"]: i for i, h in enumerate(all_hosts)}
        worker_ids = tuple(h["id"] for h in online)
        for jid in job_ids.values():
            await self.store.prepare_collector_job(jid, worker_ids)

        # master payload
        if delegate_master:
            master_prompt = prepare_delegate_master_prompt(prompt)
        else:
            master_prompt = prompt
        master_prompt = apply_participant_overrides(
            master_prompt, "master", job_ids,
            enabled_worker_ids=worker_ids, delegate_only=delegate_master,
        )

        # worker payloads + dispatch (prep bounded like reference :367-388)
        sem = asyncio.Semaphore(
            config.get("settings", {}).get("worker_prep_concurrency",
                                           constants.WORKER_PREP_CONCURRENCY))

        async def prep_and_dispatch(index: int, host: dict) -> tuple[str, Optional[str]]:
            async with sem:
                wid = host["id"]
                host_type = host.get("type")
                if host_type not in ("local", "remote"):
                    # config didn't pin a type: machine-id comparison
                    # (reference workers/detection.py:11-47)
                    from ..workers.detection import classify_host
                    host_type = await classify_host(host)
                callback = build_master_callback_url(
                    config.get("master", {}),
                    for_local=host_type == "local",
                )
                wprompt = prune_prompt_for_worker(prompt)
                if not wprompt:
                    return wid, "nothing to dispatch (no distributed nodes)"
                wprompt = apply_participant_overrides(
                    wprompt, wid, job_ids, master_url=callback,
                    enabled_worker_ids=worker_ids,
                    worker_index=stable_index[wid],
                )
                if host_type == "remote":
                    # remote hosts don't share the master's filesystem:
                    # content-addressed sync before dispatch (reference
                    # api/queue_orchestration.py:141-197)
                    settings = config.get("settings", {})
                    wprompt, sync_report = await sync_host_media(
                        host, wprompt,
                        concurrency=settings.get(
                            "media_sync_concurrency",
                            constants.MEDIA_SYNC_CONCURRENCY),
                        timeout=settings.get(
                            "media_sync_timeout_seconds",
                            constants.MEDIA_SYNC_TIMEOUT),
                        trace_id=trace_id,
                    )
                    if sync_report.failed:
                        # dispatching anyway would leave the collector
                        # waiting on a host that provably lacks its inputs
                        return wid, (f"media sync failed for "
                                     f"{sync_report.failed}")
                try:
                    await dispatch_prompt(
                        host, wprompt, client_id,
                        extra={"trace_id": trace_id}, trace_id=trace_id,
                        via_ws=bool(config.get("settings", {}).get(
                            "websocket_orchestration")))
                    return wid, None
                except WorkerError as e:
                    return wid, str(e)

        dispatch_results = await asyncio.gather(
            *(prep_and_dispatch(i, h) for i, h in enumerate(online))
        )
        dispatched = [wid for wid, err in dispatch_results if err is None]
        failures = {wid: err for wid, err in dispatch_results if err}
        if failures:
            trace_info(trace_id, f"dispatch failures: {failures}")
            # collector must not wait on hosts that never got the job
            for jid in job_ids.values():
                await self.store.prepare_collector_job(
                    jid, tuple(w for w in worker_ids if w in dispatched))
        if delegate_master and not dispatched:
            # graceful degradation: every dispatch failed AFTER probing
            # succeeded (breakers/flap mid-orchestration). The delegate-
            # pruned master prompt would execute nothing and the job
            # would complete empty — rebuild it as a full local run
            # instead of failing the job (docs/resilience.md).
            trace_info(trace_id, "all dispatches failed; delegate mode "
                                 "disabled — master computes locally")
            master_prompt = apply_participant_overrides(
                prompt, "master", job_ids,
                enabled_worker_ids=(), delegate_only=False,
            )

        # front-door metadata (tenant/priority/deadline) rides into the
        # queue so non-batchable requests still get admission-class
        # telemetry and deadline handling
        prompt_id, node_errors = self.queue.enqueue(
            master_prompt, client_id, trace_id, **queue_meta)
        return OrchestrationResult(
            prompt_id=prompt_id,
            node_errors=node_errors,
            worker_count=len(dispatched),
            dispatched_to=dispatched,
            trace_id=trace_id,
        )
