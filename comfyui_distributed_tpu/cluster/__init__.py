"""Cluster control plane (reference L4: ``api/queue_orchestration.py``,
``upscale/job_store.py``, ``api/orchestration/*``).

Scope note (SURVEY §7): on-pod parallelism needs none of this — chips talk
over ICI inside compiled programs. This layer exists for the *multi-host*
story (several host controllers, each owning a mesh slice or a whole pod)
and for parity with the reference's public behavior: job registry, result
collection across hosts, liveness probing, least-busy selection, heartbeat
timeout + requeue, and the orchestration pipeline behind
``POST /distributed/queue``.
"""

from .job_models import CollectorJob, TileJob, TileTask  # noqa: F401
from .job_store import JobStore  # noqa: F401
from .job_timeout import check_and_requeue_timed_out_workers  # noqa: F401
from .dispatch import probe_host, select_active_hosts, select_least_busy_host  # noqa: F401
from .collector_bridge import CollectorBridge  # noqa: F401
from .media_sync import (  # noqa: F401
    MediaRef,
    SyncReport,
    convert_paths_for_platform,
    find_media_refs,
    sync_host_media,
)
from .runtime import PromptQueue  # noqa: F401
from .orchestration import Orchestrator, OrchestrationResult  # noqa: F401
