"""Host controller runtime: the local prompt queue + execution context.

The reference relies on ComfyUI's PromptServer queue + executor
(``utils/async_helpers.py:108-149`` pushes into ``prompt_queue``). This is
the standalone equivalent: an asyncio consumer that validates prompts,
executes them in a worker thread (JAX compute must not block the loop),
and exposes ``queue_remaining`` for health probes — the field the
reference's least-busy scheduler reads (``dispatch.py:225-268``).

Two job shapes ride the same queue: classic solo prompts, and *batch
jobs* from the serving front door (``cluster/frontdoor``) — N coalesced
member prompts executed as one unit with a shared microbatched sampler
program. Either way execution is serialized per controller (one mesh,
one program at a time); batching raises the work per program, not the
number of concurrent programs.
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import secrets
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from .. import telemetry
from ..graph.executor import GraphExecutor, strip_meta, validate_prompt
from ..telemetry import metrics as _tm
from ..utils import constants
from ..utils.exceptions import ValidationError
from ..utils.logging import log, trace_info


@dataclasses.dataclass
class PromptJob:
    prompt_id: str
    prompt: dict
    client_id: str = ""
    trace_id: str | None = None
    # master-side dispatch span id carried by X-CDT-Trace: the execution
    # span parents onto it so cross-host traces stitch (telemetry/spans)
    parent_span_id: str | None = None
    enqueued_at: float = dataclasses.field(default_factory=time.monotonic)
    future: Optional[asyncio.Future] = None
    # --- serving front door metadata (cluster/frontdoor) -------------------
    tenant: str = constants.DEFAULT_TENANT
    priority: str = constants.DEFAULT_PRIORITY
    # monotonic deadline; a job still queued past it is recorded
    # "expired" instead of executed (the client asked for freshness)
    deadline_at: float | None = None
    # batch jobs: the coalesced member jobs (each with its own prompt_id/
    # deadline) and each member's sampler node id. ``prompt`` is unused.
    group: "list[PromptJob] | None" = None
    sampler_node_ids: dict | None = None
    # --- content cache (cluster/cache, docs/caching.md) ---------------------
    # full request fingerprint (set by the front door for the
    # deterministic-batchable class); cache_mode "bypass" skips serving
    # this member from the result cache (it still fills it)
    fingerprint: str | None = None
    cache_mode: str = "use"
    # --- step-granular preemption (cluster/preemption.py) -------------------
    # checkpoint_id: parked LatentCheckpoint to resume from (set when
    # this job was preempted, or by a resume request through the front
    # door); preempt_count bounds yielding (CDT_PREEMPT_MAX);
    # resume_attempts bounds restore retries before dead-letter
    checkpoint_id: str | None = None
    preempt_count: int = 0
    resume_attempts: int = 0
    # stable arrival order within a priority class (assigned by _put;
    # a preempted job keeps its original position on requeue)
    seq: int = 0

    def expired(self, now: float) -> bool:
        return self.deadline_at is not None and now >= self.deadline_at


class PromptQueue:
    """Priority-ordered prompt queue with a single execution worker.

    Execution is serialized per controller (one mesh, one program at a
    time — the TPU analogue of one ComfyUI executor per GPU process).
    Dequeue order is strict priority class, resumes-first within a
    class, then arrival order — the scheduling half of step-granular
    preemption (``cluster/preemption.py``): preempting a low-priority
    job is only useful if the waiting high-priority job actually runs
    next, and a preempted job's parked work resumes before fresh
    arrivals of its own class.
    """

    def __init__(self, context_factory: Callable[[], dict] | None = None):
        import itertools
        import threading

        # jobs live in _pending (priority-selected at dequeue); _wake is
        # the consumer's wakeup channel — one token per _put, tokens may
        # outnumber jobs after interrupt()/expiry drains, the consumer
        # just re-checks
        self._pending: list[PromptJob] = []
        self._wake: asyncio.Queue[None] = asyncio.Queue()
        self._seq = itertools.count()
        self._context_factory = context_factory or (lambda: {})
        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="graph-exec")
        self._task: Optional[asyncio.Task] = None
        self._sweep_task: Optional[asyncio.Task] = None
        self._executing: Optional[str] = None
        self.executing_job: Optional[PromptJob] = None
        self._interrupt = threading.Event()
        # cumulative seconds the consumer spent on jobs — the fused
        # path's "mesh lane busy" denominator bench.py's stages A/B
        # divides denoise-program time by (docs/stages.md)
        self.busy_seconds = 0.0
        self.history: dict[str, dict] = {}
        self._job_done_callbacks: list[Callable[[], None]] = []
        self._pending_by_priority: dict[str, int] = {}
        # step-granular preemption controller (cluster/preemption.py),
        # attached by the host controller; None = monolithic execution
        self.preemption = None
        # disaggregated stage-split serving (cluster/stages,
        # docs/stages.md), attached by the host controller; None =
        # fused group execution (CDT_STAGES=0)
        self.stages = None

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())
        sweep_s = constants.PREEMPT_SWEEP_S.get()
        if sweep_s > 0 and (self._sweep_task is None
                            or self._sweep_task.done()):
            self._sweep_task = asyncio.ensure_future(
                self._sweep_loop(sweep_s))

    async def stop(self) -> None:
        for task in (self._task, self._sweep_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        self._task = self._sweep_task = None
        self._pool.shutdown(wait=False, cancel_futures=True)

    def add_job_done_callback(self, cb: Callable[[], None]) -> None:
        """Called (on the event loop) after every job finishes — the
        front door uses it to flush the next coalesced group the moment
        a queue slot frees."""
        if cb not in self._job_done_callbacks:
            self._job_done_callbacks.append(cb)

    # --- producer ----------------------------------------------------------

    def enqueue(self, prompt: dict, client_id: str = "",
                trace_id: str | None = None,
                parent_span_id: str | None = None,
                tenant: str = constants.DEFAULT_TENANT,
                priority: str = constants.DEFAULT_PRIORITY,
                deadline_at: float | None = None,
                checkpoint_id: str | None = None) -> tuple[str, list]:
        """Validate + enqueue; returns (prompt_id, node_errors). Mirrors
        ``queue_prompt_payload``: validation errors reject the prompt
        before it reaches the queue (``utils/async_helpers.py:108-149``).
        ``checkpoint_id`` resumes a parked latent checkpoint
        (docs/preemption.md) — the sampler picks up mid-ladder."""
        prompt = strip_meta(prompt)
        errors = validate_prompt(prompt)
        if errors:
            return "", [e.as_dict() for e in errors]
        prompt_id = f"p_{int(time.time()*1000)}_{secrets.token_hex(3)}"
        job = PromptJob(prompt_id, prompt, client_id, trace_id,
                        parent_span_id=parent_span_id, tenant=tenant,
                        priority=priority, deadline_at=deadline_at,
                        checkpoint_id=checkpoint_id)
        self._put(job)
        return prompt_id, []

    def enqueue_batch(self, members: "list[PromptJob]",
                      sampler_node_ids: dict) -> list[str]:
        """Enqueue one batch job carrying pre-validated member prompts
        (the front door validates at submission). Returns member ids."""
        if not members:
            return []
        job = PromptJob(
            prompt_id=f"b_{int(time.time()*1000)}_{secrets.token_hex(3)}",
            prompt={}, group=list(members),
            sampler_node_ids=dict(sampler_node_ids),
            trace_id=members[0].trace_id,
            priority=min((m.priority for m in members),
                         key=_priority_rank),
        )
        self._put(job)
        return [m.prompt_id for m in members]

    def _put(self, job: PromptJob) -> None:
        if job.seq == 0:
            job.seq = next(self._seq) + 1
        self._pending.append(job)
        self._wake.put_nowait(None)
        for prio, n in _job_members(job):
            self._pending_by_priority[prio] = \
                self._pending_by_priority.get(prio, 0) + n
        if telemetry.enabled():
            _tm.PROMPT_QUEUE_DEPTH.set(self.queue_remaining)
            self._export_priority_depth()
        if self.preemption is not None:
            # a higher class arriving behind a running low-priority job
            # is THE preemption trigger (cluster/preemption.py)
            self.preemption.reevaluate()
        self.start()

    def _pop_next(self) -> Optional[PromptJob]:
        """Highest-priority pending job: class rank, resumes before
        fresh work within a class, then arrival order."""
        if not self._pending:
            return None
        job = min(self._pending, key=_dequeue_key)
        self._pending.remove(job)
        return job

    def _discard_parked(self, job: PromptJob) -> None:
        """A job dropped from the queue (interrupt, deadline expiry)
        releases its parked checkpoint — store bytes and the
        cdt_jobs_preempted gauge must not leak."""
        if self.preemption is None:
            return
        for m in (job.group or [job]):
            if getattr(m, "checkpoint_id", None):
                self.preemption.discard(m)

    def pending_best_rank(self) -> Optional[int]:
        """Best (lowest) priority rank waiting — the preemption
        controller's trigger signal. Group jobs count at their best
        member's class."""
        ranks = [min(_priority_rank(m.priority)
                     for m in (job.group or [job]))
                 for job in self._pending]
        return min(ranks) if ranks else None

    def _job_finished_accounting(self, job: PromptJob) -> None:
        for prio, n in _job_members(job):
            left = self._pending_by_priority.get(prio, 0) - n
            self._pending_by_priority[prio] = max(0, left)
        if telemetry.enabled():
            self._export_priority_depth()

    def _export_priority_depth(self) -> None:
        for prio, n in self._pending_by_priority.items():
            _tm.FD_QUEUE_DEPTH.labels(stage="queued", priority=prio).set(n)

    @property
    def queue_remaining(self) -> int:
        return len(self._pending) + (1 if self._executing else 0)

    def expire_stale(self, now: float | None = None) -> int:
        """Terminal-expire queued jobs whose deadline has passed — the
        sweep half of the freshness contract: a client's deadline is
        honored PROMPTLY, not only when a dispatch next touches the job
        (docs/preemption.md). Group jobs expire member-by-member; the
        job itself leaves the queue once every member is stale. Returns
        the number of members expired."""
        if now is None:
            now = time.monotonic()
        expired = 0
        for job in list(self._pending):
            members = job.group or [job]
            # a "preempted"/"resume_*" history row is NON-terminal — a
            # parked job waiting to resume past its deadline must sweep
            # exactly like a fresh one (its checkpoint is released)
            stale = [m for m in members if m.expired(now)
                     and self.history.get(m.prompt_id, {}).get("status")
                     not in TERMINAL_STATUSES]
            if not stale:
                continue
            if len(stale) < len(members):
                continue     # partially-stale group: execution expires
                #              the stale members individually
            self._pending.remove(job)
            for m in members:
                self.history[m.prompt_id] = {
                    "status": "expired", "duration": 0.0,
                    "error": "deadline_ms elapsed while queued",
                }
                expired += 1
                log(f"prompt {m.prompt_id} expired in queue (sweep)")
            self._discard_parked(job)
            self._job_finished_accounting(job)
            if telemetry.enabled():
                for _ in members:
                    _tm.PROMPTS_TOTAL.labels(status="expired").inc()
                _tm.PROMPT_QUEUE_DEPTH.set(self.queue_remaining)
        if expired:
            for cb in self._job_done_callbacks:
                try:
                    cb()
                except Exception:  # noqa: BLE001 — observer isolation
                    pass
        return expired

    async def _sweep_loop(self, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            self.expire_stale()

    def interrupt(self) -> int:
        """Drop pending prompts and flag the running one (checked between
        nodes — parity with the reference's interrupt fan-out,
        ``web/workerUtils.js:73-95``). Returns number of dropped jobs
        (batch members count individually)."""
        dropped = 0
        for job in list(self._pending):
            self._pending.remove(job)
            for member in (job.group or [job]):
                self.history[member.prompt_id] = {"status": "interrupted",
                                                  "duration": 0.0}
                dropped += 1
            self._discard_parked(job)
            self._job_finished_accounting(job)
        if self._executing:
            self._interrupt.set()
        if dropped:
            # dropped jobs reached terminal history WITHOUT passing the
            # consumer loop — observers (front-door flush, coalescer
            # waiter resolution) must still see the transition, or a
            # waiter on an interrupted leader would hang forever
            for cb in self._job_done_callbacks:
                try:
                    cb()
                except Exception:  # noqa: BLE001 — observer isolation
                    pass
        return dropped

    @property
    def executing(self) -> Optional[str]:
        return self._executing

    # --- consumer ----------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await self._wake.get()
            job = self._pop_next()
            if job is None:
                continue     # interrupt()/sweep drained it first
            self._executing = job.prompt_id
            self.executing_job = job
            started = time.monotonic()
            self._interrupt.clear()
            statuses: list[str] = []
            try:
                if telemetry.enabled():
                    for m in (job.group or [job]):
                        _tm.QUEUE_WAIT_SECONDS.labels(
                            priority=m.priority).observe(
                                started - m.enqueued_at)
                if self.preemption is not None:
                    # a strictly-higher class may already be waiting when
                    # a lower job starts (it was the best available)
                    self.preemption.reevaluate()
                if job.group is not None:
                    statuses = await self._run_group(loop, job, started)
                else:
                    statuses = [await self._run_solo(loop, job, started)]
            finally:
                self.busy_seconds += time.monotonic() - started
                self._executing = None
                self.executing_job = None
                if self.preemption is not None:
                    self.preemption.end(job)
                self._job_finished_accounting(job)
                if telemetry.enabled():
                    # cdt_prompts_total counts TERMINAL statuses only;
                    # a preempted/resume-retrying dispatch is the same
                    # logical prompt coming back — preemptions have
                    # their own counter (cdt_preemptions_total), and a
                    # partial segment batch must not skew the
                    # end-to-end duration histogram
                    terminal = [s for s in statuses
                                if s in TERMINAL_STATUSES]
                    for status in terminal:
                        _tm.PROMPTS_TOTAL.labels(status=status).inc()
                    if terminal and len(terminal) == len(statuses):
                        _tm.PROMPT_SECONDS.observe(
                            time.monotonic() - started)
                    _tm.PROMPT_QUEUE_DEPTH.set(self.queue_remaining)
                for cb in self._job_done_callbacks:
                    try:
                        cb()
                    except Exception:  # noqa: BLE001 — observer isolation
                        pass

    async def _run_solo(self, loop, job: PromptJob, started: float) -> str:
        if job.expired(started):
            self.history[job.prompt_id] = {
                "status": "expired", "duration": 0.0,
                "error": "deadline_ms elapsed before execution",
            }
            log(f"prompt {job.prompt_id} expired in queue")
            self._discard_parked(job)
            return "expired"
        from ..diffusion.checkpoint import (CheckpointRestoreError,
                                            PreemptedError)

        token = None
        try:
            context = dict(self._context_factory())
            context["interrupt_event"] = self._interrupt
            context["prompt_id"] = job.prompt_id
            if self.preemption is not None:
                token = self.preemption.begin(job)
                if token is not None:
                    context["preemption"] = token
            executor = GraphExecutor(context)
            # the execution span adopts the orchestration trace id and
            # parents onto the master's dispatch span (X-CDT-Trace) —
            # this is the worker-side half of a stitched job trace
            with telemetry.span("prompt.execute",
                                trace_id=job.trace_id,
                                parent_id=job.parent_span_id,
                                prompt_id=job.prompt_id):
                # run_in_executor does NOT propagate contextvars, so
                # spans opened during graph execution (pipeline_call
                # with its attn_kernels label, node-level spans)
                # would start orphan traces; copying the context in
                # parents them under this execution span
                ctx = contextvars.copy_context()
                outputs = await loop.run_in_executor(
                    self._pool, ctx.run, executor.execute, job.prompt
                )
            self.history[job.prompt_id] = {
                "status": "success",
                "duration": time.monotonic() - started,
                "outputs": {
                    nid: out for nid, out in outputs.items()
                    if _is_terminal(job.prompt, nid)
                },
            }
            if job.preempt_count:
                # resumed-and-finished: the record says so (operators
                # correlate p99 outliers with preemption history)
                self.history[job.prompt_id]["preemptions"] = \
                    job.preempt_count
            if self.preemption is not None:
                if (job.checkpoint_id and token is not None
                        and token.resume is not None
                        and not token.resume_consumed):
                    # the graph never fed the checkpoint to a sampler
                    # (img2img / ControlNet path): the run is a success
                    # but it was NOT a resume — say so loudly instead
                    # of counting a phantom resume
                    log(f"prompt {job.prompt_id} IGNORED its resume "
                        f"checkpoint {job.checkpoint_id} (graph has no "
                        "preemptible sampler) — ran from scratch")
                    self.history[job.prompt_id]["resume_ignored"] = True
                    self.preemption.discard(job)
                else:
                    self.preemption.resolve_success(job)
            trace_info(job.trace_id,
                       f"prompt {job.prompt_id} done in "
                       f"{self.history[job.prompt_id]['duration']:.2f}s")
            return "success"
        except PreemptedError as e:
            # intentional departure at a segment boundary: park the
            # checkpoint, requeue at the ORIGINAL queue position (seq is
            # kept), and record a non-terminal marker — clients polling
            # history keep waiting, exactly like a still-queued job. No
            # poison count, no breaker evidence, nothing lost.
            cid = self.preemption.park(job, e.checkpoint, e.reason)
            self.history[job.prompt_id] = {
                "status": "preempted",
                "preempted_at_step": e.checkpoint.step,
                "total_steps": e.checkpoint.total_steps,
                "checkpoint_id": cid,
                "reason": e.reason,
                "duration": time.monotonic() - started,
            }
            # fresh wait clock: cdt_queue_wait_seconds on the re-dispatch
            # must measure the RE-queue wait, not fold in the segments
            # already executed since the original enqueue
            job.enqueued_at = time.monotonic()
            # clear executing_job BEFORE the requeue: _put's reevaluate
            # would otherwise see the just-parked job as still running
            # and register a spurious second preempt request against it
            self.executing_job = None
            self._put(job)
            return "preempted"
        except CheckpointRestoreError as e:
            # bounded resume retries: a checkpoint that repeatedly fails
            # restore dead-letters (forensics kept) and the job restarts
            # from scratch — it must never loop (docs/preemption.md)
            verdict = self.preemption.restore_failed(job, str(e))
            log(f"prompt {job.prompt_id} checkpoint restore failed "
                f"({e}) -> {verdict}")
            self.history[job.prompt_id] = {
                "status": "resume_retry" if verdict == "retry"
                else "resume_scratch",
                "error": str(e),
                "duration": time.monotonic() - started,
            }
            job.enqueued_at = time.monotonic()
            self.executing_job = None
            self._put(job)
            return "resume_failed"
        except InterruptedError:
            self.history[job.prompt_id] = {
                "status": "interrupted",
                "duration": time.monotonic() - started,
            }
            log(f"prompt {job.prompt_id} interrupted")
            self._discard_parked(job)
            return "interrupted"
        except Exception as e:  # noqa: BLE001 — job isolation barrier
            self.history[job.prompt_id] = {
                "status": "error", "error": str(e),
                "duration": time.monotonic() - started,
            }
            log(f"prompt {job.prompt_id} failed: {e}")
            self._discard_parked(job)
            return "error"

    async def _run_group(self, loop, job: PromptJob,
                         started: float) -> list[str]:
        """Execute a front-door batch job: expire stale members, run the
        rest through the microbatch group executor, record per-member
        history. A group never loses a member silently — every member id
        ends with a terminal history entry."""
        from .frontdoor.microbatch import execute_group

        live: list[PromptJob] = []
        statuses: list[str] = []
        for m in job.group:
            if m.expired(started):
                self.history[m.prompt_id] = {
                    "status": "expired", "duration": 0.0,
                    "error": "deadline_ms elapsed before execution",
                }
                statuses.append("expired")
            else:
                live.append(m)
        if not live:
            return statuses

        if self.stages is not None and self.stages.eligible(job):
            staged = await self._run_group_staged(loop, job, live, started)
            if staged is not None:
                return statuses + staged

        try:
            # context build INSIDE the barrier: a transient factory error
            # (mesh/registry build) must error the members, not kill the
            # consumer task and strand every future job (_run has no
            # except of its own)
            context = dict(self._context_factory())
            context["interrupt_event"] = self._interrupt
            with telemetry.span("prompt.execute_batch",
                                trace_id=job.trace_id,
                                prompt_id=job.prompt_id,
                                batch=len(live)):
                ctx = contextvars.copy_context()
                results = await loop.run_in_executor(
                    self._pool, ctx.run, execute_group,
                    live, job.sampler_node_ids, context)
        except Exception as e:  # noqa: BLE001 — group isolation barrier
            # a failure this far out (not member-isolated by the group
            # executor) marks every unfinished member errored — never lost
            log(f"batch {job.prompt_id} failed: {e}")
            results = {m.prompt_id: {"status": "error", "error": str(e)}
                       for m in live}
        duration = time.monotonic() - started
        for m in live:
            entry = results.get(m.prompt_id,
                                {"status": "interrupted"})
            status = entry.get("status", "error")
            record = {"status": status,
                      "duration": duration,
                      "batch_size": entry.get("batch_size")}
            if entry.get("cache"):
                # served from the completed-result tier (cluster/cache)
                record["cache"] = entry["cache"]
            if entry.get("error"):
                record["error"] = entry["error"]
            if status == "success":
                record["outputs"] = {
                    nid: out
                    for nid, out in (entry.get("outputs") or {}).items()
                    if _is_terminal(m.prompt, nid)
                }
            self.history[m.prompt_id] = record
            statuses.append(status)
        trace_info(job.trace_id,
                   f"batch {job.prompt_id} ({len(live)} member(s)) done "
                   f"in {duration:.2f}s")
        return statuses

    async def _run_group_staged(self, loop, job: PromptJob,
                                live: "list[PromptJob]",
                                started: float) -> "list[str] | None":
        """Route a batch job through the stage pools (cluster/stages,
        docs/stages.md): encode pool → denoise pool → decode pool. The
        consumer awaits ONLY the denoise stage — the queue slot frees
        the moment the mesh is, so the next job's denoise overlaps this
        job's decode. Per-member terminal history lands from the decode
        pool via ``_record_staged_member`` (same record shape, same
        telemetry, same job-done callbacks as the fused path). Returns
        non-terminal ``"staged"`` markers (the finally-block counts only
        TERMINAL statuses; the staged completion path owns those), or
        None if submission itself failed — the fused path then runs."""
        try:
            context = dict(self._context_factory())
            context["interrupt_event"] = self._interrupt
            denoise_done = loop.create_future()
            by_id = {m.prompt_id: m for m in live}

            def record(member, entry, last) -> None:
                self._record_staged_member(job, member, entry, last,
                                           started)

            self.stages.submit_group(
                job, live,
                {pid: job.sampler_node_ids[pid] for pid in by_id},
                context, loop, denoise_done, record)
        except Exception as e:  # noqa: BLE001 — submission barrier: the
            # fused path still exists and must serve the group instead
            log(f"stages: submit of batch {job.prompt_id} failed "
                f"({e!r}); falling back to fused execution")
            return None
        with telemetry.span("prompt.execute_batch_staged",
                            trace_id=job.trace_id,
                            prompt_id=job.prompt_id, batch=len(live)):
            await denoise_done
        trace_info(job.trace_id,
                   f"batch {job.prompt_id} ({len(live)} member(s)) "
                   f"denoise done in {time.monotonic() - started:.2f}s "
                   "(decode in flight)")
        return ["staged"] * len(live)

    def _record_staged_member(self, job: PromptJob, member: PromptJob,
                              entry: dict, last: bool,
                              started: float) -> None:
        """Terminal history for one staged member (runs on the event
        loop, marshaled from a stage worker). Mirrors the fused
        ``_run_group`` record shape exactly — pollers and the coalescer
        cannot tell the paths apart."""
        status = entry.get("status", "error")
        record = {"status": status,
                  "duration": time.monotonic() - started,
                  "batch_size": entry.get("batch_size")}
        if entry.get("decode_batch"):
            record["decode_batch"] = entry["decode_batch"]
        if entry.get("cache"):
            record["cache"] = entry["cache"]
        if entry.get("error"):
            record["error"] = entry["error"]
        if status == "success":
            record["outputs"] = {
                nid: out
                for nid, out in (entry.get("outputs") or {}).items()
                if _is_terminal(member.prompt, nid)
            }
        self.history[member.prompt_id] = record
        if telemetry.enabled():
            if status in TERMINAL_STATUSES:
                _tm.PROMPTS_TOTAL.labels(status=status).inc()
            if last:
                # end-to-end batch duration (decode included) — the
                # fused path observes the same quantity once per group
                _tm.PROMPT_SECONDS.observe(record["duration"])
        for cb in self._job_done_callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001 — observer isolation
                pass


# one terminal-status vocabulary for every history observer (pollers,
# the sweep, the coalescer via its NON_TERMINAL mirror)
TERMINAL_STATUSES = frozenset({"success", "error", "interrupted",
                               "expired"})


def _priority_rank(priority: str) -> int:
    try:
        return constants.PRIORITY_CLASSES.index(priority)
    except ValueError:
        return len(constants.PRIORITY_CLASSES)


def _dequeue_key(job: PromptJob) -> tuple:
    """Dequeue order: priority class first (group jobs at their best
    member's class), parked resumes before fresh work within a class
    (the handback front-of-queue idiom), then arrival order."""
    rank = min(_priority_rank(m.priority) for m in (job.group or [job]))
    return (rank, 0 if job.checkpoint_id else 1, job.seq)


def _job_members(job: PromptJob) -> "list[tuple[str, int]]":
    counts: dict[str, int] = {}
    for m in (job.group or [job]):
        counts[m.priority] = counts.get(m.priority, 0) + 1
    return list(counts.items())


def _is_terminal(prompt: dict, nid: str) -> bool:
    from ..graph.node import NODE_REGISTRY

    cls = NODE_REGISTRY.get(prompt.get(nid, {}).get("class_type", ""))
    if cls is None:
        return False
    consumed = {
        v[0] for node in prompt.values()
        for v in node.get("inputs", {}).values()
        if isinstance(v, (list, tuple)) and len(v) == 2
    }
    return cls.OUTPUT_NODE or nid not in consumed
