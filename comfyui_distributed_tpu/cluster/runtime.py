"""Host controller runtime: the local prompt queue + execution context.

The reference relies on ComfyUI's PromptServer queue + executor
(``utils/async_helpers.py:108-149`` pushes into ``prompt_queue``). This is
the standalone equivalent: an asyncio consumer that validates prompts,
executes them in a worker thread (JAX compute must not block the loop),
and exposes ``queue_remaining`` for health probes — the field the
reference's least-busy scheduler reads (``dispatch.py:225-268``).
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import secrets
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from .. import telemetry
from ..graph.executor import GraphExecutor, strip_meta, validate_prompt
from ..telemetry import metrics as _tm
from ..utils.exceptions import ValidationError
from ..utils.logging import log, trace_info


@dataclasses.dataclass
class PromptJob:
    prompt_id: str
    prompt: dict
    client_id: str = ""
    trace_id: str | None = None
    # master-side dispatch span id carried by X-CDT-Trace: the execution
    # span parents onto it so cross-host traces stitch (telemetry/spans)
    parent_span_id: str | None = None
    enqueued_at: float = dataclasses.field(default_factory=time.monotonic)
    future: Optional[asyncio.Future] = None


class PromptQueue:
    """FIFO prompt queue with a single execution worker.

    Execution is serialized per controller (one mesh, one program at a
    time — the TPU analogue of one ComfyUI executor per GPU process).
    """

    def __init__(self, context_factory: Callable[[], dict] | None = None):
        import threading

        self._queue: asyncio.Queue[PromptJob] = asyncio.Queue()
        self._context_factory = context_factory or (lambda: {})
        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="graph-exec")
        self._task: Optional[asyncio.Task] = None
        self._executing: Optional[str] = None
        self._interrupt = threading.Event()
        self.history: dict[str, dict] = {}

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self._pool.shutdown(wait=False, cancel_futures=True)

    # --- producer ----------------------------------------------------------

    def enqueue(self, prompt: dict, client_id: str = "",
                trace_id: str | None = None,
                parent_span_id: str | None = None) -> tuple[str, list]:
        """Validate + enqueue; returns (prompt_id, node_errors). Mirrors
        ``queue_prompt_payload``: validation errors reject the prompt
        before it reaches the queue (``utils/async_helpers.py:108-149``)."""
        prompt = strip_meta(prompt)
        errors = validate_prompt(prompt)
        if errors:
            return "", [e.as_dict() for e in errors]
        prompt_id = f"p_{int(time.time()*1000)}_{secrets.token_hex(3)}"
        job = PromptJob(prompt_id, prompt, client_id, trace_id,
                        parent_span_id=parent_span_id)
        self._queue.put_nowait(job)
        if telemetry.enabled():
            _tm.PROMPT_QUEUE_DEPTH.set(self.queue_remaining)
        self.start()
        return prompt_id, []

    @property
    def queue_remaining(self) -> int:
        return self._queue.qsize() + (1 if self._executing else 0)

    def interrupt(self) -> int:
        """Drop pending prompts and flag the running one (checked between
        nodes — parity with the reference's interrupt fan-out,
        ``web/workerUtils.js:73-95``). Returns number of dropped jobs."""
        dropped = 0
        while True:
            try:
                job = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            self.history[job.prompt_id] = {"status": "interrupted",
                                           "duration": 0.0}
            dropped += 1
        if self._executing:
            self._interrupt.set()
        return dropped

    @property
    def executing(self) -> Optional[str]:
        return self._executing

    # --- consumer ----------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            job = await self._queue.get()
            self._executing = job.prompt_id
            started = time.monotonic()
            self._interrupt.clear()
            status = "error"
            try:
                context = dict(self._context_factory())
                context["interrupt_event"] = self._interrupt
                context["prompt_id"] = job.prompt_id
                executor = GraphExecutor(context)
                # the execution span adopts the orchestration trace id and
                # parents onto the master's dispatch span (X-CDT-Trace) —
                # this is the worker-side half of a stitched job trace
                with telemetry.span("prompt.execute",
                                    trace_id=job.trace_id,
                                    parent_id=job.parent_span_id,
                                    prompt_id=job.prompt_id):
                    # run_in_executor does NOT propagate contextvars, so
                    # spans opened during graph execution (pipeline_call
                    # with its attn_kernels label, node-level spans)
                    # would start orphan traces; copying the context in
                    # parents them under this execution span
                    ctx = contextvars.copy_context()
                    outputs = await loop.run_in_executor(
                        self._pool, ctx.run, executor.execute, job.prompt
                    )
                status = "success"
                self.history[job.prompt_id] = {
                    "status": "success",
                    "duration": time.monotonic() - started,
                    "outputs": {
                        nid: out for nid, out in outputs.items()
                        if _is_terminal(job.prompt, nid)
                    },
                }
                trace_info(job.trace_id,
                           f"prompt {job.prompt_id} done in "
                           f"{self.history[job.prompt_id]['duration']:.2f}s")
            except InterruptedError:
                status = "interrupted"
                self.history[job.prompt_id] = {
                    "status": "interrupted",
                    "duration": time.monotonic() - started,
                }
                log(f"prompt {job.prompt_id} interrupted")
            except Exception as e:  # noqa: BLE001 — job isolation barrier
                self.history[job.prompt_id] = {
                    "status": "error", "error": str(e),
                    "duration": time.monotonic() - started,
                }
                log(f"prompt {job.prompt_id} failed: {e}")
            finally:
                self._executing = None
                if telemetry.enabled():
                    _tm.PROMPTS_TOTAL.labels(status=status).inc()
                    _tm.PROMPT_SECONDS.observe(time.monotonic() - started)
                    _tm.PROMPT_QUEUE_DEPTH.set(self.queue_remaining)


def _is_terminal(prompt: dict, nid: str) -> bool:
    from ..graph.node import NODE_REGISTRY

    cls = NODE_REGISTRY.get(prompt.get(nid, {}).get("class_type", ""))
    if cls is None:
        return False
    consumed = {
        v[0] for node in prompt.values()
        for v in node.get("inputs", {}).values()
        if isinstance(v, (list, tuple)) and len(v) == 2
    }
    return cls.OUTPUT_NODE or nid not in consumed
