"""Multi-model HBM residency planner: one fleet serves every workload.

The alternative — per-model worker pools — wastes chips whenever the
traffic mix shifts (the reference's answer: one ComfyUI process per GPU
per model). Instead, a single worker keeps several model bundles
(SDXL bf16, FLUX fp8, WAN dual-expert) under a per-chip HBM budget and
swaps deterministically:

- :class:`ResidencyPlanner` is the pure policy core: registered entries
  with (bytes, priority, last-use); eviction order is **lowest priority
  first, then least-recently-used**, pinned entries are untouchable.
  Pure → unit-testable on CPU with synthetic budgets, and the same
  decisions replay identically on every host.
- :class:`BundleResidency` binds the planner to a ``ModelRegistry``:
  acquiring a bundle measures its parameter bytes, evicts victims
  (dropping them from the registry cache and releasing any offload
  executors' device buffers via ``diffusion/offload.release_store``),
  and touches the LRU clock. Per-request LoRA hot-patching
  (:meth:`BundleResidency.request`) pins the base bundle for the
  request's duration and patches a copy-on-write clone
  (``models/lora.apply_lora`` shares every untouched leaf), so serving
  a LoRA'd request never evicts — or duplicates — the base model.

Accounting is host-side planning, not an HBM allocator: bytes are the
packed parameter sizes (same arithmetic as ``diffusion/offload.py``'s
placement planner). Activations/workspace stay the caller's headroom to
budget, exactly as with ``CDT_OFFLOAD_RESIDENT_GB``.

Knobs: ``CDT_HBM_BUDGET_GB`` (0/unset = unlimited, planner inactive).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Callable, Optional

from ..lint.lockorder import tracked_lock
from ..utils.constants import HBM_BUDGET_GB
from ..utils.exceptions import DistributedError
from ..utils.logging import log


class ResidencyError(DistributedError):
    """A bundle cannot be made resident under the configured budget."""


def hbm_budget_bytes() -> int:
    """0 = unlimited (planner off)."""
    return int(HBM_BUDGET_GB.get() * (1 << 30))


@dataclasses.dataclass
class _Entry:
    name: str
    nbytes: int
    priority: int = 0
    last_use: int = 0
    pins: int = 0


class ResidencyPlanner:
    """Deterministic LRU/priority residency policy over named entries.

    ``on_evict(name)`` performs the actual release (drop registry cache,
    free device buffers); the planner only decides. Thread-safe — the
    graph-executor thread and warmup/executor threads share it.
    """

    def __init__(self, budget_bytes: int,
                 on_evict: Optional[Callable[[str], None]] = None):
        self.budget = int(budget_bytes)
        self.on_evict = on_evict
        self._entries: dict[str, _Entry] = {}
        self._clock = 0
        self._lock = tracked_lock("residency.planner", reentrant=True)

    # --- introspection ------------------------------------------------------

    def resident(self) -> list[str]:
        """Names in eviction order (first = next victim)."""
        with self._lock:
            return [e.name for e in self._victim_order()]

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def is_resident(self, name: str) -> bool:
        return name in self._entries

    # --- policy -------------------------------------------------------------

    def _victim_order(self) -> list[_Entry]:
        return sorted(self._entries.values(),
                      key=lambda e: (e.priority, e.last_use))

    def plan(self, name: str, nbytes: int) -> list[str]:
        """Victims that WOULD be evicted to fit ``name`` — without
        applying anything (capacity planning / dry runs). Raises
        :class:`ResidencyError` when no eviction sequence fits."""
        with self._lock:
            return self._plan_locked(name, int(nbytes))

    def _plan_locked(self, name: str, nbytes: int) -> list[str]:
        have = self._entries.get(name)
        used = sum(e.nbytes for e in self._entries.values()) \
            - (have.nbytes if have else 0)
        if self.budget <= 0 or used + nbytes <= self.budget:
            return []
        victims = []
        for e in self._victim_order():
            if e.name == name or e.pins > 0:
                continue
            victims.append(e.name)
            used -= e.nbytes
            if used + nbytes <= self.budget:
                return victims
        if nbytes > self.budget:
            raise ResidencyError(
                f"model {name!r} needs {nbytes / 1e9:.2f} GB but the HBM "
                f"budget is {self.budget / 1e9:.2f} GB "
                "(CDT_HBM_BUDGET_GB) — it can never be resident")
        pinned = [e.name for e in self._entries.values() if e.pins > 0]
        raise ResidencyError(
            f"cannot fit {name!r} ({nbytes / 1e9:.2f} GB): "
            f"{used / 1e9:.2f} GB held by pinned bundles {pinned} under a "
            f"{self.budget / 1e9:.2f} GB budget")

    def acquire(self, name: str, nbytes: int, priority: int = 0
                ) -> list[str]:
        """Make ``name`` resident: evict the planned victims (calling
        ``on_evict`` for each), then register/touch the entry. Returns
        the evicted names, in order."""
        with self._lock:
            victims = self._plan_locked(name, int(nbytes))
            for v in victims:
                self._evict_locked(v, reason="budget")
            e = self._entries.get(name)
            if e is None:
                e = self._entries[name] = _Entry(name, int(nbytes),
                                                 int(priority))
            else:
                e.nbytes = int(nbytes)
                e.priority = int(priority)
            self._clock += 1
            e.last_use = self._clock
            self._export_gauges()
            return victims

    def touch(self, name: str) -> None:
        with self._lock:
            e = self._entries.get(name)
            if e is not None:
                self._clock += 1
                e.last_use = self._clock

    def release(self, name: str) -> bool:
        """Manual eviction (e.g. ``/distributed/clear_memory``)."""
        with self._lock:
            if name not in self._entries:
                return False
            if self._entries[name].pins > 0:
                raise ResidencyError(
                    f"cannot release {name!r}: pinned by an in-flight "
                    "request")
            self._evict_locked(name, reason="manual")
            self._export_gauges()
            return True

    def _evict_locked(self, name: str, reason: str) -> None:
        self._entries.pop(name, None)
        log(f"residency: evicting {name!r} ({reason})")
        try:
            from ..telemetry import enabled as _tm_enabled
            from ..telemetry import metrics as _tm

            if _tm_enabled():
                _tm.RESIDENCY_EVICTIONS.labels(reason=reason).inc()
        except Exception:  # noqa: BLE001
            pass
        if self.on_evict is not None:
            self.on_evict(name)

    # --- pinning ------------------------------------------------------------

    def pin(self, name: str) -> None:
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                raise ResidencyError(f"cannot pin non-resident {name!r}")
            e.pins += 1

    def unpin(self, name: str) -> None:
        with self._lock:
            e = self._entries.get(name)
            if e is not None and e.pins > 0:
                e.pins -= 1

    @contextlib.contextmanager
    def pinned(self, name: str):
        self.pin(name)
        try:
            yield
        finally:
            self.unpin(name)

    def _export_gauges(self) -> None:
        try:
            from ..telemetry import enabled as _tm_enabled
            from ..telemetry import metrics as _tm

            if _tm_enabled():
                _tm.RESIDENT_MODELS.set(len(self._entries))
                _tm.RESIDENT_BYTES.set(
                    sum(e.nbytes for e in self._entries.values()))
        except Exception:  # noqa: BLE001
            pass


def tp_shard_bytes(params, rules, tp: int) -> int:
    """PER-CHIP bytes of ``params`` under Megatron tp sharding: leaves
    the placement rules shard contribute ``nbytes/tp``, everything else
    (norms, embeddings, modulation — and any leaf whose dims don't
    divide) its full size. This is the tp-shard-granularity arithmetic
    the mesh serving tier plans HBM with: a 12B model at tp=4 costs each
    chip a quarter of its matmul weights plus the replicated glue, not
    the headline parameter count."""
    import jax

    from ..parallel.tensor import _path_str, spec_for_param

    total = 0

    def visit(path, leaf):
        nonlocal total
        nbytes = leaf.size * leaf.dtype.itemsize
        spec = spec_for_param(_path_str(path), leaf.shape, rules,
                              "tp", tp)
        total += nbytes // tp if any(d is not None for d in spec) \
            else nbytes

    jax.tree_util.tree_map_with_path(visit, params)
    return total


def _tp_rules_for(bundle):
    """The Megatron placement rule set this bundle's core model shards
    with — the same tables ``generate_tp_fn`` places weights by, so
    planning and placement can't disagree about what shards."""
    from ..parallel.tensor import (DIT_TP_RULES, UNET_TP_RULES,
                                   WAN_TP_RULES)

    pipe = bundle.pipeline
    if getattr(pipe, "unet", None) is not None:
        return UNET_TP_RULES
    dit = getattr(pipe, "dit", None)
    if dit is not None and type(dit).__name__.startswith("Wan"):
        return WAN_TP_RULES
    return DIT_TP_RULES


def bundle_bytes(bundle, tp_shards: int = 1) -> int:
    """Packed parameter bytes of a loaded ``ModelBundle`` — core params
    (+ the low-noise expert for dual-expert WAN), both VAE halves, and
    the active text stack. Same per-leaf arithmetic as the offload
    placement planner.

    ``tp_shards > 1`` plans at tp-shard granularity: the core model's
    rule-matched weights divide over the tp axis (``tp_shard_bytes``)
    while VAE/text — which serve replicated on every chip — count
    full-size."""
    from ..diffusion.offload import tree_bytes

    core = bundle._core_params()
    low = getattr(bundle.pipeline, "dit_params_low", None)
    if tp_shards > 1:
        rules = _tp_rules_for(bundle)
        total = tp_shard_bytes(core, rules, tp_shards)
        if low is not None:
            total += tp_shard_bytes(low, rules, tp_shards)
    else:
        total = tree_bytes(core)
        if low is not None:
            total += tree_bytes(low)
    total += tree_bytes(bundle.pipeline.vae.enc_params)
    total += tree_bytes(bundle.pipeline.vae.dec_params)
    params = getattr(bundle.text_encoder, "params", None)
    if params is not None:
        total += tree_bytes(params)
    return total


class BundleResidency:
    """Planner ↔ registry binding (constructed by ``ModelRegistry`` when
    ``CDT_HBM_BUDGET_GB`` is set)."""

    def __init__(self, registry, budget_bytes: int,
                 estimator: Callable = bundle_bytes,
                 tp_shards: Optional[int] = None):
        """``tp_shards``: plan HBM at tp-shard granularity (per-chip
        slice of rule-matched weights + replicated glue). ``None``
        resolves per-acquire via ``tp_shards_fn`` — the controller sets
        it to the SERVING MESH's tp degree, the same axis that routes
        weight-sharded programs (``generate_microbatch``), so planned
        bytes can never diverge from held bytes. With neither set,
        planning stays whole-model (replicated serving)."""
        self._registry = registry
        self._estimator = estimator
        self._tp_shards = tp_shards
        # set post-construction by the controller (the mesh is built
        # lazily there); must mirror the mesh that shards weights
        self.tp_shards_fn: Optional[Callable[[], int]] = None
        self.planner = ResidencyPlanner(budget_bytes,
                                        on_evict=self._evict_bundle)

    def _evict_bundle(self, name: str) -> None:
        bundle = self._registry._cache.pop(name, None)
        if bundle is not None:
            bundle.release_device()

    def _resolve_tp(self) -> int:
        if self._tp_shards is not None:
            return max(1, int(self._tp_shards))
        from ..parallel.serving import mesh_tier_enabled

        if not mesh_tier_enabled() or self.tp_shards_fn is None:
            return 1
        try:
            return max(1, int(self.tp_shards_fn()))
        except Exception:  # noqa: BLE001 — planning must not sink a build
            return 1

    def measure(self, bundle) -> int:
        """Planner-relevant bytes for one bundle (tp-shard granularity
        when the mesh tier shards weights; custom estimators without a
        ``tp_shards`` kwarg keep their whole-model arithmetic)."""
        tp = self._resolve_tp()
        if tp > 1:
            try:
                return self._estimator(bundle, tp_shards=tp)
            except TypeError:
                pass
        return self._estimator(bundle)

    def note_use(self, name: str, bundle, priority: int = 0) -> list[str]:
        """Account a registry hit: first sight measures + acquires
        (evicting victims), repeats just touch the LRU clock.

        Sizing happens after the build (params exist to be measured);
        a build that transiently overlaps a victim is the documented
        cost of not materializing abstract trees twice.
        """
        if self.planner.is_resident(name):
            self.planner.touch(name)
            return []
        return self.planner.acquire(name, self.measure(bundle),
                                    priority=priority)

    @contextlib.contextmanager
    def request(self, name: str, lora_sd=None, **lora_kw):
        """Serve one request against ``name``, optionally hot-patched
        with a LoRA. The base bundle is pinned for the duration — a
        concurrent acquire of another model can evict any *other*
        bundle, never the one mid-request — and the LoRA patch is an
        ephemeral copy-on-write clone (shared leaves, fresh compile
        caches) that is never registered with the planner."""
        # get→pin is not atomic against a concurrent acquire evicting
        # this bundle in the gap — retry until a pin lands on a live
        # registration (bounded: eviction requires another thread
        # actively thrashing the budget)
        for _ in range(8):
            bundle = self._registry.get(name)
            try:
                self.planner.pin(name)
                break
            except ResidencyError:
                continue
        else:
            raise ResidencyError(
                f"could not pin {name!r}: concurrent acquires keep "
                "evicting it (budget thrash — raise CDT_HBM_BUDGET_GB)")
        try:
            if lora_sd is None:
                yield bundle
            else:
                from ..models.lora import apply_lora

                patched, _ = apply_lora(bundle, lora_sd, **lora_kw)
                yield patched
        finally:
            self.planner.unpin(name)


@contextlib.contextmanager
def pinned_bundle(bundle):
    """Pin a registry bundle for the duration of a generate call (no-op
    when no residency planner is attached). The sampler nodes wrap
    execution in this so a concurrent acquire — the warmup thread, a
    second model's request — can never ``release_device()`` the bundle
    mid-program."""
    res = getattr(bundle, "_residency", None)
    name = getattr(getattr(bundle, "preset", None), "name", None)
    if res is None or name is None:
        yield
        return
    try:
        res.planner.pin(name)
    except ResidencyError:
        # already evicted between fetch and pin: the caller's reference
        # keeps the host params alive — execution proceeds (re-uploading
        # as needed), it just lost the residency fast path
        yield
        return
    try:
        yield
    finally:
        res.planner.unpin(name)
