"""Unified resilience layer: retry policy + per-worker circuit breakers.

Before this module, every cluster call site hand-rolled its own
timeout/retry loop (``dispatch.py``, ``tile_farm.py:361-371,459``,
``collector_bridge.py``, ``media_sync.py``) with no shared policy, no
bound on poison-tile requeues, and no way to quarantine a flapping host.
Pod-scale operation experience (Kumar et al., "Exploring the Limits of
Concurrency in ML Training on Google TPUs") treats transient host loss
and stragglers as the steady state — so failure handling is centralized
here and *parameterized*, not re-implemented per call site:

- :class:`RetryPolicy` — exponential backoff with **full jitter**
  (delay ~ U(0, min(cap, base·2^attempt)), the AWS-recommended variant:
  desynchronizes a thundering herd of workers re-polling one master),
  capped by attempts and/or a wall-clock budget, and **idempotency-
  aware**: an exception carrying ``retry_safe=False`` is never retried
  (a WS-acked dispatch may already sit in the worker's queue — re-sending
  double-runs the job, ``dispatch.py``).
- :class:`CircuitBreaker` / :class:`BreakerRegistry` — per-worker
  closed→open→half-open state driven by probe/dispatch/submit outcomes.
  An open breaker short-circuits worker selection (``dispatch.py``)
  so a flapping host is quarantined instead of re-probed on every job;
  after ``recovery_s`` one half-open trial decides re-admission.

Breaker state is exported as the ``cdt_worker_breaker_state`` gauge
(0=closed, 1=half-open, 2=open) and shown on the dashboard worker cards.
Every failure path here is reproducible under test via the deterministic
fault harness in :mod:`.faults` (``CDT_FAULTS``, docs/resilience.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import random
import threading
import time
from typing import Any, Awaitable, Callable, Iterable, Optional

from ..telemetry import enabled as _tm_enabled, metrics as _tm
from ..lint.lockorder import tracked_lock
from ..utils import constants
from ..utils.logging import debug_log, log

# Module-level RNG for jitter; tests pass their own seeded Random for
# deterministic backoff schedules.
_rng = random.Random()


def is_retryable(exc: BaseException) -> bool:
    """Default retry predicate.

    The explicit ``retry_safe`` attribute always wins (idempotency
    marker set at raise sites); otherwise the transient transport trio —
    aiohttp client errors, timeouts, OS-level socket errors — retries.
    """
    flag = getattr(exc, "retry_safe", None)
    if flag is not None:
        return bool(flag)
    import aiohttp

    return isinstance(exc, (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + full jitter, bounded by attempts and/or a
    wall-clock budget.

    ``max_attempts=None`` means "until the budget expires" (the
    404-tolerant work-request loop); ``budget_s=None`` means "attempts
    only" (the classic send loop). At least one bound must be set.
    """

    max_attempts: Optional[int] = 5
    base: float = 0.5               # first backoff upper bound (seconds)
    cap: float = 5.0                # per-sleep upper bound (seconds)
    budget_s: Optional[float] = None
    jitter: bool = True

    def __post_init__(self):
        if self.max_attempts is None and self.budget_s is None:
            raise ValueError("RetryPolicy needs max_attempts or budget_s")

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        """Backoff before retry ``attempt+1`` (attempt is 0-based)."""
        upper = min(self.cap, self.base * (2 ** attempt))
        if not self.jitter:
            return upper
        return (rng or _rng).uniform(0.0, upper)

    def _attempts(self) -> Iterable[int]:
        if self.max_attempts is None:
            return itertools.count()
        return range(self.max_attempts)

    async def run(
        self,
        fn: Callable[[], Awaitable[Any]],
        *,
        op: str = "call",
        retryable: Callable[[BaseException], bool] = is_retryable,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], Awaitable[None]] = asyncio.sleep,
    ) -> Any:
        """Run ``fn`` until it returns, raises a non-retryable error, or
        the policy's bounds are exhausted (the last exception re-raises —
        call sites wrap it in their domain error if they want to).
        """
        start = time.monotonic()
        last: Optional[BaseException] = None
        for attempt in self._attempts():
            try:
                return await fn()
            except asyncio.CancelledError:
                raise                      # cancellation is never retried
            except BaseException as e:     # noqa: BLE001 — predicate decides
                if not retryable(e):
                    raise
                last = e
            d = self.delay(attempt, rng)
            elapsed = time.monotonic() - start
            if self.budget_s is not None and elapsed + d >= self.budget_s:
                break
            if self.max_attempts is not None and \
                    attempt >= self.max_attempts - 1:
                break
            if _tm_enabled():
                _tm.RETRY_ATTEMPTS.labels(op=op).inc()
            debug_log(f"retry[{op}] attempt {attempt + 1} failed "
                      f"({last}); backing off {d:.2f}s")
            await sleep(d)
        assert last is not None
        raise last


def send_policy() -> RetryPolicy:
    """The classic bounded send loop (reference
    ``worker_comms.py:88-104``): SEND_MAX_RETRIES attempts."""
    return RetryPolicy(max_attempts=constants.SEND_MAX_RETRIES,
                       base=constants.SEND_BACKOFF_BASE,
                       cap=constants.RETRY_CAP_S)


def work_request_policy() -> RetryPolicy:
    """The 404-tolerant work-request loop: unbounded attempts inside a
    WORK_REQUEST_BUDGET wall-clock window, jittered so a worker fleet
    hammering a restarting master spreads out instead of busy-spinning."""
    return RetryPolicy(max_attempts=None,
                       base=constants.SEND_BACKOFF_BASE,
                       cap=constants.RETRY_CAP_S,
                       budget_s=constants.WORK_REQUEST_BUDGET)


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-worker closed→open→half-open breaker.

    - ``closed``: all calls pass; ``failure_threshold`` consecutive
      failures trip it open.
    - ``open``: calls are refused (``allow()`` False) until
      ``recovery_s`` elapses, then ONE half-open trial is admitted.
    - ``half_open``: the trial's outcome decides — success closes,
      failure re-opens (and re-arms the recovery clock).

    ``trip()`` forces open immediately: a heartbeat-timeout eviction is
    a high-confidence failure that shouldn't wait for a threshold.
    Thread-safe (asyncio handlers + the executor thread both record).
    """

    def __init__(self, failure_threshold: Optional[int] = None,
                 recovery_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = (constants.BREAKER_FAIL_THRESHOLD
                                  if failure_threshold is None
                                  else failure_threshold)
        self.recovery_s = (constants.BREAKER_RECOVERY_S
                           if recovery_s is None else recovery_s)
        self._clock = clock
        self._lock = tracked_lock("resilience.breaker")
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._trial_inflight = False

    # -- observation (no state consumption) ---------------------------------

    @property
    def state(self) -> str:
        """Current state; reports ``half_open`` once the recovery window
        has elapsed (without consuming the trial slot)."""
        with self._lock:
            if self._state == OPEN and \
                    self._clock() - self._opened_at >= self.recovery_s:
                return HALF_OPEN
            return self._state

    @property
    def failures(self) -> int:
        return self._failures

    # -- gating --------------------------------------------------------------

    def allow(self) -> bool:
        """May a call proceed? Consumes the single half-open trial slot."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.recovery_s:
                    return False
                self._state = HALF_OPEN
                self._trial_inflight = True
                return True
            # half-open: one probe in flight at a time
            if self._trial_inflight:
                return False
            self._trial_inflight = True
            return True

    # -- outcome recording ---------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._trial_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._reopen_locked()
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._reopen_locked()

    def trip(self) -> None:
        """Force open (eviction-grade evidence)."""
        with self._lock:
            self._reopen_locked()

    def _reopen_locked(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._trial_inflight = False
        self._failures = max(self._failures, self.failure_threshold)


class BreakerRegistry:
    """worker_id → breaker, with telemetry export on every transition.

    One process-global instance (``BREAKERS``) feeds worker selection in
    ``dispatch.py`` and the eviction path in ``job_timeout.py``; tests
    reset it between cases (conftest fixture).
    """

    def __init__(self, **breaker_kw):
        self._lock = tracked_lock("resilience.breakers")
        self._breakers: dict[str, CircuitBreaker] = {}
        self._breaker_kw = breaker_kw

    def get(self, worker_id: str) -> CircuitBreaker:
        wid = str(worker_id)
        with self._lock:
            b = self._breakers.get(wid)
            if b is None:
                b = self._breakers[wid] = CircuitBreaker(**self._breaker_kw)
                self._export(wid, b)
            return b

    def _export(self, worker_id: str, breaker: CircuitBreaker) -> None:
        if _tm_enabled():
            _tm.BREAKER_STATE.labels(worker=worker_id).set(
                _STATE_VALUE[breaker.state])

    def allow(self, worker_id: str) -> bool:
        b = self.get(worker_id)
        ok = b.allow()
        self._export(worker_id, b)
        return ok

    def record(self, worker_id: str, ok: bool) -> None:
        b = self.get(worker_id)
        before = b.state
        if ok:
            b.record_success()
        else:
            b.record_failure()
        self._transitioned(worker_id, b, before)

    def trip(self, worker_id: str) -> None:
        b = self.get(worker_id)
        before = b.state
        b.trip()
        self._transitioned(worker_id, b, before)

    def _transitioned(self, worker_id: str, b: CircuitBreaker,
                      before: str) -> None:
        after = b.state
        self._export(worker_id, b)
        if after != before:
            log(f"breaker[{worker_id}] {before} -> {after}")
            if _tm_enabled():
                _tm.BREAKER_TRANSITIONS.labels(to=after).inc()

    def state(self, worker_id: str) -> str:
        return self.get(worker_id).state

    def states(self) -> dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {wid: b.state for wid, b in items}

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()


BREAKERS = BreakerRegistry()
