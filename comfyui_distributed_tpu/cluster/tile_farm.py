"""Cross-host tile farm: pull-queue scatter of tile work between hosts.

On-pod, tile parallelism is one SPMD program (``tiles/engine.py``). Across
hosts — where chips don't share ICI — this module reproduces the
reference's distributed-upscale machinery over the HTTP control plane:

- master (``master_run``): seeds the pull queue
  (``upscale/modes/static.py:371-395``), processes tasks itself while
  draining worker results (``:406-448``), runs the heartbeat-timeout
  requeue monitor every HEARTBEAT_INTERVAL (``:337-343``,
  ``upscale/job_timeout.py:17-150``), and reprocesses every leftover
  locally so a job always completes (``:469-513``);
- worker (``worker_run``): polls job-ready (``:33-47``), pulls task
  ranges (``worker_comms.py:124-188``), runs them through the local SPMD
  chunk program, heartbeats per task, and flushes results in size-capped
  multipart batches with retries (``worker_comms.py:16-108``).

Transport: CDTF binary frames (float32, crc-checked — zero precision loss)
instead of the reference's PNG parts; the route also accepts PNG for
compatibility. Tile task ranges are defined on *global* tile indices, and
per-tile noise keys fold the global index, so any host can process any
range and requeue is numerically invisible.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Awaitable, Callable, Optional, Sequence

import aiohttp
import numpy as np

from ..telemetry import span as _tm_span
from ..utils import constants
from ..utils.async_helpers import run_in_loop
from ..utils.exceptions import TileCollectionError, WorkerError
from ..utils.logging import debug_log, log
from ..utils.network import get_client_session, normalize_host_url
from .job_store import JobStore
from .job_timeout import check_and_requeue_timed_out_workers
from .resilience import send_policy, work_request_policy

ProcessFn = Callable[[int, int], np.ndarray]      # (start, end) -> [n,...]
ProbeFn = Callable[[str], Awaitable[Optional[dict]]]


class TileJournal:
    """Disk journal of completed tile tasks (crash resume for long jobs —
    SURVEY §5.4: the reference restarts minutes-long jobs from scratch;
    multi-hour video upscales warrant result journaling).

    One CDTF frame file per completed task, written atomically
    (tmp + rename, same discipline as the config saver); a restarted
    master preloads them and only the remainder is recomputed.

    The key must be STABLE ACROSS RESTARTS (a content hash of the job's
    inputs, not the per-execution job id — a crashed workflow re-submits
    under a fresh exec id). Stale sibling dirs are pruned by TTL on open
    so crashed-and-abandoned jobs can't leak disk forever.
    """

    TTL_S = 7 * 24 * 3600.0

    def __init__(self, root, key: str):
        import time
        from pathlib import Path

        from ..utils.names import sanitize_name

        self.dir = Path(root) / sanitize_name(key, max_len=120,
                                              fallback="job")
        self.dir.mkdir(parents=True, exist_ok=True)
        self.disabled = False
        # TTL sweep of abandoned sibling journals
        horizon = time.time() - self.TTL_S
        for sib in Path(root).iterdir():
            try:
                if sib.is_dir() and sib != self.dir and sib.stat().st_mtime < horizon:
                    import shutil

                    shutil.rmtree(sib, ignore_errors=True)
            except OSError:
                pass

    def write(self, task_id: int, arr: np.ndarray) -> None:
        """Best-effort: journaling must never kill the job it protects —
        on any write failure the journal disables itself and the run
        continues un-journaled."""
        if self.disabled:
            return
        out = self.dir / f"task_{task_id}.cdtf"
        if out.exists():
            return   # master-processed tasks also flow through the results
                     # queue; don't pack+write the same frame twice
        from .. import native

        try:
            tmp = self.dir / f".task_{task_id}.tmp"
            tmp.write_bytes(
                native.pack_frame(np.asarray(arr, np.float32), level=1))
            tmp.rename(out)
        except OSError as e:
            log(f"journal: write failed ({e}); disabling journal for this run")
            self.disabled = True

    def load(self) -> dict[int, np.ndarray]:
        from .. import native

        out: dict[int, np.ndarray] = {}
        for f in sorted(self.dir.glob("task_*.cdtf")):
            try:
                tid = int(f.stem.split("_", 1)[1])
                out[tid] = native.unpack_frame(f.read_bytes())
            except (ValueError, OSError) as e:
                log(f"journal: skipping corrupt entry {f.name} ({e})")
        return out

    def clear(self) -> None:
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)


class TileFarm:
    """Bound to the controller's store + event loop; graph nodes call the
    sync wrappers from the executor thread (same bridging discipline as
    ``CollectorBridge``)."""

    def __init__(self, store: JobStore, loop: asyncio.AbstractEventLoop):
        self.store = store
        self.loop = loop

    # --- sync wrappers (node-facing) ---------------------------------------

    def master_run(self, job_id: str, total: int, process_fn: ProcessFn,
                   chunk: int = 1, **kw) -> dict[int, np.ndarray]:
        return run_in_loop(
            self.master_run_async(job_id, total, process_fn, chunk, **kw),
            self.loop, timeout=None)

    def worker_run(self, job_id: str, worker_id: str, master_url: str,
                   process_fn: ProcessFn, **kw) -> int:
        return run_in_loop(
            self.worker_run_async(job_id, worker_id, master_url,
                                  process_fn, **kw),
            self.loop, timeout=None)

    # --- master role --------------------------------------------------------

    async def master_run_async(
        self, job_id: str, total: int, process_fn: ProcessFn, chunk: int = 1,
        **kw,
    ) -> dict[int, np.ndarray]:
        """Drive a tile job to completion; returns {task_id: array}.

        The loop interleaves what the reference splits into three phases
        (master work loop → collect-and-monitor → local fallback): the
        master pulls from the same queue as workers, so it naturally takes
        over everything requeued from dead workers, and the job completes
        whenever at least the master survives.

        The whole job runs under a ``tile_job.master`` span, so
        ``/distributed/trace/{job_id}`` shows where a multi-hour upscale
        spent its wall-clock.
        """
        with _tm_span("tile_job.master", job_id=job_id, tiles=total,
                      chunk=chunk):
            return await self._master_run_inner(job_id, total, process_fn,
                                                chunk, **kw)

    async def _master_run_inner(
        self, job_id: str, total: int, process_fn: ProcessFn, chunk: int = 1,
        heartbeat_interval: float | None = None,
        worker_timeout: float | None = None,
        probe_fn: ProbeFn | None = None,
        overall_timeout: float | None = None,
        journal_dir=None,
        journal_key: str | None = None,
    ) -> dict[int, np.ndarray]:
        heartbeat_interval = (constants.HEARTBEAT_INTERVAL
                              if heartbeat_interval is None else heartbeat_interval)
        job = await self.store.init_tile_job(job_id, total, chunk=chunk)
        journal = None
        if journal_dir:
            # ctor (mkdir + TTL sweep) and load (read+unpack of possibly
            # hundreds of MB) must not block the serving event loop
            journal = await asyncio.to_thread(
                TileJournal, journal_dir, journal_key or job_id)
        if journal:
            restored = 0
            loaded = await asyncio.to_thread(journal.load)
            for tid, arr in loaded.items():
                if await self.store.restore_completed(job_id, tid,
                                                      {"image": arr}):
                    restored += 1
            if restored:
                log(f"tile-farm[{job_id}] resumed {restored} tasks "
                    "from journal")
        deadline = (time.monotonic() + overall_timeout) if overall_timeout else None
        last_check = time.monotonic()
        log(f"tile-farm[{job_id}] master: {job.total_tasks} tasks "
            f"(chunk {chunk}, {total} tiles)")
        # Optional grace window before the master competes for the queue:
        # until a worker's first pull (or the window expires) the master
        # only drains results. A warm master on a loaded host can otherwise
        # drain every task before a cold worker's first pull — harmless in
        # production (the job still completes) but it starves fault-
        # injection tests that need the worker to HOLD an assignment
        # (tests/test_integration.py). Default 0 = no behavior change.
        holdback_s = constants.TILE_MASTER_HOLDBACK_S.get()
        # 0.0 = disabled (falsy); the release check below also resets it
        holdback_until = time.monotonic() + holdback_s if holdback_s else 0.0

        while True:
            async with self.store.lock:
                # dead-lettered tasks are terminal: a poison tile bounds
                # the damage instead of hanging the whole job
                done = job.is_complete()
                if holdback_until and any(
                        w != "master" for w in job.worker_status):
                    holdback_until = 0.0    # a worker pulled; master joins
            if done:
                break
            if deadline and time.monotonic() > deadline:
                raise TileCollectionError(
                    f"tile job {job_id} timed out", job_id=job_id)

            if holdback_until and time.monotonic() < holdback_until:
                task = None                 # leave the queue to workers
            else:
                task = await self.store.request_work(job_id, "master")
            if task is not None:
                try:
                    arr = await asyncio.to_thread(
                        process_fn, task["start"], task["end"])
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # poison tile on the master itself: bounded requeue,
                    # then dead-letter — never crash the whole job for
                    # one range (degradation contract, docs/resilience.md)
                    live = await self.store.record_task_failure(
                        job_id, "master", task["task_id"], repr(e))
                    log(f"tile-farm[{job_id}] task {task['task_id']} "
                        f"failed on master ({e!r}); "
                        f"{'requeued' if live else 'dead-lettered'}")
                    continue
                await self.store.submit_result(
                    job_id, "master", task["task_id"], {"image": arr})
                if journal:
                    await asyncio.to_thread(journal.write, task["task_id"], arr)
            else:
                # queue momentarily empty: wait for worker results
                try:
                    tid, payload = await asyncio.wait_for(
                        job.results.get(),
                        timeout=min(constants.COLLECT_POLL_TIMEOUT,
                                    heartbeat_interval),
                    )
                    if journal:
                        await asyncio.to_thread(
                            journal.write, tid, payload["image"])
                except asyncio.TimeoutError:
                    pass

            if time.monotonic() - last_check >= heartbeat_interval:
                evicted = await check_and_requeue_timed_out_workers(
                    self.store, job_id, timeout=worker_timeout,
                    probe_fn=probe_fn)
                for w, tasks in evicted.items():
                    log(f"tile-farm[{job_id}] requeued {len(tasks)} tasks "
                        f"from silent worker {w}")
                last_check = time.monotonic()

        async with self.store.lock:
            results = {tid: payload["image"]
                       for tid, payload in job.completed.items()}
            dead = list(job.dead_letter.values())
        if dead:
            log(f"tile-farm[{job_id}] finished with {len(dead)} "
                f"dead-lettered tasks: "
                f"{[d['task_id'] for d in dead]}")
        await self.store.cleanup_job(job_id)
        if journal:
            journal.clear()
        log(f"tile-farm[{job_id}] complete ({len(results)} tasks)")
        return results

    # --- worker role --------------------------------------------------------

    async def worker_run_async(
        self, job_id: str, worker_id: str, master_url: str,
        process_fn: ProcessFn, **kw,
    ) -> int:
        with _tm_span("tile_job.worker", job_id=job_id,
                      worker_id=worker_id):
            return await self._worker_run_inner(job_id, worker_id,
                                                master_url, process_fn,
                                                **kw)

    async def _worker_run_inner(
        self, job_id: str, worker_id: str, master_url: str,
        process_fn: ProcessFn, max_batch: int | None = None,
        ready_polls: int | None = None, ready_interval: float = 1.0,
    ) -> int:
        """Pull-process-submit loop; returns number of tasks completed.

        The default ready budget (``CDT_TILE_READY_POLLS`` × 1 s) covers
        a COLD master: the tile job is seeded only when the master's
        executor reaches the USDU node, behind the same upstream
        compiles the worker races through — a 20 s budget lost that race
        on a 1-core host and the worker left with 0 tasks."""
        if ready_polls is None:
            ready_polls = constants.TILE_READY_POLLS.get()
        max_batch = constants.MAX_BATCH if max_batch is None else max_batch
        base = normalize_host_url(master_url)
        session = get_client_session()

        if not await self._poll_job_ready(session, base, job_id,
                                          ready_polls, ready_interval):
            log(f"tile-farm[{job_id}] worker {worker_id}: job never appeared")
            return 0

        pending_flush: list[tuple[int, dict, np.ndarray]] = []
        completed = 0
        while True:
            task, draining = await self._request_work(session, base, job_id,
                                                      worker_id)
            if task is None:
                if draining:
                    debug_log(f"tile-farm[{job_id}] worker {worker_id} "
                              "marked draining; flushing and leaving")
                break
            arr = await asyncio.to_thread(process_fn, task["start"], task["end"])
            meta = {"task_id": task["task_id"], "start": task["start"],
                    "end": task["end"]}
            pending_flush.append((task["task_id"], meta, arr))
            completed += 1
            await self._heartbeat(session, base, job_id, worker_id)
            if len(pending_flush) >= max_batch:
                await self._flush(session, base, job_id, worker_id, pending_flush)
                pending_flush = []
        if pending_flush:
            await self._flush(session, base, job_id, worker_id, pending_flush)
        debug_log(f"tile-farm[{job_id}] worker {worker_id}: "
                  f"{completed} tasks done")
        return completed

    # --- steal-mode worker role (cluster/elastic/scheduler) -----------------

    def worker_steal_run(self, worker_id: str, master_url: str,
                         resolve_fn: Callable[[str], Optional[ProcessFn]],
                         **kw) -> dict[str, int]:
        return run_in_loop(
            self.worker_steal_run_async(worker_id, master_url, resolve_fn,
                                        **kw),
            self.loop, timeout=None)

    async def worker_steal_run_async(
        self, worker_id: str, master_url: str,
        resolve_fn: Callable[[str], Optional[ProcessFn]],
        max_batch: int | None = None,
        idle_polls: int = 3, idle_interval: float = 0.5,
    ) -> dict[str, int]:
        """Cross-job pull loop: ask the master's steal scheduler for work
        from ANY open job (``job_id="*"``), process each grant with the
        job resolved by ``resolve_fn(job_id) -> ProcessFn`` (None =
        unknown job: the grant is handed straight back), and flush
        results to the grant's own job. Returns {job_id: completed}.

        This is what a newly arrived (scale-up) worker runs: it serves
        whichever open job is most starved the moment it comes up,
        instead of waiting for the next dispatch. The loop ends after
        ``idle_polls`` consecutive empty pulls (every open queue drained)
        or the moment the master marks this worker draining.
        """
        with _tm_span("tile_job.steal_worker", worker_id=worker_id):
            return await self._worker_steal_inner(
                worker_id, master_url, resolve_fn, max_batch,
                idle_polls, idle_interval)

    async def _worker_steal_inner(
        self, worker_id: str, master_url: str,
        resolve_fn: Callable[[str], Optional[ProcessFn]],
        max_batch: int | None, idle_polls: int, idle_interval: float,
    ) -> dict[str, int]:
        max_batch = constants.MAX_BATCH if max_batch is None else max_batch
        base = normalize_host_url(master_url)
        session = get_client_session()
        completed: dict[str, int] = {}
        # per-job flush buffers: results must route to their own job
        pending: dict[str, list[tuple[int, dict, np.ndarray]]] = {}
        unservable: set[str] = set()
        idle = 0
        while idle < idle_polls:
            task, draining = await self._request_work(
                session, base, "*", worker_id,
                extra={"exclude_jobs": sorted(unservable)}
                if unservable else None)
            if draining:
                # asked to leave: stop pulling IMMEDIATELY (the refusal
                # is intentional, not an empty queue) — buffered results
                # still flush below so a clean drain loses nothing
                debug_log(f"steal[{worker_id}] marked draining; "
                          "flushing and exiting")
                break
            if task is None:
                idle += 1
                # flush everything before idling — a result sitting in
                # the buffer is still "assigned" master-side and would be
                # handed back if this worker drains while waiting
                for jid, batch in list(pending.items()):
                    if batch:
                        await self._flush(session, base, jid, worker_id,
                                          batch)
                        pending[jid] = []
                await asyncio.sleep(idle_interval)
                continue
            jid = task.get("job_id", "")
            fn = resolve_fn(jid)
            if fn is None:
                # a job this worker can't serve (no weights/workflow):
                # give the grant straight back so someone else takes it.
                # A re-grant from a known-unservable job counts as an
                # idle poll — when unservable jobs are all that's open,
                # the loop must wind down, not ping-pong the grant
                debug_log(f"steal[{worker_id}] cannot serve job {jid}; "
                          "handing the task back")
                await self._handback_task(session, base, jid, worker_id)
                if jid in unservable:
                    idle += 1
                    await asyncio.sleep(idle_interval)
                else:
                    unservable.add(jid)
                continue
            idle = 0
            arr = await asyncio.to_thread(fn, task["start"], task["end"])
            meta = {"task_id": task["task_id"], "start": task["start"],
                    "end": task["end"]}
            pending.setdefault(jid, []).append((task["task_id"], meta, arr))
            completed[jid] = completed.get(jid, 0) + 1
            # heartbeat EVERY job we still hold unflushed work in, not
            # just the latest grant's: job A's monitor must keep seeing
            # us alive while the scheduler has us grinding job B, or A
            # falsely evicts us through the failure path (breaker trip +
            # poison-bound requeue) with its results sitting in our buffer
            for held_jid in {jid, *(j for j, b in pending.items() if b)}:
                await self._heartbeat(session, base, held_jid, worker_id)
            if len(pending[jid]) >= max_batch:
                await self._flush(session, base, jid, worker_id,
                                  pending[jid])
                pending[jid] = []
        for jid, batch in pending.items():
            if batch:
                await self._flush(session, base, jid, worker_id, batch)
        debug_log(f"steal[{worker_id}] done: {completed}")
        return completed

    async def _handback_task(self, session, base, job_id, worker_id) -> None:
        """Give an unservable grant back (drain-handback accounting:
        the hop is intentional, not failure evidence)."""
        try:
            async with session.post(
                    f"{base}/distributed/handback",
                    json={"job_id": job_id, "worker_id": worker_id}) as resp:
                await resp.release()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            pass   # heartbeat silence will requeue it eventually anyway

    # --- wire helpers -------------------------------------------------------

    async def _poll_job_ready(self, session, base, job_id, polls, interval) -> bool:
        for _ in range(polls):
            try:
                async with session.get(
                        f"{base}/distributed/job_status",
                        params={"job_id": job_id}) as resp:
                    if resp.status < 400:
                        body = await resp.json()
                        # the TILE job specifically: orchestration
                        # pre-creates a collector-kind entry under the
                        # same id BEFORE the master's node seeds the
                        # tile queue — a worker that accepted it would
                        # pull once into the not-yet-initialized farm,
                        # read task=None as "drained", and leave with 0
                        # tasks (observed in the 3-host integration
                        # test; the reference covers the same race with
                        # 404-tolerant pulls, worker_comms.py:124-169)
                        if body.get("exists") and \
                                body.get("kind") != "collector":
                            return True
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                pass
            await asyncio.sleep(interval)
        return False

    async def _request_work(self, session, base, job_id, worker_id,
                            extra: "Optional[dict]" = None,
                            ) -> "tuple[Optional[dict], bool]":
        """WORK_REQUEST_BUDGET-bounded, 404-tolerant pull (reference
        ``worker_comms.py:124-169``) through the unified RetryPolicy:
        full-jitter backoff instead of the old fixed ladder, so a worker
        fleet re-polling a restarting master spreads out rather than
        connecting in lockstep.

        ``job_id="*"`` asks the master's cross-job scheduler for work
        from ANY open job (the grant carries its ``job_id``). Returns
        ``(task, draining)``: a ``draining: true`` answer means this
        worker was asked to leave — an intentional refusal, not an empty
        queue — so the caller must stop pulling NOW (and it never burns
        the retry budget). ``extra`` merges into the request body (the
        steal loop sends its ``exclude_jobs`` can't-serve list there)."""
        async def attempt() -> "tuple[Optional[dict], bool]":
            async with session.post(
                    f"{base}/distributed/request_image",
                    json={"job_id": job_id, "worker_id": worker_id,
                          **(extra or {})}) as resp:
                if resp.status >= 400:
                    # master mid-restart / job not yet seeded: retryable
                    err = WorkerError(f"work request {resp.status}",
                                      worker_id=worker_id)
                    err.retry_safe = True
                    raise err
                body = await resp.json()
                return body.get("task"), bool(body.get("draining"))

        try:
            return await work_request_policy().run(attempt, op="request_work")
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                WorkerError) as e:
            debug_log(f"work request budget exhausted ({e}); "
                      "treating queue as drained")
            return None, False

    async def _heartbeat(self, session, base, job_id, worker_id) -> None:
        try:
            async with session.post(
                    f"{base}/distributed/heartbeat",
                    json={"job_id": job_id, "worker_id": worker_id}) as resp:
                await resp.release()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            pass   # heartbeat loss is what the timeout monitor detects

    async def _flush(self, session, base, job_id, worker_id, batch) -> None:
        """Size-capped chunked multipart submit with retries (reference
        ``worker_comms.py:16-108``: ≤ MAX_PAYLOAD−1MB per POST, ≥1 tile).

        A single frame larger than the cap (dynamic mode ships whole
        upscaled images, which a 4× upscale easily pushes past 50 MB) is
        byte-split across sequential POSTs; the master reassembles before
        unpacking."""
        from .. import native

        # 1 MB headroom for multipart framing; the floor keeps the math
        # sane when tests shrink MAX_PAYLOAD_SIZE
        cap = max(constants.MAX_PAYLOAD_SIZE - (1 << 20),
                  constants.MAX_PAYLOAD_SIZE // 2, 1)
        loop = asyncio.get_running_loop()
        group: list[tuple[int, dict, bytes]] = []
        size = 0
        for task_id, meta, arr in batch:
            # zlib deflate + crc of a full tile: off the event loop
            frame = await loop.run_in_executor(
                None,
                lambda a=arr: native.pack_frame(
                    np.asarray(a, np.float32), level=1))
            if len(frame) > cap:
                if group:
                    await self._post_tiles(session, base, job_id, worker_id, group)
                    group, size = [], 0
                await self._post_frame_parts(session, base, job_id, worker_id,
                                             task_id, frame, cap)
                continue
            if group and size + len(frame) > cap:
                await self._post_tiles(session, base, job_id, worker_id, group)
                group, size = [], 0
            group.append((task_id, meta, frame))
            size += len(frame)
        if group:
            await self._post_tiles(session, base, job_id, worker_id, group)

    async def _post_frame_parts(self, session, base, job_id, worker_id,
                                task_id, frame: bytes, cap: int) -> None:
        """Split one oversized frame into byte-range parts ≤ cap each."""
        n = -(-len(frame) // cap)
        for j in range(n):
            chunk = frame[j * cap:(j + 1) * cap]
            await self._post_tiles(
                session, base, job_id, worker_id,
                [(task_id, {"task_id": task_id}, chunk)],
                frame_parts={"task_id": task_id, "part_index": j,
                             "part_count": n})

    async def _post_tiles(self, session, base, job_id, worker_id, group,
                          frame_parts: dict | None = None) -> None:
        url = f"{base}/distributed/submit_tiles"

        async def attempt() -> None:
            # the form is rebuilt per attempt — aiohttp consumes FormData
            # on send, and a corrupted payload (crc-rejected by the
            # master) must be re-encoded from the intact frames
            form = aiohttp.FormData()
            meta_doc = {
                "job_id": job_id, "worker_id": worker_id,
                "tiles": [{**meta, "part": f"tile_{tid}"}
                          for tid, meta, _ in group],
            }
            if frame_parts:
                meta_doc["frame_parts"] = frame_parts
            form.add_field("tiles_metadata", json.dumps(meta_doc),
                           content_type="application/json")
            for tid, _, frame in group:
                form.add_field(f"tile_{tid}", frame,
                               filename=f"tile_{tid}.cdtf",
                               content_type="application/x-cdt-frame")
            async with session.post(url, data=form,
                                    headers={"X-CDT-Client": "1"}) as resp:
                if resp.status >= 400:
                    body = await resp.text()
                    # submit_result is idempotent on the master, so a
                    # re-send can never double-record a tile
                    err = WorkerError(f"{resp.status}: {body[:200]}",
                                      worker_id=worker_id)
                    err.retry_safe = True
                    raise err

        try:
            await send_policy().run(attempt, op="submit")
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                WorkerError) as e:
            raise WorkerError(
                f"tile submit to {url} failed after retries: {e}") from e


def assemble_tiles(results: dict[int, np.ndarray], total: int,
                   chunk: int, *,
                   fallback_fn: "ProcessFn | None" = None) -> np.ndarray:
    """{task_id: [n, ch, cw, C]} → ordered [total, ch, cw, C].

    ``master_run`` returns only COMPLETED tasks — dead-lettered (poison)
    tasks are absent. With ``fallback_fn(start, end)`` the missing
    ranges are filled from a degraded source (e.g. the plain-resized
    tiles, skipping diffusion) so one poison tile costs one unrefined
    region instead of the whole job; without it, missing tasks raise a
    descriptive :class:`TileCollectionError` naming them (never a raw
    shape/concatenate error)."""
    n_tasks = -(-total // chunk)
    filled = dict(results)
    missing = [tid for tid in range(n_tasks) if tid not in filled]
    if missing and fallback_fn is not None:
        for tid in missing:
            start, end = tid * chunk, min((tid + 1) * chunk, total)
            filled[tid] = fallback_fn(start, end)
        log(f"assemble: filled {len(missing)} dead-lettered task(s) "
            f"{missing} from the degraded fallback")
    elif missing:
        raise TileCollectionError(
            f"tile tasks {missing} missing from results (dead-lettered? "
            "see the job's dead_letter list in /distributed/job_status)")
    parts = [np.asarray(filled[tid], np.float32) for tid in sorted(filled)]
    out = np.concatenate(parts, axis=0)
    if out.shape[0] < total:
        raise TileCollectionError(
            f"assembled {out.shape[0]} tiles, expected {total}")
    return out[:total]
