"""Heartbeat-timeout detection and work requeue.

Parity: reference ``upscale/job_timeout.py:17-150`` with the same
three-phase discipline:

1. snapshot suspect workers **under** the store lock;
2. probe the suspects **outside** the lock (a probe can take seconds —
   holding the lock would stall result ingest);
3. re-acquire to apply: spare workers whose probe shows a busy queue
   (refresh their heartbeat — the "busy grace"), requeue everything else.
"""

from __future__ import annotations

import time
from typing import Awaitable, Callable, Optional

from ..telemetry import enabled as _tm_enabled, metrics as _tm
from ..utils import constants
from ..utils.logging import log
from .job_store import JobStore
from .resilience import BREAKERS

ProbeFn = Callable[[str], Awaitable[Optional[dict]]]


async def check_and_requeue_timed_out_workers(
    store: JobStore,
    job_id: str,
    timeout: float | None = None,
    probe_fn: ProbeFn | None = None,
    now: float | None = None,
    max_requeues: int | None = None,
) -> dict[str, list[int]]:
    """Returns {worker_id: [requeued task ids]} for evicted workers.

    ``probe_fn(worker_id)`` returns a health dict or None; a worker whose
    health reports ``queue_remaining > 0`` is spared and its heartbeat
    refreshed (reference busy-probe grace, ``job_timeout.py:48-110``).

    Requeues are bounded by ``max_requeues`` (default
    ``constants.MAX_TILE_REQUEUES``): a task evicted more often
    dead-letters instead of cycling forever. An eviction also trips the
    worker's circuit breaker (``resilience.BREAKERS``) so orchestration
    quarantines the host instead of re-probing it on the next job.
    """
    timeout = constants.HEARTBEAT_TIMEOUT if timeout is None else timeout
    now = time.monotonic() if now is None else now

    # phase 1: snapshot under lock
    async with store.lock:
        job = store.tile_jobs.get(job_id)
        if job is None:
            return {}
        suspects = [
            w for w, last in job.worker_status.items()
            if now - last > timeout and any(
                owner == w and tid not in job.completed
                for tid, owner in job.assigned.items()
            )
        ]
    if not suspects:
        return {}

    # phase 2: probe outside the lock
    spared: set[str] = set()
    if probe_fn is not None:
        for w in suspects:
            health = await probe_fn(w)
            if health and int(health.get("queue_remaining", 0)) > 0:
                spared.add(w)

    # phase 3: apply
    from .elastic.states import DRAIN

    evicted: dict[str, list[int]] = {}
    for w in suspects:
        if w in spared:
            await store.heartbeat(job_id, w)
            log(f"worker {w} silent but busy — heartbeat refreshed (grace)")
            if _tm_enabled():
                _tm.TILE_WORKER_EVICTIONS.labels(outcome="spared").inc()
            continue
        leaving = w != "master" and DRAIN.is_leaving(w)
        if leaving:
            # a draining worker that went silent left a little early —
            # that is still an INTENTIONAL departure: requeue its held
            # tiles without poison-bound accounting and leave its breaker
            # alone. The drain handback path and this one both clear
            # ``assigned`` under the store lock, so whichever runs first
            # requeues and the other finds nothing (exactly-once).
            requeued = await store.requeue_worker_tasks(
                job_id, w, count_requeue=False)
            if requeued:
                log(f"draining worker {w} went silent; handed back "
                    f"tasks {requeued} (no breaker, no requeue count)")
            evicted[w] = requeued
            if _tm_enabled():
                _tm.TILE_WORKER_EVICTIONS.labels(outcome="draining").inc()
                if requeued:
                    _tm.DRAIN_HANDBACKS.inc(len(requeued))
            continue
        requeued = await store.requeue_worker_tasks(
            job_id, w, max_requeues=max_requeues)
        if requeued:
            log(f"worker {w} timed out; requeued tasks {requeued}")
        evicted[w] = requeued
        if w != "master":
            # eviction-grade evidence: open the breaker immediately so the
            # next orchestration skips this host instead of re-probing it
            BREAKERS.trip(w)
        if _tm_enabled():
            _tm.TILE_WORKER_EVICTIONS.labels(outcome="evicted").inc()
            if requeued:
                _tm.TILE_EVENTS.labels(event="timed_out").inc(len(requeued))
    return evicted
