"""Graceful drain/decommission: the deliberate way out of the fleet.

``POST /distributed/worker/{id}/drain`` (api/worker_routes.py) lands
here. The lifecycle:

1. **Mark draining** (:mod:`.states`): from this instant
   ``select_active_hosts`` skips the host without probing it, the tile
   scheduler stops granting it work (``/distributed/request_image``
   answers ``draining: true``), and the front door's healthy-fraction
   math ignores it.
2. **Let in-flight work finish**: the coordinator polls the job store
   until the worker holds no assignments — completed tiles flow back
   through the normal submit path, so a clean drain loses nothing and
   requeues nothing.
3. **Deadline handback**: work still held when the drain deadline
   expires is returned to the front of its job's queue via
   ``JobStore.handback_worker_tasks`` — requeued WITHOUT poison-bound
   accounting and WITHOUT breaker evidence (the worker didn't fail; it
   was told to go). The heartbeat-eviction path applies the same
   accounting to a draining worker that goes silent early, and both
   paths clear assignments under the store lock, so a tile is handed
   back exactly once.
4. **Decommission**: the managed process (if any) is stopped and the
   registry records ``decommissioned``. ``undrain`` at any point before
   that reactivates the worker (scale-up reusing a drained id does the
   same).

Every step is observable: ``cdt_worker_drain_state``,
``cdt_drain_handbacks_total``, and the per-drain report kept for
``GET /distributed/elastic``.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from ...utils import constants
from ...utils.logging import log
from .states import DRAIN, DrainRegistry


class DrainCoordinator:
    """Runs drains as asyncio tasks on the controller loop; one live
    drain per worker id (a second request is a no-op reporting the
    existing drain)."""

    def __init__(self, store, *, registry: DrainRegistry = DRAIN,
                 process_stopper: Optional[Callable[[str], bool]] = None,
                 poll_interval: float = 0.25,
                 preempter: Optional[Callable[[], object]] = None):
        self.store = store
        self.registry = registry
        # stops the local managed process after handback (process
        # manager hook; None for externally-managed / remote workers)
        self.process_stopper = process_stopper
        self.poll_interval = poll_interval
        # step-granular preemption hook (cluster/preemption.py): a drain
        # asks the running denoise loop to checkpoint at its next
        # segment boundary instead of waiting it out — scale-downs free
        # the slot in one segment, not one job (docs/preemption.md)
        self.preempter = preempter
        self._tasks: dict[str, asyncio.Task] = {}
        # worker_id → last drain report (kept after completion for the
        # status surface; bounded by fleet size)
        self.reports: dict[str, dict] = {}

    # --- public API ---------------------------------------------------------

    def begin(self, worker_id: str,
              deadline_s: Optional[float] = None,
              stop_process: bool = True) -> dict:
        """Start (or report an already-running) drain. Returns the
        current report snapshot."""
        wid = str(worker_id)
        if deadline_s is None:
            deadline_s = constants.DRAIN_DEADLINE_S
        live = self._tasks.get(wid)
        if live is not None and not live.done():
            return dict(self.reports.get(wid, {"worker_id": wid,
                                               "phase": "draining"}))
        if not self.registry.mark_draining(wid, deadline_s=deadline_s):
            # already draining/decommissioned with no live task (e.g.
            # marked by a peer path) — report what we know
            return dict(self.reports.get(
                wid, {"worker_id": wid, "phase": self.registry.state(wid)}))
        self.reports[wid] = {
            "worker_id": wid, "phase": "draining",
            "deadline_s": deadline_s, "handed_back": {}, "held_at_start": {},
        }
        self._tasks[wid] = asyncio.ensure_future(
            self._drain(wid, deadline_s, stop_process))
        return dict(self.reports[wid])

    def undrain(self, worker_id: str) -> bool:
        """Cancel a drain-in-progress and reactivate the worker."""
        wid = str(worker_id)
        task = self._tasks.pop(wid, None)
        if task is not None and not task.done():
            task.cancel()
        cleared = self.registry.reactivate(wid)
        if cleared:
            self.reports.setdefault(wid, {"worker_id": wid})
            self.reports[wid]["phase"] = "reactivated"
        return cleared

    async def wait(self, worker_id: str) -> Optional[dict]:
        """Await a live drain (tests / synchronous callers)."""
        task = self._tasks.get(str(worker_id))
        if task is not None:
            try:
                await task
            except asyncio.CancelledError:
                pass
        return self.reports.get(str(worker_id))

    def status(self) -> dict:
        return {
            "states": self.registry.states(),
            "reports": {w: dict(r) for w, r in self.reports.items()},
        }

    async def close(self) -> None:
        """Cancel in-flight drains (controller shutdown): the registry
        keeps its states — a restart resumes from them — but no task may
        outlive the loop."""
        for task in list(self._tasks.values()):
            if not task.done():
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        self._tasks.clear()

    # --- the drain itself ---------------------------------------------------

    async def _drain(self, wid: str, deadline_s: float,
                     stop_process: bool) -> None:
        report = self.reports[wid]
        if self.preempter is not None:
            try:
                preempted = self.preempter()
                if preempted:
                    report["preempted_prompt"] = preempted
            except Exception as e:  # noqa: BLE001 — the drain proceeds
                # on the deadline path regardless; preemption only
                # makes it faster
                report["preempt_error"] = str(e)
        report["held_at_start"] = await self.store.worker_held_tasks(wid)
        # the registry's deadline (stamped by mark_draining) is the ONE
        # source of truth — it is what the status surface reports, so
        # the coordinator must act on the same clock
        deadline = self.registry.deadline(wid)
        if deadline is None:
            deadline = time.monotonic() + deadline_s
        try:
            while time.monotonic() < deadline:
                if self.registry.state(wid) != "draining":
                    # undrained concurrently — stop quietly
                    return
                held = await self.store.worker_held_tasks(wid)
                if not held:
                    break
                await asyncio.sleep(self.poll_interval)
            # deadline (or clean finish): hand back whatever is left —
            # no-op when the worker finished everything
            handed = await self.store.handback_worker_tasks(wid)
            report["handed_back"] = handed
            if handed:
                log(f"drain[{wid}] deadline handback: "
                    f"{ {j: len(t) for j, t in handed.items()} }")
            if stop_process and self.process_stopper is not None:
                try:
                    report["process_stopped"] = bool(
                        await asyncio.to_thread(self.process_stopper, wid))
                except Exception as e:  # noqa: BLE001 — decommission must
                    # not hang on a process-manager error; the registry
                    # state is what the fleet acts on
                    report["process_stop_error"] = str(e)
            self.registry.mark_decommissioned(wid)
            report["phase"] = "decommissioned"
        except asyncio.CancelledError:
            # undrain() sets phase="reactivated" right after cancelling
            # this task; the handler runs on a LATER loop tick and must
            # not overwrite that verdict (shutdown-time cancellation
            # still records "cancelled")
            if report.get("phase") == "draining":
                report["phase"] = "cancelled"
            raise
