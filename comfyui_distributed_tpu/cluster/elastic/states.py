"""Worker lifecycle states for the elastic fleet: leaving ≠ broken.

PR 3's circuit breakers answer "is this worker *failing*?"; this registry
answers the orthogonal question "is this worker *supposed to be here*?".
A worker being decommissioned on purpose — autoscaler scale-down, rolling
restart, operator drain — must be distinguishable from a dead one
everywhere failure evidence is collected, or every intentional departure
poisons the fleet's health signals:

- ``select_active_hosts`` would probe it, time out, and feed the failure
  to its breaker (quarantining a worker that was *asked* to leave);
- the tile farm would keep assigning it work it is trying to give up;
- heartbeat eviction would trip its breaker and count its requeues
  toward the poison-tile bound;
- the front door's healthy-fraction scaling would shed load for a fleet
  that is merely *smaller*, not *sicker*.

The registry is process-global on the master (mirroring ``BREAKERS``) and
thread-safe: asyncio route handlers, the autoscaler loop, and the graph
executor thread all consult it. States move strictly forward
(active → draining → decommissioned) except for an explicit
``reactivate`` — a worker that rejoins (undrain, or a scale-up reusing
the id) starts clean.

Exported as the ``cdt_worker_drain_state`` gauge (0=active, 1=draining,
2=decommissioned) and shown on the dashboard next to the breaker badge.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ...lint.lockorder import tracked_lock
from ...telemetry import enabled as _tm_enabled, metrics as _tm
from ...utils.logging import log

ACTIVE, DRAINING, DECOMMISSIONED = "active", "draining", "decommissioned"
_STATE_VALUE = {ACTIVE: 0, DRAINING: 1, DECOMMISSIONED: 2}


class DrainRegistry:
    """worker_id → lifecycle state (+ drain deadline bookkeeping).

    Unknown workers are ``active`` — the registry only tracks departures,
    so a fresh fleet costs nothing.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = tracked_lock("elastic.drain")
        self._states: dict[str, str] = {}
        # worker_id → monotonic deadline by which in-flight work must be
        # finished or handed back (None = no deadline pressure yet)
        self._deadlines: dict[str, Optional[float]] = {}
        self._clock = clock
        # lifecycle listeners: fn(worker_id, state) called OUTSIDE the
        # lock after every transition (fleet cache ring rebuild / drain
        # handback subscribe here)
        self._listeners: list[Callable[[str, str], None]] = []

    # --- queries ------------------------------------------------------------

    def state(self, worker_id: str) -> str:
        with self._lock:
            return self._states.get(str(worker_id), ACTIVE)

    def is_active(self, worker_id: str) -> bool:
        return self.state(worker_id) == ACTIVE

    def is_draining(self, worker_id: str) -> bool:
        return self.state(worker_id) == DRAINING

    def is_leaving(self, worker_id: str) -> bool:
        """Draining OR decommissioned: every site that must treat the
        departure as intentional (breakers, healthy-fraction, eviction
        accounting) checks this, not the individual states."""
        return self.state(worker_id) != ACTIVE

    def deadline(self, worker_id: str) -> Optional[float]:
        with self._lock:
            return self._deadlines.get(str(worker_id))

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._states)

    # --- lifecycle feed -----------------------------------------------------

    def subscribe(self, fn: Callable[[str, str], None]) -> None:
        """Register ``fn(worker_id, new_state)``, invoked after every
        transition, outside the registry lock (a listener may re-enter
        queries). Listener exceptions are swallowed — lifecycle
        bookkeeping must never be blocked by an observer."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def unsubscribe(self, fn: Callable[[str, str], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _notify(self, worker_id: str) -> None:
        with self._lock:
            listeners = list(self._listeners)
        state = self.state(worker_id)
        for fn in listeners:
            try:
                fn(worker_id, state)
            except Exception:  # noqa: BLE001 — observers never block lifecycle
                pass

    # --- transitions --------------------------------------------------------

    def mark_draining(self, worker_id: str,
                      deadline_s: Optional[float] = None) -> bool:
        """Begin an intentional departure. Returns False when the worker
        is already draining/decommissioned (idempotent — a double drain
        request must not reset the deadline clock)."""
        wid = str(worker_id)
        with self._lock:
            if self._states.get(wid, ACTIVE) != ACTIVE:
                return False
            self._states[wid] = DRAINING
            self._deadlines[wid] = (
                self._clock() + deadline_s if deadline_s else None)
        log(f"drain[{wid}] active -> draining"
            + (f" (deadline {deadline_s:.0f}s)" if deadline_s else ""))
        self._export(wid)
        self._notify(wid)
        return True

    def mark_decommissioned(self, worker_id: str) -> None:
        wid = str(worker_id)
        with self._lock:
            before = self._states.get(wid, ACTIVE)
            self._states[wid] = DECOMMISSIONED
            self._deadlines.pop(wid, None)
        if before != DECOMMISSIONED:
            log(f"drain[{wid}] {before} -> decommissioned")
        self._export(wid)
        self._notify(wid)

    def reactivate(self, worker_id: str) -> bool:
        """Undrain / rejoin: the worker is part of the fleet again.
        Returns whether a non-active state was cleared."""
        wid = str(worker_id)
        with self._lock:
            before = self._states.pop(wid, ACTIVE)
            self._deadlines.pop(wid, None)
        if before != ACTIVE:
            log(f"drain[{wid}] {before} -> active (reactivated)")
        self._export(wid)
        self._notify(wid)
        return before != ACTIVE

    def reset(self) -> None:
        with self._lock:
            wids = list(self._states)
            self._states.clear()
            self._deadlines.clear()
        for wid in wids:
            self._export(wid)

    # --- telemetry ----------------------------------------------------------

    def _export(self, worker_id: str) -> None:
        if _tm_enabled():
            _tm.WORKER_DRAIN_STATE.labels(worker=worker_id).set(
                _STATE_VALUE[self.state(worker_id)])


DRAIN = DrainRegistry()
