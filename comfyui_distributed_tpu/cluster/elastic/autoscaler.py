"""Telemetry-driven autoscaling: size the fleet to offered concurrency.

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) frames fleet sizing as matching parallel capacity to offered
work rather than provisioning a static pool; the serving analogue here
drives a policy loop off the signals the repo already exports — the
front door's depth (``cdt_fd_queue_depth``'s underlying quantity), the
cross-job tile backlog (``cdt_tile_queue_depth``'s), and the sampler
step-time — and turns them into scale-up / scale-down decisions.

Decisions are deliberately boring:

- **pressure** = (prompt depth + tile backlog) / serving capacity
  (active workers + the master itself);
- **hysteresis**: pressure must hold above ``scale_up_depth`` (below
  ``scale_down_depth``) for N consecutive evaluations before anything
  happens — one bursty poll must not flap the fleet;
- **cooldowns**: independent up/down refractory windows, because adding
  capacity should be fast and removing it should be reluctant;
- **envelope**: a ``[min_workers, max_workers]`` clamp the policy can
  never leave, whatever the signals say.

Execution goes through a :class:`ScaleProvider`: the in-repo
:class:`LocalProcessProvider` launches/drains managed local processes
(``workers/process_manager.py``); remote/tunnel capacity (the source
paper's cloud-presets model) plugs in via ``CDT_SCALE_PROVIDER`` with a
``module:factory`` path. Scale-down is NEVER a kill: it begins a
graceful drain (:mod:`.drain`), so in-flight work finishes or hands back
and the breaker layer sees an intentional departure.

Every verdict — including holds — is itself telemetry:
``cdt_autoscale_decisions_total{direction,reason}``.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable, Optional, Protocol

from ...telemetry import enabled as _tm_enabled, metrics as _tm
from ...utils import constants
from ...utils.logging import debug_log, log
from .states import DRAIN, DrainRegistry


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """One evaluation tick's inputs (all instantaneous reads).

    Signals are split PER STAGE POOL (docs/stages.md): ``queue_depth``
    is the DENOISE-facing depth (queued/executing prompts + the
    coalescing window — work that needs a chip), while
    ``encode_depth``/``decode_depth`` are the host-side stage pools'
    backlogs. Only the denoise-facing signals feed ``work`` /
    ``effective_work`` — a decode pile-up is the stage rebalancer's
    problem (more decode threads), and folding it into one queue signal
    would scale up denoise chips that then sit idle (the pre-split
    bug, pinned by a regression test in tests/test_stages.py)."""

    queue_depth: int            # denoise-facing: queued/executing
    #                             prompts (+ coalescing window)
    tile_depth: int             # pending tile tasks across open jobs
    step_time_p50: Optional[float] = None   # informational, for reports
    active_workers: int = 0
    draining_workers: int = 0
    decommissioned_workers: int = 0
    # recent fraction of QUEUED fingerprinted requests the result cache
    # answered without a sampler program — the content cache's pressure
    # discount (cluster/cache, docs/caching.md). Coalesced duplicates
    # are excluded: they never occupy queue depth in the first place.
    # Fleet-tier remote serves (cluster/cache/fleet.py) are INCLUDED:
    # a request answered from another worker's shard ran no program
    # here, so it discounts exactly like a local hit
    cache_hit_rate: float = 0.0
    # host-side stage pool backlogs (cluster/stages): reported and
    # exported, NEVER part of the chip-pressure computation
    encode_depth: int = 0
    decode_depth: int = 0

    @property
    def work(self) -> int:
        return self.queue_depth + self.tile_depth

    @property
    def effective_work(self) -> float:
        """Queued work discounted by the cache hit rate: a request the
        cache will answer occupies a queue slot for microseconds, not a
        TPU program — sizing the fleet on raw depth would keep paying
        for chips the cache already replaced. Tile backlog is never
        discounted (tiles don't ride the content cache). Stage-pool
        backlogs (encode/decode) are deliberately absent: they are
        host-thread work, not chip work."""
        rate = min(max(self.cache_hit_rate, 0.0), 1.0)
        return self.queue_depth * (1.0 - rate) + self.tile_depth


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    min_workers: int = 0
    max_workers: int = 4
    scale_up_depth: float = 4.0     # work per capacity unit → add a worker
    scale_down_depth: float = 0.5   # work per capacity unit → drain one
    up_streak: int = 2              # consecutive ticks before acting
    down_streak: int = 4
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0

    @classmethod
    def from_env(cls) -> "AutoscalePolicy":
        return cls(
            min_workers=constants.AUTOSCALE_MIN,
            max_workers=constants.AUTOSCALE_MAX,
            scale_up_depth=constants.AUTOSCALE_UP_DEPTH,
            scale_down_depth=constants.AUTOSCALE_DOWN_DEPTH,
            up_streak=constants.AUTOSCALE_UP_STREAK,
            down_streak=constants.AUTOSCALE_DOWN_STREAK,
            up_cooldown_s=constants.AUTOSCALE_UP_COOLDOWN_S,
            down_cooldown_s=constants.AUTOSCALE_DOWN_COOLDOWN_S,
        )


@dataclasses.dataclass(frozen=True)
class Decision:
    direction: str              # up | down | hold
    reason: str
    worker_id: Optional[str] = None
    pressure: float = 0.0


class ScaleProvider(Protocol):
    """What the policy loop needs from a capacity backend."""

    def list_workers(self) -> dict[str, dict]:
        """worker_id → {"state": lifecycle state, "running": bool}."""
        ...

    def scale_up(self) -> Optional[str]:
        """Bring one worker up; returns its id (None = no capacity)."""
        ...

    def scale_down(self, worker_id: str) -> None:
        """Begin a GRACEFUL departure (drain, never kill)."""
        ...


class LocalProcessProvider:
    """Managed local worker processes as the capacity pool.

    Scale-up launches the first enabled, configured, not-running local
    host (``workers/process_manager.py``); scale-down hands the chosen
    worker to the drain coordinator. The config's host list *is* the
    envelope of launchable capacity — remote providers replace this
    class, not the policy loop.
    """

    def __init__(self, config_loader, manager, coordinator,
                 registry: DrainRegistry = DRAIN):
        self.load_config = config_loader
        self.manager = manager
        self.coordinator = coordinator
        self.registry = registry

    def _local_hosts(self) -> list[dict]:
        return [h for h in self.load_config().get("hosts", [])
                if h.get("type") == "local" and h.get("enabled", True)
                and h.get("id")]

    def list_workers(self) -> dict[str, dict]:
        managed = self.manager.get_managed_workers()
        out: dict[str, dict] = {}
        for h in self._local_hosts():
            wid = str(h["id"])
            out[wid] = {"state": self.registry.state(wid),
                        "running": wid in managed}
        for wid in managed:
            out.setdefault(wid, {"state": self.registry.state(wid),
                                 "running": True})
        return out

    def scale_up(self) -> Optional[str]:
        managed = self.manager.get_managed_workers()
        for h in self._local_hosts():
            wid = str(h["id"])
            if wid in managed:
                continue
            # a previously drained id coming back is a fresh worker
            self.registry.reactivate(wid)
            try:
                self.manager.launch_worker(wid)
            except Exception as e:  # noqa: BLE001 — a single unlaunchable
                # host must not stop the sweep over the rest of the pool
                debug_log(f"autoscale: launch {wid} failed: {e}")
                continue
            return wid
        return None

    def scale_down(self, worker_id: str) -> None:
        self.coordinator.begin(worker_id)


class Autoscaler:
    """The policy loop. ``evaluate()`` is a pure-ish, clock-injected
    single tick (what the tests drive); ``run()`` is the controller's
    background task around it."""

    def __init__(self, signals: Callable[[], FleetSignals],
                 provider: ScaleProvider,
                 policy: Optional[AutoscalePolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.signals = signals
        self.provider = provider
        self.policy = policy or AutoscalePolicy.from_env()
        self._clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        self.decisions: list[Decision] = []   # bounded history (status)

    # --- one tick -----------------------------------------------------------

    def evaluate(self) -> Decision:
        pol = self.policy
        sig = self.signals()
        now = self._clock()
        # the master always serves, so capacity is never zero — a
        # 0-worker fleet with deep queues must still read as pressured.
        # Work is cache-discounted (FleetSignals.effective_work): a hot
        # cache scales the fleet DOWN even while raw depth stays high
        capacity = max(1, sig.active_workers + 1)
        pressure = sig.effective_work / capacity

        if pressure >= pol.scale_up_depth:
            self._up_streak += 1
            self._down_streak = 0
        elif pressure <= pol.scale_down_depth:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0

        decision = self._decide(sig, now, pressure)
        self._record(decision, sig)
        return decision

    def _decide(self, sig: FleetSignals, now: float,
                pressure: float) -> Decision:
        pol = self.policy
        if self._up_streak >= pol.up_streak:
            if sig.active_workers >= pol.max_workers:
                return Decision("hold", "envelope_max", pressure=pressure)
            if now - self._last_up < pol.up_cooldown_s:
                return Decision("hold", "cooldown", pressure=pressure)
            wid = self.provider.scale_up()
            if wid is None:
                return Decision("hold", "no_capacity", pressure=pressure)
            self._last_up = now
            self._up_streak = 0
            log(f"autoscale: scale UP -> {wid} "
                f"(pressure {pressure:.2f}, work {sig.work})")
            return Decision("up", "queue_pressure", worker_id=wid,
                            pressure=pressure)
        if self._down_streak >= pol.down_streak:
            if sig.active_workers <= pol.min_workers:
                return Decision("hold", "envelope_min", pressure=pressure)
            if now - self._last_down < pol.down_cooldown_s:
                return Decision("hold", "cooldown", pressure=pressure)
            wid = self._pick_scale_down()
            if wid is None:
                return Decision("hold", "no_candidate", pressure=pressure)
            self.provider.scale_down(wid)
            self._last_down = now
            self._down_streak = 0
            log(f"autoscale: scale DOWN (drain) -> {wid} "
                f"(pressure {pressure:.2f})")
            return Decision("down", "idle_fleet", worker_id=wid,
                            pressure=pressure)
        return Decision("hold", "steady", pressure=pressure)

    def _pick_scale_down(self) -> Optional[str]:
        """Deterministic victim selection: the lexicographically-last
        running, active worker — stable under replay, and biased away
        from the long-lived low-numbered workers a config lists first."""
        workers = self.provider.list_workers()
        candidates = sorted(
            wid for wid, info in workers.items()
            if info.get("running") and info.get("state") == "active")
        return candidates[-1] if candidates else None

    def _record(self, decision: Decision, sig: FleetSignals) -> None:
        self.decisions.append(decision)
        del self.decisions[:-50]
        if _tm_enabled():
            _tm.AUTOSCALE_DECISIONS.labels(direction=decision.direction,
                                           reason=decision.reason).inc()
            # gauge from the tick's own signal snapshot — no second
            # provider.list_workers() (each one re-reads the config from
            # disk on the serving loop in the local provider)
            _tm.FLEET_SIZE.labels(state="active").set(sig.active_workers)
            _tm.FLEET_SIZE.labels(state="draining").set(
                sig.draining_workers)
            _tm.FLEET_SIZE.labels(state="decommissioned").set(
                sig.decommissioned_workers)

    # --- background loop ----------------------------------------------------

    async def run(self, interval_s: Optional[float] = None) -> None:
        interval_s = (constants.AUTOSCALE_INTERVAL_S
                      if interval_s is None else interval_s)
        while True:
            try:
                self.evaluate()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # a transient signals/provider error (config mid-write,
                # manager race); the next tick re-reads everything
                debug_log(f"autoscale tick failed: {e!r}")
            await asyncio.sleep(interval_s)

    def status(self) -> dict:
        sig = self.signals()
        return {
            "policy": dataclasses.asdict(self.policy),
            "signals": dataclasses.asdict(sig),
            "pressure": round(
                sig.effective_work / max(1, sig.active_workers + 1), 3),
            "streaks": {"up": self._up_streak, "down": self._down_streak},
            "recent_decisions": [dataclasses.asdict(d)
                                 for d in self.decisions[-10:]],
            "workers": self.provider.list_workers(),
        }
