"""Cross-job work stealing: one master-side scheduler over every open
tile job.

The tile farm's pull queue was strictly per-job: a worker dispatched into
job A polls job A until it drains, then leaves — even while job B's queue
is deep and A's is empty. Under a mixed SDXL/USDU/video load that leaves
chips idle exactly when the fleet is busiest, and a newly arrived
(scale-up) worker can only join jobs dispatched *after* it came up.

This module generalizes the pull: a worker may ask for work from *any*
open job (``job_id="*"`` on ``POST /distributed/request_image``), and the
:class:`StealPolicy` decides which job's task it gets. The grant carries
the task's ``job_id`` so results route back to the right queue — tile
task ranges are defined on global tile indices and per-tile noise keys
fold the global index (tile_farm.py module docs), so *who* processes a
range is numerically invisible and stealing can never change output bits.

Determinism: the policy is a pure function of (ordered open-job state,
worker_id, seed). Jobs are ranked most-starved first — fewest distinct
workers currently assigned, then most pending work — with ties broken by
a seeded stable hash of (job seq, worker_id). Same seed + same event
order ⇒ the same assignment schedule, which is what lets the chaos suite
replay a scale event bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional, Sequence

from ...utils import constants


def _stable_tiebreak(seed: int, job_seq: int, worker_id: str) -> int:
    """Deterministic across processes and Python hash randomization."""
    digest = hashlib.sha256(
        f"{seed}:{job_seq}:{worker_id}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


@dataclasses.dataclass(frozen=True)
class JobView:
    """The slice of TileJob state the policy ranks on (built under the
    store lock; the policy itself never touches the store)."""

    job_id: str
    seq: int                    # creation order, process-unique
    pending: int                # unassigned tasks
    active_workers: int         # distinct non-master workers assigned


class StealPolicy:
    """Rank open jobs for a pulling worker; deterministic under a seed.

    Most-starved-first: a job nobody is serving beats a well-staffed one
    (a fresh scale-up worker lands where it helps most), deeper pending
    queues beat shallower ones, and the seeded hash settles exact ties
    without introducing a global round-robin cursor (which would make the
    schedule depend on unrelated jobs' history).
    """

    def __init__(self, seed: Optional[int] = None):
        if seed is None:
            seed = constants.STEAL_SEED.get()
        self.seed = seed

    def rank(self, jobs: Sequence[JobView],
             worker_id: str) -> list[JobView]:
        candidates = [j for j in jobs if j.pending > 0]
        return sorted(
            candidates,
            key=lambda j: (j.active_workers, -j.pending,
                           _stable_tiebreak(self.seed, j.seq, worker_id)))

    def pick(self, jobs: Sequence[JobView],
             worker_id: str) -> Optional[JobView]:
        ranked = self.rank(jobs, worker_id)
        return ranked[0] if ranked else None
