"""Elastic fleet subsystem (ROADMAP item 5, docs/elasticity.md).

PR 3's resilience layer handles workers *dying*; this package handles
workers *arriving and leaving on purpose* — the other half of running a
fleet that serves real traffic:

- :mod:`states` — the master-side lifecycle registry
  (active → draining → decommissioned) every failure-evidence site
  consults, so an intentional departure is never mistaken for a fault;
- :mod:`drain` — graceful drain/decommission: stop new work, let
  in-flight work finish or hand it back cleanly at a deadline, then
  stop the process;
- :mod:`autoscaler` — the telemetry-driven policy loop that sizes the
  fleet to offered work, with hysteresis, cooldowns, a min/max
  envelope, and a pluggable capacity provider (local processes in-repo,
  remote/tunnel via ``CDT_SCALE_PROVIDER``);
- :mod:`scheduler` — the deterministic cross-job steal policy behind
  ``JobStore.request_any_work`` (mixed workloads keep every chip busy;
  a scale-up worker immediately picks up pending work from *any* open
  job).

The :class:`ElasticManager` binds the pieces to one controller and is
what ``GET /distributed/elastic`` and the drain routes talk to.
"""

from __future__ import annotations

import asyncio
import importlib
from typing import Optional

from ...utils import constants
from ...utils.logging import log
from .autoscaler import (AutoscalePolicy, Autoscaler, FleetSignals,
                         LocalProcessProvider, ScaleProvider)
from .drain import DrainCoordinator
from .scheduler import JobView, StealPolicy
from .states import ACTIVE, DECOMMISSIONED, DRAIN, DRAINING, DrainRegistry

__all__ = [
    "ACTIVE", "DRAINING", "DECOMMISSIONED", "DRAIN", "DrainRegistry",
    "DrainCoordinator", "Autoscaler", "AutoscalePolicy", "FleetSignals",
    "ScaleProvider", "LocalProcessProvider", "StealPolicy", "JobView",
    "ElasticManager", "build_elastic", "autoscale_enabled",
]


def autoscale_enabled() -> bool:
    return constants.AUTOSCALE.get()


def _step_time_p50() -> "float | None":
    """Median sampler step time from the ``cdt_sampler_step_seconds``
    histogram (all pipelines merged) — the latency context the
    autoscaler reports alongside the depth pressure. None until the
    first sampled program runs (or telemetry is off)."""
    from ... import telemetry
    from ...telemetry.registry import REGISTRY

    if not telemetry.enabled():
        return None
    fam = REGISTRY.snapshot().get("cdt_sampler_step_seconds")
    series = (fam or {}).get("series") or []
    total = sum(s.get("count", 0) for s in series)
    if not total:
        return None
    # merge the per-pipeline cumulative buckets (bounds are shared)
    merged: dict[float, int] = {}
    for s in series:
        for le, cum in s.get("buckets", []):
            merged[le] = merged.get(le, 0) + cum
    target = total / 2
    for le in sorted(merged):
        if merged[le] >= target:
            return le
    return None


def _load_provider_factory():
    """``CDT_SCALE_PROVIDER="pkg.mod:factory"`` → callable(controller)
    building a custom :class:`ScaleProvider` (remote/tunnel capacity).
    A broken spec logs and falls back to the local provider — an env
    typo must not take autoscaling down with it."""
    spec = constants.SCALE_PROVIDER.get()
    if not spec:
        return None
    try:
        mod_name, _, attr = spec.partition(":")
        mod = importlib.import_module(mod_name)
        return getattr(mod, attr or "build_provider")
    except Exception as e:  # noqa: BLE001 — fall back, loudly
        log(f"elastic: bad CDT_SCALE_PROVIDER={spec!r} ({e}); "
            "using the local process provider")
        return None


class ElasticManager:
    """One controller's elasticity surface: drain coordination always,
    the autoscaler loop when ``CDT_AUTOSCALE=1``."""

    def __init__(self, controller):
        from ...workers.process_manager import get_worker_manager

        self.controller = controller
        self.registry = DRAIN
        manager = get_worker_manager(controller.config_path)

        def _preempt_for_drain():
            pre = getattr(controller, "preemption", None)
            return (pre.preempt_executing("drain")
                    if pre is not None else None)

        self.coordinator = DrainCoordinator(
            controller.store,
            process_stopper=manager.stop_worker,
            preempter=_preempt_for_drain)
        factory = _load_provider_factory()
        if factory is not None:
            self.provider: ScaleProvider = factory(controller)
        else:
            self.provider = LocalProcessProvider(
                controller.load_config, manager, self.coordinator)
        self.autoscaler = Autoscaler(self._signals, self.provider)
        self._task: Optional[asyncio.Task] = None

    # --- signals ------------------------------------------------------------

    def _signals(self) -> FleetSignals:
        c = self.controller
        fd = getattr(c, "frontdoor", None)
        # DENOISE-facing depth only: fd.depth() also counts the
        # encode/decode pools' host-side backlog (admission needs
        # that), but sizing the CHIP fleet on it would scale up denoise
        # capacity for a decode pile-up — the split FleetSignals carry
        # the stage backlogs separately (docs/stages.md)
        queue_depth = (fd.denoise_depth() if fd is not None
                       else c.queue.queue_remaining)
        stages = getattr(c, "stages", None)
        stage_depths = stages.depths() if stages is not None else {}
        # racy unlocked read of list lengths — fine for a gauge-grade
        # signal (the policy's hysteresis absorbs one stale tick)
        tile_depth = sum(len(j.pending)
                         for j in c.store.tile_jobs.values())
        workers = self.provider.list_workers()
        active = sum(1 for w in workers.values()
                     if w.get("running") and w.get("state") == ACTIVE)
        draining = sum(1 for w in workers.values()
                       if w.get("state") == DRAINING)
        decommissioned = sum(1 for w in workers.values()
                             if w.get("state") == DECOMMISSIONED)
        # content-cache hit rate (cluster/cache): a hot cache answers
        # queued work without a sampler program, so the policy sizes the
        # fleet on the cache-discounted effective work
        cache = getattr(c, "cache", None)
        hit_rate = cache.hit_rate() if cache is not None else 0.0
        return FleetSignals(queue_depth=queue_depth, tile_depth=tile_depth,
                            step_time_p50=_step_time_p50(),
                            active_workers=active,
                            draining_workers=draining,
                            decommissioned_workers=decommissioned,
                            cache_hit_rate=hit_rate,
                            encode_depth=stage_depths.get("encode", 0),
                            decode_depth=stage_depths.get("decode", 0))

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if autoscale_enabled() and (
                self._task is None or self._task.done()):
            log("elastic: autoscaler loop up (CDT_AUTOSCALE=1)")
            self._task = asyncio.ensure_future(self.autoscaler.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.coordinator.close()

    # --- status -------------------------------------------------------------

    def status(self) -> dict:
        return {
            "autoscale_enabled": autoscale_enabled(),
            "autoscaler": self.autoscaler.status(),
            "drain": self.coordinator.status(),
        }


def build_elastic(controller) -> ElasticManager:
    return ElasticManager(controller)
