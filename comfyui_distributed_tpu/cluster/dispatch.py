"""Host probing, selection, and prompt dispatch.

Parity: reference ``api/orchestration/dispatch.py`` — bounded-semaphore
probe fan-out (``:56-59,144-191``), delegate auto-disable when all hosts
are offline (``:184-190``), least-busy selection with round-robin among
idle (``:204-268``), HTTP prompt dispatch with validation-error propagation
(``:62-141``).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import time
from typing import Any, Optional, Sequence

import aiohttp

from .. import telemetry
from ..telemetry import metrics as _tm
from ..utils import constants
from ..utils.logging import debug_log, log, trace_info
from ..utils.network import build_host_url, get_client_session, probe_host
from .resilience import BREAKERS, CLOSED, RetryPolicy

# Global round-robin cursor for idle-host selection (reference keeps the
# same module-global index, dispatch.py:28)
_rr_counter = itertools.count()


async def select_active_hosts(
    hosts: Sequence[dict[str, Any]],
    probe_concurrency: int | None = None,
    trace_id: str | None = None,
) -> tuple[list[dict], list[dict]]:
    """Probe all enabled hosts concurrently (bounded) → (online, offline).

    Each probe result dict gains ``_probe`` with the health payload.

    Circuit-breaker gate: a host whose breaker is **open** is quarantined
    without being probed at all (its dict gains ``_breaker: "open"``) —
    a flapping worker costs one gauge read per job instead of a
    PROBE_TIMEOUT stall; after the recovery window one half-open trial
    probe decides re-admission. Probe outcomes feed the breakers.

    Drain gate (checked FIRST): a host that is draining/decommissioned
    (``cluster/elastic/states``) is *intentionally* unavailable — skipped
    without probing (``_drain`` marks the dict, ``outcome="draining"`` in
    telemetry) and, critically, without feeding its breaker: an asked-to-
    leave worker must never accumulate failure evidence on the way out.
    """
    from .elastic.states import DRAIN

    sem = asyncio.Semaphore(probe_concurrency or constants.WORKER_PROBE_CONCURRENCY)

    async def probe_one(host: dict) -> "tuple[dict, Optional[dict], str]":
        wid = str(host.get("id"))
        if DRAIN.is_leaving(wid):
            return host, None, "draining"       # leaving, not broken
        if not BREAKERS.allow(wid):
            return host, None, "quarantined"    # quarantined, not probed
        health = None
        try:
            async with sem:
                health = await probe_host(host)
        except asyncio.CancelledError:
            # a consumed half-open trial slot must be released (allow()
            # never re-admits a stuck half_open breaker, so a leaked slot
            # quarantines the worker until process restart) — but an
            # aborted orchestration is not failure evidence against a
            # closed breaker on a healthy host
            if BREAKERS.state(wid) != CLOSED:
                BREAKERS.record(wid, False)
            raise
        except Exception as e:  # noqa: BLE001 — one bad host must not
            # kill the whole fan-out; it just counts as offline
            debug_log(f"probe {wid} raised unexpectedly: {e!r}")
        BREAKERS.record(wid, health is not None)
        return host, health, ""

    results = await asyncio.gather(*(probe_one(h) for h in hosts))
    online, offline = [], []
    quarantined = draining = 0
    for host, health, skipped in results:
        if skipped == "quarantined":
            quarantined += 1
            offline.append({**host, "_breaker": "open"})
        elif skipped == "draining":
            draining += 1
            offline.append({**host, "_drain": DRAIN.state(str(host.get("id")))})
        elif health is None:
            offline.append(host)
        else:
            online.append({**host, "_probe": health})
    if telemetry.enabled() and results:
        _tm.WORKER_PROBES.labels(outcome="online").inc(len(online))
        _tm.WORKER_PROBES.labels(outcome="offline").inc(
            len(offline) - quarantined - draining)
        if quarantined:
            _tm.WORKER_PROBES.labels(outcome="quarantined").inc(quarantined)
        if draining:
            _tm.WORKER_PROBES.labels(outcome="draining").inc(draining)
    trace_info(trace_id, f"probe: {len(online)} online, "
                         f"{len(offline) - quarantined - draining} offline, "
                         f"{quarantined} quarantined (breaker open), "
                         f"{draining} draining")
    return online, offline


def queue_depth(host: dict) -> int:
    return int((host.get("_probe") or {}).get("queue_remaining", 0))


def is_hot(host: dict) -> bool:
    """A host mid-warmup ("warming") would stall the job behind the rest
    of its catalog compile pass; everything else — "ready", "cold"
    (warmup not configured), or a pre-warmup peer without the field —
    keeps the old behavior."""
    return (host.get("_probe") or {}).get("warmup") != "warming"


def select_least_busy_host(online_hosts: Sequence[dict]) -> Optional[dict]:
    """Round-robin among idle hosts; else min queue depth (reference
    ``select_least_busy_worker``, ``dispatch.py:204-268``). Hot hosts
    (AOT-warmed / not mid-warmup) are preferred at every tier — a
    rolling restart drains traffic toward workers that won't pay a
    cold compile, falling back to warming hosts only when they are all
    that's online."""
    if not online_hosts:
        return None
    idle = [h for h in online_hosts if queue_depth(h) == 0]
    if idle:
        hot = [h for h in idle if is_hot(h)] or idle
        return hot[next(_rr_counter) % len(hot)]
    hot = [h for h in online_hosts if is_hot(h)] or list(online_hosts)
    return min(hot, key=queue_depth)


async def dispatch_prompt_ws(
    host: dict[str, Any],
    prompt: dict,
    client_id: str = "",
    extra: dict | None = None,
    trace_id: str | None = None,
) -> dict:
    """Dispatch over the WebSocket channel: connect to the host's
    ``/distributed/worker_ws``, send ``dispatch_prompt``, await the
    ``dispatch_ack`` (reference ``_dispatch_via_websocket``,
    ``dispatch.py:62-95``). Validation errors in the ack raise
    ``WorkerError`` exactly like the HTTP path."""
    from ..utils.exceptions import WorkerError

    url = build_host_url(host, "/distributed/worker_ws")
    session = get_client_session()
    with telemetry.span("dispatch.ws", trace_id=trace_id,
                        host=str(host.get("id"))):
        t0 = time.perf_counter()
        outcome = "error"
        try:
            try:
                ws_ctx = session.ws_connect(
                    url, headers=telemetry.trace_headers() or None)
                ws = await ws_ctx.__aenter__()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                # connection never opened — the prompt cannot have been
                # delivered, so the caller may safely retry over HTTP
                err = WorkerError(
                    f"ws dispatch to {host.get('id')} unreachable: {e}",
                    worker_id=host.get("id"))
                err.ws_undelivered = True
                raise err from e
            try:
                # serialize once: measured AND sent as the same string
                payload_s = json.dumps({
                    "type": "dispatch_prompt",
                    "prompt": prompt,
                    "client_id": client_id,
                    **(extra or {}),
                })
                if telemetry.enabled():
                    _tm.DISPATCH_PAYLOAD_BYTES.labels(
                        transport="ws").observe(
                            len(payload_s.encode()))
                await ws.send_str(payload_s)
                msg = await ws.receive(timeout=constants.DISPATCH_TIMEOUT)
                if msg.type != aiohttp.WSMsgType.TEXT:
                    # the send may have been delivered even though the ack
                    # never arrived — retrying over HTTP could
                    # double-enqueue; fail hard
                    raise WorkerError(
                        f"ws dispatch to {host.get('id')}: connection closed "
                        f"before ack ({msg.type})", worker_id=host.get("id"))
                ack = json.loads(msg.data)
                if ack.get("type") != "dispatch_ack" or not ack.get("ok", False):
                    err = WorkerError(
                        f"ws dispatch to {host.get('id')} rejected: "
                        f"{ack.get('node_errors') or ack.get('error')}",
                        worker_id=host.get("id"))
                    # a nack is the worker healthily validating; only
                    # transport failures count against its breaker
                    err.client_rejected = True
                    raise err
                trace_info(trace_id, f"dispatched to {host.get('id')} (ws)")
                outcome = "ok"
                return ack
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                raise WorkerError(
                    f"ws dispatch to {host.get('id')} failed after connect: {e}",
                    worker_id=host.get("id"),
                ) from e
            finally:
                await ws_ctx.__aexit__(None, None, None)
        finally:
            if telemetry.enabled():
                _tm.DISPATCH_SECONDS.labels(
                    transport="ws", outcome=outcome).observe(
                        time.perf_counter() - t0)


async def dispatch_prompt(
    host: dict[str, Any],
    prompt: dict,
    client_id: str = "",
    extra: dict | None = None,
    trace_id: str | None = None,
    via_ws: bool = False,
) -> dict:
    """POST the prompt to a host's queue endpoint; returns its response.

    Raises ``WorkerError`` with the remote validation errors on 4xx
    (reference propagates node_errors the same way, ``dispatch.py:98-141``).
    With ``via_ws`` (settings.websocket_orchestration) the WebSocket channel
    is tried first; transport errors fall back to HTTP so enabling the
    setting can't strand a cluster whose peers lack the WS route.

    Resilience: the HTTP POST retries through the unified ``RetryPolicy``
    — but **only** when the connection never opened
    (``ClientConnectorError``: the prompt provably never left this host).
    A timeout or mid-request error after connect is ambiguous — the
    worker may already hold the prompt, and a re-send would double-run
    the job — so it fails fast, exactly like the lost-WS-ack case.
    The final outcome feeds the host's circuit breaker.
    """
    from ..utils.exceptions import WorkerError

    wid = str(host.get("id"))
    try:
        result = await _dispatch_prompt_once(host, prompt, client_id, extra,
                                             trace_id, via_ws)
    except WorkerError as e:
        # a validation rejection (HTTP 4xx / WS nack) is the worker
        # HEALTHILY answering a bad prompt — evidence FOR the host, not
        # against it; a flood of invalid workflows must not open the
        # breaker on every online worker
        BREAKERS.record(wid, getattr(e, "client_rejected", False))
        raise
    BREAKERS.record(wid, True)
    return result


def _never_sent(e: BaseException) -> bool:
    """Retry predicate for prompt dispatch: only failures that prove the
    request never reached the peer are idempotent-safe to re-send."""
    import aiohttp as _aiohttp

    if isinstance(e, _aiohttp.ClientConnectorError):
        return True
    cause = getattr(e, "__cause__", None)
    return isinstance(cause, _aiohttp.ClientConnectorError)


async def _dispatch_prompt_once(
    host: dict[str, Any],
    prompt: dict,
    client_id: str,
    extra: dict | None,
    trace_id: str | None,
    via_ws: bool,
) -> dict:
    from ..utils.exceptions import WorkerError

    if via_ws:
        try:
            return await dispatch_prompt_ws(host, prompt, client_id, extra,
                                            trace_id)
        except WorkerError as e:
            if not getattr(e, "ws_undelivered", False):
                # the prompt may already sit in the worker's queue (lost
                # ack ≠ lost dispatch) — an HTTP retry would double-run it
                raise
            debug_log(f"ws connect failed ({e}); falling back to HTTP")

    url = build_host_url(host, "/prompt")
    payload = {"prompt": prompt, "client_id": client_id, **(extra or {})}
    session = get_client_session()
    # the dispatch span's id rides the X-CDT-Trace header, so the worker's
    # execution span parents onto THIS span and the job stitches into one
    # cross-host timeline (docs/telemetry.md)
    with telemetry.span("dispatch", trace_id=trace_id,
                        host=str(host.get("id"))):
        # serialize ONCE: the pre-encoded body both feeds the payload
        # histogram and goes on the wire (aiohttp would otherwise
        # re-serialize the same dict)
        body_bytes = json.dumps(payload).encode()
        if telemetry.enabled():
            _tm.DISPATCH_PAYLOAD_BYTES.labels(transport="http").observe(
                len(body_bytes))

        async def attempt() -> dict:
            t0 = time.perf_counter()
            outcome = "error"
            try:
                async with session.post(
                    url, data=body_bytes,
                    timeout=aiohttp.ClientTimeout(
                        total=constants.DISPATCH_TIMEOUT),
                    headers={"Content-Type": "application/json",
                             **telemetry.trace_headers()},
                ) as resp:
                    body = await resp.json(content_type=None)
                    if resp.status >= 400:
                        err = WorkerError(
                            f"dispatch to {host.get('id')} failed "
                            f"({resp.status}): {body}",
                            worker_id=host.get("id"),
                        )
                        # 4xx = the host is up and rejecting the prompt;
                        # 5xx = the host itself is failing
                        err.client_rejected = resp.status < 500
                        raise err
                    trace_info(trace_id, f"dispatched to {host.get('id')}")
                    outcome = "ok"
                    return body
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                err = WorkerError(
                    f"dispatch to {host.get('id')} unreachable: {e}",
                    worker_id=host.get("id"),
                )
                # connection-refused/DNS failures are provably un-sent
                # (idempotency-safe); anything after connect is not
                err.retry_safe = _never_sent(e)
                raise err from e
            finally:
                if telemetry.enabled():
                    _tm.DISPATCH_SECONDS.labels(
                        transport="http", outcome=outcome).observe(
                            time.perf_counter() - t0)

        policy = RetryPolicy(max_attempts=constants.DISPATCH_MAX_RETRIES,
                             base=constants.SEND_BACKOFF_BASE,
                             cap=constants.RETRY_CAP_S)
        return await policy.run(
            attempt, op="dispatch",
            retryable=lambda e: getattr(e, "retry_safe", False) is True)
