"""Host-side sampling-progress tracker: step counts + live latent previews.

Consumes the ``jax.debug.callback`` events emitted by
``diffusion/progress.wrap_denoiser`` and serves them to the control plane
(``/distributed/progress/{prompt_id}``, ``/distributed/preview/{prompt_id}``)
— the standalone equivalent of the per-step progress bar + live preview the
reference inherits from ComfyUI's executor hooks.

Events are unordered (async host effects): ``sigma`` — strictly decreasing
over the ladder — orders previews; the step *count* is simply the number of
events seen from shard 0 (order-independent). Previews are kept per shard
so a dp fan-out can show every participant's image forming.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..diffusion import progress as _events
from ..utils.image import encode_png

# Approximate linear latent→RGB maps for previews (rows = latent channels,
# cols = RGB). These are the community-standard preview approximations for
# 4-channel VP latents; they need only be *recognizable*, not exact — the
# real decode happens in the VAE at the end of the run.
_RGB_4CH = np.array(
    [[0.298, 0.207, 0.208],
     [0.187, 0.286, 0.173],
     [-0.158, 0.189, 0.264],
     [-0.184, -0.271, -0.473]], dtype=np.float32)


def latent_to_rgb(latent: np.ndarray) -> np.ndarray:
    """[H,W,C] latent → [H,W,3] float image in [0,1] (preview quality).

    4-channel latents go through the standard linear approximation;
    anything else (16-ch FLUX/WAN, video frames) takes the first three
    channels. Output is mean/std normalized so previews stay visible at
    any sigma scale."""
    lat = np.asarray(latent, dtype=np.float32)
    if lat.ndim == 4:          # video [F,H,W,C] → middle frame
        lat = lat[lat.shape[0] // 2]
    if lat.shape[-1] == _RGB_4CH.shape[0]:
        rgb = lat @ _RGB_4CH
    else:
        rgb = lat[..., :3]
    std = float(rgb.std()) or 1.0
    rgb = (rgb - float(rgb.mean())) / (3.0 * std) + 0.5
    return np.clip(rgb, 0.0, 1.0)


class _Job:
    __slots__ = ("prompt_id", "total", "calls_seen", "previews",
                 "preview_sigmas", "started", "updated", "done", "failed")

    def __init__(self, prompt_id: str, total: int):
        self.prompt_id = prompt_id
        self.total = max(1, int(total))
        self.calls_seen = 0
        self.previews: dict[int, np.ndarray] = {}
        self.preview_sigmas: dict[int, float] = {}
        self.started = time.time()
        self.updated = self.started
        self.done = False
        self.failed = False


class ProgressTracker:
    """Registry of in-flight sampling runs, keyed by token (traced into
    the compiled program) and by prompt id (control-plane handle)."""

    def __init__(self, keep: int = 16):
        self._keep = keep
        self._jobs: "OrderedDict[int, _Job]" = OrderedDict()
        self._by_prompt: dict[str, int] = {}
        self._lock = threading.Lock()
        # Events fan out to every registered sink; tokens are allocated
        # from the process-global counter (diffusion/progress.next_token)
        # so this tracker's job table simply misses on tokens issued by a
        # coexisting tracker (embedded master+worker, test fixtures) —
        # no stealing, no warning (VERDICT r3 weak #4).
        self._sink_handle = _events.add_sink(self._on_event)

    def close(self) -> None:
        """Detach this tracker's sink from the event registry."""
        _events.remove_sink(self._sink_handle)

    # --- producer side (node layer) ------------------------------------

    def start(self, prompt_id: str, total_calls: int) -> int:
        """Allocate a token for a run about to execute; returns the int32
        scalar to thread into the compiled program."""
        token = _events.next_token()
        with self._lock:
            job = _Job(prompt_id, total_calls)
            self._jobs[token] = job
            self._by_prompt[prompt_id] = token
            while len(self._jobs) > self._keep:
                old_token, old = self._jobs.popitem(last=False)
                # a newer token may have reused the same prompt id (one
                # prompt, many sampler nodes) — only drop the mapping if
                # it still points at the evicted token
                if self._by_prompt.get(old.prompt_id) == old_token:
                    self._by_prompt.pop(old.prompt_id, None)
        return token

    def finish(self, prompt_id: str, failed: bool = False) -> None:
        """Mark a run finished. ``failed=True`` freezes progress where it
        stopped instead of fabricating 100% — an OOM at step 5/30 must not
        render as "done (30 steps)"."""
        with self._lock:
            token = self._by_prompt.get(prompt_id)
            job = self._jobs.get(token) if token is not None else None
            if job is not None:
                job.done = True
                job.failed = failed
                if not failed:
                    job.calls_seen = job.total
                job.updated = time.time()

    def report(self, token: int, sigma: float, x0,
               shard: int = 0) -> None:
        """Host-side progress report — the offloaded samplers run their
        ladder as a Python loop (``diffusion/offload.sample_euler_py``),
        so they feed the SAME per-step progress/preview machinery the
        compiled paths drive via ``jax.debug.callback``."""
        self._on_event(token, shard, float(sigma), np.asarray(x0))

    # --- event sink (jax.debug.callback, runtime threads) ---------------

    def _on_event(self, token: int, shard: int, sigma: float,
                  x0: np.ndarray) -> None:
        with self._lock:
            job = self._jobs.get(token)
            if job is None or job.done:
                return
            job.updated = time.time()
            if shard == 0:
                job.calls_seen += 1
            prev = job.preview_sigmas.get(shard)
            if prev is None or sigma <= prev:
                job.preview_sigmas[shard] = sigma
                job.previews[shard] = x0[0] if x0.ndim >= 4 else x0

    # --- consumer side (routes / dashboard) -----------------------------

    def snapshot(self, prompt_id: str) -> Optional[dict]:
        with self._lock:
            token = self._by_prompt.get(prompt_id)
            job = self._jobs.get(token) if token is not None else None
            if job is None:
                return None
            frac = min(1.0, job.calls_seen / job.total)
            return {
                "prompt_id": prompt_id,
                "step": job.calls_seen,
                "total": job.total,
                "fraction": round(frac, 4),
                "done": job.done,
                "failed": job.failed,
                "shards_reporting": len(job.previews),
                "updated_s_ago": round(time.time() - job.updated, 2),
            }

    def preview_png(self, prompt_id: str, shard: int = 0) -> Optional[bytes]:
        """Latest preview as PNG. Image latents render as one frame; a
        VIDEO latent ([F,h,w,c]) renders as a horizontal strip of up to
        four evenly-spaced frames — the motion arc at a glance, which a
        single middle frame can't show (the dashboard polls this for the
        t2v frame strip)."""
        with self._lock:
            token = self._by_prompt.get(prompt_id)
            job = self._jobs.get(token) if token is not None else None
            lat = None if job is None else job.previews.get(shard)
            if lat is None:
                return None
            lat = np.array(lat)
        if lat.ndim == 4 and lat.shape[0] > 1:
            idxs = np.unique(np.linspace(0, lat.shape[0] - 1,
                                         min(4, lat.shape[0])).astype(int))
            # tile the LATENT first and normalize once: per-frame
            # normalization would flatten real brightness changes across
            # the clip and leave step seams between tiles
            strip = np.concatenate([lat[i] for i in idxs], axis=1)
            return encode_png(latent_to_rgb(strip))
        return encode_png(latent_to_rgb(lat))
