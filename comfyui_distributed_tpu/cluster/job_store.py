"""Lock-guarded job registry.

Parity: reference ``upscale/job_store.py`` (asyncio-locked dicts attached to
the server) + collector queue management (``api/queue_orchestration.py:42-48``,
``nodes/collector.py:321-327``). One store instance lives on the controller;
every mutation happens under the store lock, mirroring the reference's
race-avoidance discipline (SURVEY §5.2).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Optional, Sequence

from ..telemetry import enabled as _tm_enabled, metrics as _tm
from ..utils import constants
from ..utils.exceptions import JobQueueError
from ..utils.logging import debug_log
from .job_models import CollectorJob, TileJob, TileTask


class JobStore:
    # finished-job summaries retained for status queries (dead-letter
    # forensics after the job completed); bounded FIFO
    MAX_FINISHED = 64

    def __init__(self):
        self.lock = asyncio.Lock()
        self.collector_jobs: dict[str, CollectorJob] = {}
        self.tile_jobs: dict[str, TileJob] = {}
        self.finished: dict[str, dict] = {}
        self._job_seq = 0

    def _record_tiles(self, event: str, n: int = 1) -> None:
        """Telemetry (call under ``self.lock``): lifecycle counter + the
        cross-job pending-depth gauge."""
        if not _tm_enabled() or n <= 0:
            return
        _tm.TILE_EVENTS.labels(event=event).inc(n)
        _tm.TILE_QUEUE_DEPTH.set(
            sum(len(j.pending) for j in self.tile_jobs.values()))

    # --- collector jobs ----------------------------------------------------

    async def prepare_collector_job(
        self, job_id: str, expected_workers: tuple[str, ...] = ()
    ) -> CollectorJob:
        """Pre-create the result queue BEFORE any compute is dispatched —
        closes the init race the reference closes the same way
        (``nodes/collector.py:321-327``)."""
        async with self.lock:
            job = self.collector_jobs.get(job_id)
            if job is None:
                job = CollectorJob(job_id, tuple(expected_workers))
                self.collector_jobs[job_id] = job
            elif expected_workers:
                job.expected_workers = tuple(expected_workers)
            return job

    async def put_collector_result(
        self, job_id: str, envelope: dict[str, Any],
        grace: float | None = None,
    ) -> None:
        """Enqueue a worker envelope; retries while the job is not yet
        initialized (reference ``api/job_routes.py:314-333`` 10 s grace)."""
        grace = constants.JOB_INIT_GRACE if grace is None else grace
        deadline = time.monotonic() + grace
        while True:
            async with self.lock:
                job = self.collector_jobs.get(job_id)
            if job is not None:
                await job.results.put(envelope)
                if envelope.get("is_last"):
                    job.completed_workers[envelope.get("worker_id", "")] = True
                return
            if time.monotonic() >= deadline:
                raise JobQueueError(f"collector job {job_id!r} never initialized",
                                    job_id=job_id)
            await asyncio.sleep(0.1)

    async def get_collector_job(self, job_id: str) -> Optional[CollectorJob]:
        async with self.lock:
            return self.collector_jobs.get(job_id)

    # --- tile jobs ---------------------------------------------------------

    async def init_tile_job(
        self, job_id: str, total_tasks: int, mode: str = "static",
        chunk: int = 1,
    ) -> TileJob:
        """Seed the pending queue with shard-range tasks (reference
        ``init_static_job_batched``/``init_dynamic_job``,
        ``upscale/job_store.py:34-114``)."""
        async with self.lock:
            if job_id in self.tile_jobs:
                raise JobQueueError(f"tile job {job_id!r} already initialized",
                                    job_id=job_id)
            tasks = []
            tid = 0
            for start in range(0, total_tasks, chunk):
                tasks.append(TileTask(tid, start, min(start + chunk, total_tasks)))
                tid += 1
            self._job_seq += 1
            job = TileJob(job_id, total_tasks=len(tasks), mode=mode,
                          seq=self._job_seq,
                          tasks={t.task_id: t for t in tasks}, pending=list(tasks))
            self.tile_jobs[job_id] = job
            self._record_tiles("seeded", len(tasks))
            return job

    async def request_work(self, job_id: str, worker_id: str) -> Optional[dict]:
        """Pull-based assignment (reference ``/distributed/request_image``,
        ``api/usdu_routes.py:168-215``): pop a pending task, record the
        assignment + heartbeat; None when drained."""
        async with self.lock:
            job = self.tile_jobs.get(job_id)
            if job is None:
                return None
            job.heartbeat(worker_id)
            return self._grant_locked(job, worker_id)

    def _grant_locked(self, job, worker_id: str) -> Optional[dict]:
        """Pop + assign one pending task (call under ``self.lock``)."""
        if not job.pending:
            return None
        task = job.pending.pop(0)
        job.assigned[task.task_id] = worker_id
        self._record_tiles("assigned")
        return {**task.as_dict(), "job_id": job.job_id,
                "estimated_remaining": len(job.pending)}

    async def request_any_work(self, worker_id: str,
                               policy=None,
                               exclude: "Sequence[str]" = ()) -> Optional[dict]:
        """Cross-job pull (``job_id="*"``): grant a task from whichever
        open tile job the steal policy ranks first — a worker that
        drained its own job (or just arrived via scale-up) keeps every
        chip busy on the rest of the mixed load. The grant carries the
        task's ``job_id`` so the result routes home; per-tile noise keys
        fold the global tile index, so stealing is numerically invisible
        (cluster/elastic/scheduler.py).

        ``exclude`` is the puller's can't-serve list (jobs whose
        weights/workflow it lacks): without it, a top-ranked unservable
        job would ping-pong its grant (grant → handback → re-grant)
        and starve every servable job ranked below it."""
        from .elastic.scheduler import JobView, StealPolicy

        policy = policy or StealPolicy()
        excluded = set(exclude)
        async with self.lock:
            views = []
            for jid, job in self.tile_jobs.items():
                if jid in excluded:
                    continue
                owners = {w for w in job.assigned.values() if w != "master"}
                views.append(JobView(job_id=jid, seq=job.seq,
                                     pending=len(job.pending),
                                     active_workers=len(owners)))
            choice = policy.pick(views, worker_id)
            if choice is None:
                return None
            job = self.tile_jobs[choice.job_id]
            job.heartbeat(worker_id)
            return self._grant_locked(job, worker_id)

    async def submit_result(
        self, job_id: str, worker_id: str, task_id: int, payload: Any,
    ) -> bool:
        """Record a completed task; idempotent for duplicate submissions
        (a timed-out-then-revived worker may double-send; the reference's
        batched-completeness check covers the same case,
        ``upscale/job_timeout.py:111-150``)."""
        async with self.lock:
            job = self.tile_jobs.get(job_id)
            if job is None:
                raise JobQueueError(f"unknown tile job {job_id!r}", job_id=job_id)
            job.heartbeat(worker_id)
            if task_id in job.completed:
                debug_log(f"duplicate result for {job_id}:{task_id} ignored")
                return False
            if task_id in job.dead_letter:
                # a presumed-poison tile finished after all (worker revived
                # past its eviction) — a real result always wins
                job.dead_letter.pop(task_id)
                debug_log(f"dead-lettered task {job_id}:{task_id} "
                          "resurrected by late result")
            job.completed[task_id] = payload
            job.assigned.pop(task_id, None)
            self._record_tiles("completed")
        await job.results.put((task_id, payload))
        return True

    async def restore_completed(self, job_id: str, task_id: int,
                                payload: Any) -> bool:
        """Pre-mark a task complete from a journal (crash resume): unlike
        ``submit_result`` this also removes it from the pending queue so
        nobody reprocesses it, and skips the results queue."""
        async with self.lock:
            job = self.tile_jobs.get(job_id)
            if job is None:
                raise JobQueueError(f"unknown tile job {job_id!r}", job_id=job_id)
            if task_id not in job.tasks or task_id in job.completed:
                return False
            job.completed[task_id] = payload
            job.pending = [t for t in job.pending if t.task_id != task_id]
            job.assigned.pop(task_id, None)
            self._record_tiles("restored")
            return True

    async def heartbeat(self, job_id: str, worker_id: str) -> bool:
        async with self.lock:
            job = self.tile_jobs.get(job_id)
            if job is None:
                return False
            job.heartbeat(worker_id)
            return True

    async def job_status(self, job_id: str) -> dict:
        """Job-ready poll (reference ``/distributed/job_status``,
        ``api/usdu_routes.py:218-228``)."""
        async with self.lock:
            tile = self.tile_jobs.get(job_id)
            if tile is not None:
                return {"exists": True, "kind": "tile", "mode": tile.mode,
                        "pending": len(tile.pending),
                        "completed": len(tile.completed),
                        "total": tile.total_tasks,
                        "dead_letter": sorted(tile.dead_letter.values(),
                                              key=lambda d: d["task_id"])}
            if job_id in self.collector_jobs:
                return {"exists": True, "kind": "collector"}
            done = self.finished.get(job_id)
            if done is not None:
                # job already cleaned up: dead-letter forensics survive
                # (``exists`` stays False so worker ready-polls never
                # mistake a finished job for a live queue)
                return {"exists": False, "finished": True, **done}
            return {"exists": False}

    def _dead_letter_locked(self, job, task_id: int, worker_id: str,
                            reason: str) -> None:
        """Move a task to the job's dead-letter list (call under
        ``self.lock``). Terminal for completion accounting — a poison
        tile must bound the damage instead of hanging the job."""
        job.dead_letter[task_id] = {
            "task_id": task_id,
            "worker_id": worker_id,
            "reason": reason,
            "requeues": job.requeue_counts.get(task_id, 0),
        }
        job.assigned.pop(task_id, None)
        job.pending = [t for t in job.pending if t.task_id != task_id]
        self._record_tiles("dead_letter")

    async def requeue_worker_tasks(
        self, job_id: str, worker_id: str,
        max_requeues: int | None = None,
        count_requeue: bool = True,
    ) -> list[int]:
        """Requeue the incomplete tasks of a (presumed dead) worker and
        evict it (reference ``_check_and_requeue_timed_out_workers`` apply
        phase, ``upscale/job_timeout.py:111-150``).

        Requeues are **bounded**: a task already requeued ``max_requeues``
        times (default ``constants.MAX_TILE_REQUEUES``) moves to the job's
        dead-letter list instead — a tile that deterministically kills its
        host must not cycle through the fleet forever.

        ``count_requeue=False`` is the intentional-departure variant
        (drain handback, drain-then-silence eviction): the task goes back
        to the queue but the hop does NOT count toward the poison bound —
        a tile is only suspect when its host *failed*, not when its host
        was asked to leave.
        """
        if max_requeues is None:
            max_requeues = constants.MAX_TILE_REQUEUES
        async with self.lock:
            job = self.tile_jobs.get(job_id)
            if job is None:
                return []
            requeued = []
            poisoned = []
            for task_id, owner in list(job.assigned.items()):
                if owner != worker_id or task_id in job.completed:
                    continue
                del job.assigned[task_id]
                if not count_requeue:
                    requeued.append(task_id)
                    continue
                count = job.requeue_counts.get(task_id, 0) + 1
                job.requeue_counts[task_id] = count
                if count > max_requeues:
                    poisoned.append(task_id)
                    self._dead_letter_locked(
                        job, task_id, worker_id,
                        f"exceeded max_requeues={max_requeues} "
                        f"(last owner {worker_id})")
                    continue
                requeued.append(task_id)
            if requeued:
                # push to the FRONT so recovered work is picked up first
                job.pending[:0] = [job.tasks[tid] for tid in requeued]
                self._record_tiles(
                    "requeued" if count_requeue else "handed_back",
                    len(requeued))
            if poisoned:
                debug_log(f"tile job {job_id}: dead-lettered poison tasks "
                          f"{poisoned} from {worker_id}")
            job.worker_status.pop(worker_id, None)
            return requeued

    async def worker_held_tasks(self, worker_id: str) -> dict[str, list[int]]:
        """{job_id: [task ids]} the worker is currently assigned and has
        not completed, across every open tile job (drain bookkeeping)."""
        async with self.lock:
            held: dict[str, list[int]] = {}
            for jid, job in self.tile_jobs.items():
                tids = sorted(tid for tid, owner in job.assigned.items()
                              if owner == worker_id
                              and tid not in job.completed)
                if tids:
                    held[jid] = tids
            return held

    async def handback_worker_tasks(self, worker_id: str
                                    ) -> dict[str, list[int]]:
        """Drain handback: return every task the departing worker still
        holds (across all open jobs) to the front of its job's queue,
        WITHOUT counting against the poison bound and WITHOUT touching
        the worker's breaker. Idempotent with heartbeat eviction — both
        paths remove from ``assigned`` under the store lock, so a tile
        can be requeued by at most one of them."""
        held = await self.worker_held_tasks(worker_id)
        out: dict[str, list[int]] = {}
        total = 0
        for jid in held:
            requeued = await self.requeue_worker_tasks(
                jid, worker_id, count_requeue=False)
            if requeued:
                out[jid] = requeued
                total += len(requeued)
        if total and _tm_enabled():
            _tm.DRAIN_HANDBACKS.inc(total)
        return out

    async def record_task_failure(
        self, job_id: str, worker_id: str, task_id: int, reason: str,
        max_requeues: int | None = None,
    ) -> bool:
        """A processing attempt raised (master-side poison tile): requeue
        the task, or dead-letter it past the bound. Returns True while the
        task is still live (requeued), False once dead-lettered."""
        if max_requeues is None:
            max_requeues = constants.MAX_TILE_REQUEUES
        async with self.lock:
            job = self.tile_jobs.get(job_id)
            if job is None:
                return False
            if task_id in job.completed or task_id in job.dead_letter:
                return False
            count = job.requeue_counts.get(task_id, 0) + 1
            job.requeue_counts[task_id] = count
            job.assigned.pop(task_id, None)
            if count > max_requeues:
                self._dead_letter_locked(job, task_id, worker_id, reason)
                return False
            if all(t.task_id != task_id for t in job.pending):
                job.pending.append(job.tasks[task_id])
                self._record_tiles("requeued")
            return True

    async def cleanup_job(self, job_id: str) -> None:
        async with self.lock:
            self.collector_jobs.pop(job_id, None)
            tile = self.tile_jobs.pop(job_id, None)
            if tile is not None:
                self.finished[job_id] = {
                    "kind": "tile",
                    "completed": len(tile.completed),
                    "total": tile.total_tasks,
                    "dead_letter": sorted(tile.dead_letter.values(),
                                          key=lambda d: d["task_id"]),
                }
                while len(self.finished) > self.MAX_FINISHED:
                    self.finished.pop(next(iter(self.finished)))
            if _tm_enabled():
                _tm.TILE_QUEUE_DEPTH.set(
                    sum(len(j.pending) for j in self.tile_jobs.values()))

    async def prune_stale(self, max_age: float = 3600.0) -> list[str]:
        """Drop jobs older than ``max_age`` (the reference cleans up on
        collection end, ``upscale/job_store.py:174``; this adds a safety
        net for abandoned jobs)."""
        now = time.monotonic()
        dropped = []
        async with self.lock:
            for d in (self.collector_jobs, self.tile_jobs):
                for jid in [j for j, job in d.items()
                            if now - job.created_at > max_age]:
                    del d[jid]
                    dropped.append(jid)
        return dropped
