// Pure transforms of the /distributed/metrics.json snapshot for the
// dashboard's telemetry panel (node:test-covered in
// tests/telemetryLogic.test.mjs; main.js only renders the rows).
//
// The snapshot shape is telemetry/export.py's render_json():
//   { format: "cdt.metrics.v1", metrics: { name: {type, series: [...]}} }
// counters/gauges carry {labels, value}; histograms carry
// {labels, buckets: [[le, cumulative], ...], sum, count}.

function matches(labels, filter) {
  if (!filter) return true;
  return Object.entries(filter).every(([k, v]) => labels[k] === v);
}

// Sum a counter/gauge family's series, optionally filtered by labels.
export function seriesSum(metrics, name, labelFilter = null) {
  const fam = metrics && metrics[name];
  let total = 0;
  for (const s of (fam && fam.series) || []) {
    if (matches(s.labels || {}, labelFilter)) total += s.value || 0;
  }
  return total;
}

// Per-label-value totals of a counter family: { labelValue: sum }.
export function countsByLabel(metrics, name, label) {
  const fam = metrics && metrics[name];
  const out = {};
  for (const s of (fam && fam.series) || []) {
    const key = (s.labels || {})[label] ?? "";
    out[key] = (out[key] || 0) + (s.value || 0);
  }
  return out;
}

// Merge a histogram family's (optionally filtered) series into one
// {buckets, sum, count} — bucket bounds are fixed per family, so the
// cumulative counts add bucket-for-bucket.
export function mergeHistogram(metrics, name, labelFilter = null) {
  const fam = metrics && metrics[name];
  let merged = null;
  for (const s of (fam && fam.series) || []) {
    if (!matches(s.labels || {}, labelFilter)) continue;
    if (!merged) {
      merged = {
        buckets: s.buckets.map(([le, c]) => [le, c]),
        sum: s.sum,
        count: s.count,
      };
    } else {
      s.buckets.forEach(([, c], i) => { merged.buckets[i][1] += c; });
      merged.sum += s.sum;
      merged.count += s.count;
    }
  }
  return merged;
}

// q ∈ (0,1] → upper-bound estimate from cumulative buckets; null when the
// histogram is empty, Infinity when the quantile lands past the last
// finite bucket.
export function histQuantile(hist, q) {
  if (!hist || !hist.count) return null;
  const target = q * hist.count;
  for (const [le, cum] of hist.buckets) {
    if (cum >= target) return le;
  }
  return Infinity;
}

export function fmtSeconds(s) {
  if (s === null || s === undefined) return "—";
  if (s === Infinity) return ">max";
  if (s < 0.001) return `${(s * 1e6).toFixed(0)}µs`;
  if (s < 1) return `${(s * 1e3).toFixed(1)}ms`;
  return `${s.toFixed(2)}s`;
}

function fmtCounts(byLabel) {
  const parts = Object.entries(byLabel)
    .filter(([, v]) => v > 0)
    .map(([k, v]) => `${v} ${k}`);
  return parts.length ? parts.join(" · ") : "none";
}

// The panel's [label, value] rows, assembled from the standard families
// (telemetry/metrics.py). Tolerant of absent families — an older
// controller simply shows fewer rows.
export function telemetryRows(metrics) {
  const rows = [];
  rows.push(["Prompts", fmtCounts(
    countsByLabel(metrics, "cdt_prompts_total", "status"))]);
  const step = mergeHistogram(metrics, "cdt_sampler_step_seconds");
  rows.push(["Sampler step p50 / p95", step
    ? `${fmtSeconds(histQuantile(step, 0.5))} / ${fmtSeconds(histQuantile(step, 0.95))} (${step.count} obs)`
    : "no runs yet"]);
  rows.push(["Tile tasks", fmtCounts(
    countsByLabel(metrics, "cdt_tile_tasks_total", "event"))]);
  rows.push(["Tile queue depth",
    String(seriesSum(metrics, "cdt_tile_queue_depth"))]);
  const disp = mergeHistogram(metrics, "cdt_dispatch_seconds");
  rows.push(["Dispatches", disp && disp.count
    ? `${disp.count} · p95 ${fmtSeconds(histQuantile(disp, 0.95))}`
    : "none"]);
  rows.push(["Worker probes", fmtCounts(
    countsByLabel(metrics, "cdt_worker_probe_total", "outcome"))]);
  rows.push(["Circuit breakers", breakerSummary(metrics)]);
  const retries = seriesSum(metrics, "cdt_retry_attempts_total");
  if (retries > 0) rows.push(["Retries", String(retries)]);
  rows.push(["Front door", frontDoorSummary(metrics)]);
  rows.push(["Stages", stagesSummary(metrics)]);
  rows.push(["Content cache", cacheSummary(metrics)]);
  rows.push(["Fleet cache", fleetCacheSummary(metrics)]);
  rows.push(["Elastic fleet", elasticSummary(metrics)]);
  rows.push(["Preemption", preemptionSummary(metrics)]);
  return rows;
}

// Disaggregated stage-split serving (cluster/stages): per-pool depth
// and occupancy, the mean decode batch (cross-request VAE coalescing),
// latent-transfer volume, and the loud redispatch counter for work a
// dead stage worker was holding (docs/stages.md).
export function stagesSummary(metrics) {
  const depthFam = metrics && metrics.cdt_stage_queue_depth;
  const occFam = metrics && metrics.cdt_stage_occupancy;
  const jobs = countsByLabel(metrics, "cdt_stage_jobs_total", "stage");
  const total = Object.values(jobs).reduce((a, b) => a + b, 0);
  if (!depthFam && !total) return "fused path";
  const parts = [];
  const occBy = {};
  for (const s of ((occFam && occFam.series) || [])) {
    occBy[(s.labels || {}).stage || "?"] = s.value;
  }
  const depthBy = {};
  for (const s of ((depthFam && depthFam.series) || [])) {
    depthBy[(s.labels || {}).stage || "?"] = s.value;
  }
  for (const stage of ["encode", "denoise", "decode"]) {
    if (stage in depthBy || stage in occBy) {
      const occ = stage in occBy
        ? ` ${(occBy[stage] * 100).toFixed(0)}%` : "";
      parts.push(`${stage} q${depthBy[stage] || 0}${occ}`);
    }
  }
  const dec = mergeHistogram(metrics, "cdt_decode_batch_size");
  if (dec && dec.count) {
    parts.push(`decode x̄ ${(dec.sum / dec.count).toFixed(2)}`);
  }
  const xfer = mergeHistogram(metrics, "cdt_latent_transfer_bytes");
  if (xfer && xfer.count) {
    parts.push(`${xfer.count} handoffs ${(xfer.sum / (1024 * 1024)).toFixed(1)} MB`);
  }
  const steals = seriesSum(metrics, "cdt_stage_steals_total");
  if (steals > 0) parts.push(`${steals} steals`);
  const redisp = countsByLabel(metrics, "cdt_stage_jobs_total", "outcome")
    .redispatch || 0;
  if (redisp > 0) parts.push(`${redisp} REDISPATCHED`);
  return parts.length ? parts.join(" · ") : "fused path";
}

// Step-granular preemption (cluster/preemption.py): preempt counts by
// reason, currently-parked jobs, checkpoint footprint, resume p95, and
// the loud dead-letter counter that means a checkpoint repeatedly
// failed restore (docs/preemption.md).
export function preemptionSummary(metrics) {
  const byReason = countsByLabel(metrics, "cdt_preemptions_total", "reason");
  const total = Object.values(byReason).reduce((a, b) => a + b, 0);
  const parked = seriesSum(metrics, "cdt_jobs_preempted");
  if (!total && !parked) return "none";
  const parts = [];
  if (total > 0) parts.push(fmtCounts(byReason));
  if (parked > 0) parts.push(`${parked} parked`);
  const bytes = seriesSum(metrics, "cdt_checkpoint_bytes");
  if (bytes > 0) parts.push(`${(bytes / (1024 * 1024)).toFixed(1)} MB ckpt`);
  const resume = mergeHistogram(metrics, "cdt_resume_seconds");
  if (resume && resume.count) {
    parts.push(`resume p95 ${fmtSeconds(histQuantile(resume, 0.95))}`);
  }
  const dead = seriesSum(metrics, "cdt_checkpoint_dead_letters_total");
  if (dead > 0) parts.push(`${dead} DEAD-LETTERED`);
  return parts.join(" · ");
}

// Content cache (cluster/cache): per-tier hit rates, coalesce width, and
// the two loud counters — corruption rejections and hash-tokenization
// fallbacks — that each mean an operator should look (docs/caching.md).
export function cacheSummary(metrics) {
  const hits = countsByLabel(metrics, "cdt_cache_hits_total", "tier");
  const misses = countsByLabel(metrics, "cdt_cache_misses_total", "tier");
  const tiers = [...new Set([...Object.keys(hits), ...Object.keys(misses)])]
    .filter((t) => t).sort();
  const parts = [];
  for (const t of tiers) {
    const h = hits[t] || 0;
    const total = h + (misses[t] || 0);
    if (total) parts.push(`${t} ${(100 * h / total).toFixed(0)}% of ${total}`);
  }
  const width = mergeHistogram(metrics, "cdt_coalesce_width");
  if (width && width.count && width.sum > width.count) {
    parts.push(`coalesce x̄ ${(width.sum / width.count).toFixed(2)}`);
  }
  const corrupt = seriesSum(metrics, "cdt_cache_corrupt_total");
  if (corrupt > 0) parts.push(`${corrupt} CORRUPT rejected`);
  const hashTok = seriesSum(metrics, "cdt_hash_tokenization_total");
  if (hashTok > 0) parts.push(`${hashTok} hash-tokenized`);
  return parts.length ? parts.join(" · ") : "no cacheable traffic";
}

// Fleet cache tier (cluster/cache/fleet.py): consistent-hash ring size,
// remote serve outcomes over GET /distributed/cache/entry/{key}, async
// fill/handback traffic, and the opt-in near tier's reuse counters
// (docs/caching.md "Fleet tier").
export function fleetCacheSummary(metrics) {
  const fam = "cdt_fleet_cache_remote_total";
  const ring = seriesSum(metrics, "cdt_fleet_ring_size");
  const remoteHits = seriesSum(metrics, fam, { op: "get", outcome: "hit" });
  const remoteOther =
    seriesSum(metrics, fam, { op: "get", outcome: "miss" }) +
    seriesSum(metrics, fam, { op: "get", outcome: "error" }) +
    seriesSum(metrics, fam, { op: "get", outcome: "skipped" });
  const fills = seriesSum(metrics, fam, { op: "put", outcome: "hit" });
  const handback = seriesSum(metrics, fam, { op: "handback", outcome: "hit" });
  const nearReuse = seriesSum(metrics, "cdt_fleet_near_reuse_total");
  if (!ring && !remoteHits && !remoteOther && !nearReuse) {
    return "per-host only";
  }
  const parts = [`ring ${ring}`];
  const probes = remoteHits + remoteOther;
  if (probes) {
    parts.push(`remote ${remoteHits}/${probes} ` +
      `(${(100 * remoteHits / probes).toFixed(0)}%)`);
  }
  if (fills) parts.push(`${fills} fills`);
  if (handback) parts.push(`${handback} handed back`);
  if (nearReuse) {
    const saved = seriesSum(metrics, "cdt_fleet_near_steps_saved_total");
    parts.push(`near ${nearReuse} reuse (${saved} steps saved)`);
  }
  return parts.join(" · ");
}

// Elastic fleet (cluster/elastic): lifecycle states from the
// cdt_worker_drain_state gauge (0=active, 1=draining, 2=decommissioned),
// autoscale verdicts, steal-scheduler grants, and drain handbacks — the
// numbers that say whether scale events are graceful. Draining workers
// are named: "which worker is leaving?" is the operator's first question.
export function elasticSummary(metrics) {
  const fam = metrics && metrics.cdt_worker_drain_state;
  const series = (fam && fam.series) || [];
  const by = { active: [], draining: [], decommissioned: [] };
  for (const s of series) {
    const name = s.value >= 2 ? "decommissioned"
      : s.value >= 1 ? "draining" : "active";
    by[name].push((s.labels || {}).worker || "?");
  }
  const parts = [];
  if (by.active.length) parts.push(`${by.active.length} active`);
  if (by.draining.length) parts.push(
    `${by.draining.length} draining (${by.draining.sort().join(", ")})`);
  if (by.decommissioned.length) parts.push(
    `${by.decommissioned.length} decommissioned`);
  const scaled = countsByLabel(
    metrics, "cdt_autoscale_decisions_total", "direction");
  const acted = (scaled.up || 0) + (scaled.down || 0);
  if (acted > 0) parts.push(
    `scale ${scaled.up || 0}↑ ${scaled.down || 0}↓`);
  const stolen = seriesSum(metrics, "cdt_steal_assignments_total",
                           { kind: "stolen" });
  if (stolen > 0) parts.push(`${stolen} stolen`);
  const handbacks = seriesSum(metrics, "cdt_drain_handbacks_total");
  if (handbacks > 0) parts.push(`${handbacks} handed back`);
  return parts.length ? parts.join(" · ") : "static fleet";
}

// Serving front door (cluster/frontdoor): admission outcomes, mean
// microbatch occupancy, and queue-wait p95 — the three numbers that say
// whether cross-user batching is earning its window.
export function frontDoorSummary(metrics) {
  const admissions = countsByLabel(metrics, "cdt_admission_total", "outcome");
  const total = Object.values(admissions).reduce((a, b) => a + b, 0);
  if (!total) return "no traffic";
  const parts = [fmtCounts(admissions)];
  const occ = mergeHistogram(metrics, "cdt_batch_size");
  if (occ && occ.count) {
    parts.push(`batch x̄ ${(occ.sum / occ.count).toFixed(2)}`);
  }
  const wait = mergeHistogram(metrics, "cdt_queue_wait_seconds");
  if (wait && wait.count) {
    parts.push(`wait p95 ${fmtSeconds(histQuantile(wait, 0.95))}`);
  }
  const fallbacks = seriesSum(metrics, "cdt_batch_fallbacks_total");
  if (fallbacks > 0) parts.push(`${fallbacks} fallback`);
  return parts.join(" · ");
}

// cdt_worker_breaker_state gauge (0=closed, 1=half-open, 2=open) →
// "3 closed · 1 open (w1)"; names the quarantined workers because that's
// the first question an operator asks.
export function breakerSummary(metrics) {
  const fam = metrics && metrics.cdt_worker_breaker_state;
  const series = (fam && fam.series) || [];
  if (!series.length) return "none tracked";
  const by = { closed: [], half_open: [], open: [] };
  for (const s of series) {
    const name = s.value >= 2 ? "open" : s.value >= 1 ? "half_open" : "closed";
    by[name].push((s.labels || {}).worker || "?");
  }
  const parts = [];
  if (by.closed.length) parts.push(`${by.closed.length} closed`);
  if (by.half_open.length) parts.push(`${by.half_open.length} half-open (${by.half_open.sort().join(", ")})`);
  if (by.open.length) parts.push(`${by.open.length} open (${by.open.sort().join(", ")})`);
  return parts.join(" · ");
}
