// Sampling-progress poll state machine, DOM-free (extracted from
// main.js's trackProgress so node:test can cover it — VERDICT r3 next
// #8). Consumes /distributed/progress snapshots; decides label, bar
// width, preview refetch, and when to stop polling.

// A prompt can sit behind a long serial queue and a cold compile alone
// can take minutes — keep polling ~10 min of misses before giving up.
export const MAX_MISSES = 800;

export function newPollState() {
  return { misses: 0, lastStep: -1 };
}

export function progressLabel(snap) {
  if (snap.failed) return `failed at step ${snap.step}/${snap.total}`;
  if (snap.done) return `done (${snap.total} steps)`;
  return `step ${snap.step}/${snap.total}`;
}

// One poll tick. `snap` is the progress snapshot or null (404/transport).
// Returns {label, widthPct, refetchPreview, stop, hide} and updates
// `state` in place.
export function pollTick(state, snap) {
  if (!snap) {
    state.misses += 1;
    if (state.misses > MAX_MISSES) {
      return { label: "", widthPct: null, refetchPreview: false,
               stop: true, hide: true };
    }
    return { label: "queued…", widthPct: null, refetchPreview: false,
             stop: false, hide: false };
  }
  state.misses = 0;
  // refetch the preview image only when a NEW step reported — refetching
  // every 750 ms would hammer the PNG encoder for identical bytes
  const refetch = snap.step > 0 && snap.step !== state.lastStep;
  if (refetch) state.lastStep = snap.step;
  return {
    label: progressLabel(snap),
    widthPct: Math.round(snap.fraction * 100),
    refetchPreview: refetch,
    stop: !!snap.done,
    hide: false,
  };
}
