// Pure prompt-graph widget logic (no DOM): divider dynamic outputs and
// host auto-populate helpers. Kept DOM-free so node:test can exercise it
// (scripts/test-web.sh) — parity with the reference's vitest'ed helpers
// (web/tests/), and with web/image_batch_divider.js:10-62 which grows/
// shrinks node outputs to divide_by.

export const DIVIDER_CLASSES = ["ImageBatchDivider", "AudioBatchDivider"];
export const MAX_DIVIDE = 10;

export function clampDivideBy(value) {
  const n = Math.floor(Number(value));
  if (!Number.isFinite(n)) return 1;
  return Math.max(1, Math.min(n, MAX_DIVIDE));
}

// [[nodeId, node], ...] for divider nodes in prompt-JSON order
export function dividerNodes(prompt) {
  if (!prompt || typeof prompt !== "object") return [];
  return Object.entries(prompt).filter(
    ([, n]) => n && DIVIDER_CLASSES.includes(n.class_type));
}

// Links from any node's inputs into `nodeId`'s outputs at index >=
// divideBy — the chunks that repeat the empty batch once divide_by
// shrinks (graph/nodes_builtin.py dividers). The dashboard warns on
// these instead of silently wiring empty outputs.
export function inactiveLinks(prompt, nodeId, divideBy) {
  const hits = [];
  if (!prompt) return hits;
  for (const [consumerId, node] of Object.entries(prompt)) {
    const inputs = (node && node.inputs) || {};
    for (const [inputName, v] of Object.entries(inputs)) {
      if (Array.isArray(v) && String(v[0]) === String(nodeId)
          && Number(v[1]) >= divideBy) {
        hits.push({ consumerId, inputName, outputIndex: Number(v[1]) });
      }
    }
  }
  return hits;
}

// Rows the auto-populate endpoint added, normalized for display
export function describeAddedHosts(result) {
  const hosts = (result && result.added) || [];
  return hosts.map((h) => `${h.id} → ${h.address}`).join(", ");
}
