// Typed fetch client for the /distributed/* control plane with
// retry/backoff (parity: reference web/apiClient.js — retry x3 with
// backoff, web/apiClient.js:10-47; route coverage per SURVEY §2.6).

const RETRIES = 3;
const BACKOFF_MS = 400;
const AUTH_STORAGE_KEY = "cdt_auth_token";

// Cluster auth token (utils/auth.py): mutating routes 401 without it once
// a token is configured (a public tunnel auto-generates one). The user
// pastes it into the dashboard settings; persisted in localStorage.
export function getAuthToken() {
  try { return localStorage.getItem(AUTH_STORAGE_KEY) || ""; } catch { return ""; }
}

export function setAuthToken(token) {
  try {
    if (token) localStorage.setItem(AUTH_STORAGE_KEY, token);
    else localStorage.removeItem(AUTH_STORAGE_KEY);
  } catch { /* storage unavailable (private mode) — header still unset */ }
}

function buildHeaders(method) {
  const headers = {};
  // POSTs always declare JSON: the control plane rejects POSTs without a
  // JSON content type (cross-origin simple-request guard)
  if (method === "POST") headers["Content-Type"] = "application/json";
  const token = getAuthToken();
  if (token) headers["X-CDT-Auth"] = token;
  return Object.keys(headers).length ? headers : undefined;
}

async function request(path, { method = "GET", body, retries = RETRIES, timeoutMs = 15000 } = {}) {
  let lastErr;
  for (let attempt = 0; attempt <= retries; attempt++) {
    const ctrl = new AbortController();
    const timer = setTimeout(() => ctrl.abort(), timeoutMs);
    try {
      const resp = await fetch(path, {
        method,
        headers: buildHeaders(method),
        body: body !== undefined ? JSON.stringify(body) : undefined,
        signal: ctrl.signal,
      });
      clearTimeout(timer);
      const text = await resp.text();
      let data = null;
      try { data = text ? JSON.parse(text) : null; } catch { data = { raw: text }; }
      if (!resp.ok) {
        const err = new Error((data && data.error) || `HTTP ${resp.status}`);
        err.status = resp.status;
        err.data = data;
        // client errors are final; only retry transport/5xx
        if (resp.status < 500) throw err;
        lastErr = err;
      } else {
        return data;
      }
    } catch (e) {
      clearTimeout(timer);
      if (e.status && e.status < 500) throw e;
      lastErr = e;
    }
    if (attempt < retries) {
      await new Promise((r) => setTimeout(r, BACKOFF_MS * 2 ** attempt));
    }
  }
  throw lastErr;
}

export const api = {
  // health / info
  health: () => request("/distributed/health", { retries: 0, timeoutMs: 4000 }),
  systemInfo: () => request("/distributed/system_info"),
  networkInfo: () => request("/distributed/network_info"),

  // config
  getConfig: () => request("/distributed/config"),
  updateWorker: (worker) => request("/distributed/config/update_worker", { method: "POST", body: worker }),
  deleteWorker: (workerId) => request("/distributed/config/delete_worker", { method: "POST", body: { id: workerId } }),
  updateSetting: (key, value) => request("/distributed/config/update_setting", { method: "POST", body: { key, value } }),
  updateMaster: (fields) => request("/distributed/config/update_master", { method: "POST", body: fields }),
  autoPopulate: () => request("/distributed/config/auto_populate", { method: "POST", body: {}, retries: 0 }),

  // queue
  queue: (prompt, opts = {}) => request("/distributed/queue", {
    method: "POST",
    body: { prompt, ...opts },
    timeoutMs: 120000,
    retries: 0,
  }),
  clearMemory: () => request("/distributed/clear_memory", { method: "POST", body: {} }),
  interrupt: () => request("/distributed/interrupt", { method: "POST", body: {}, retries: 0 }),

  // worker processes
  launchWorker: (workerId) => request("/distributed/launch_worker", { method: "POST", body: { worker_id: workerId }, retries: 0, timeoutMs: 60000 }),
  stopWorker: (workerId) => request("/distributed/stop_worker", { method: "POST", body: { worker_id: workerId }, retries: 0 }),
  managedWorkers: () => request("/distributed/managed_workers"),
  workerLog: (workerId) => request(`/distributed/worker_log/${encodeURIComponent(workerId)}`),
  remoteWorkerLog: (workerId) => request(`/distributed/remote_worker_log/${encodeURIComponent(workerId)}`),
  localLog: () => request("/distributed/local_log"),
  localWorkerStatus: () => request("/distributed/local-worker-status"),
  clearLaunching: (workerId) => request("/distributed/worker/clear_launching", { method: "POST", body: { worker_id: workerId } }),

  // node interface specs (drives the workflow parameter forms)
  objectInfo: () => request("/distributed/object_info"),

  // shipped workflows
  listWorkflows: () => request("/distributed/workflows"),
  getWorkflow: (name) => request(`/distributed/workflows/${encodeURIComponent(name)}`),

  // observability
  memoryStats: () => request("/distributed/memory_stats"),
  stepTimes: () => request("/distributed/step_times"),
  metrics: () => request("/distributed/metrics.json", { retries: 0 }),
  trace: (jobId) => request(`/distributed/trace/${encodeURIComponent(jobId)}`, { retries: 0 }),
  progress: (promptId) => request(`/distributed/progress/${encodeURIComponent(promptId)}`, { retries: 0 }),
  previewUrl: (promptId, shard = 0) => `/distributed/preview/${encodeURIComponent(promptId)}?shard=${shard}&t=${Date.now()}`,
  profileStart: (out) => request("/distributed/profile/start", { method: "POST", body: out ? { out } : {}, retries: 0 }),
  profileStop: () => request("/distributed/profile/stop", { method: "POST", body: {}, retries: 0 }),

  // tunnel
  tunnelStatus: () => request("/distributed/tunnel/status"),
  tunnelStart: () => request("/distributed/tunnel/start", { method: "POST", body: {}, retries: 0, timeoutMs: 45000 }),
  tunnelStop: () => request("/distributed/tunnel/stop", { method: "POST", body: {}, retries: 0 }),
};

// Probe a worker host directly from the browser (parity: the UI's
// pre-flight probe, web/executionUtils.js:108-151). Cross-origin — the
// controller enables CORS on /distributed/health.
export async function probeHost(address, timeoutMs = 4000) {
  const base = normalizeAddress(address);
  const ctrl = new AbortController();
  const timer = setTimeout(() => ctrl.abort(), timeoutMs);
  try {
    const resp = await fetch(`${base}/distributed/health`, { signal: ctrl.signal });
    clearTimeout(timer);
    return resp.ok ? await resp.json() : null;
  } catch {
    clearTimeout(timer);
    return null;
  }
}

// URL normalization (parity: reference web/urlUtils.js — https heuristics
// for cloud domains).
const HTTPS_DOMAINS = ["trycloudflare.com", "ngrok.io", "ngrok-free.app", "proxy.runpod.net"];

export function normalizeAddress(address) {
  let a = String(address || "").trim().replace(/\/+$/, "");
  if (!a) return "";
  if (!a.includes("://")) {
    const https = HTTPS_DOMAINS.some((d) => a.includes(d));
    a = `${https ? "https" : "http"}://${a}`;
  }
  if (a.startsWith("http://") && HTTPS_DOMAINS.some((d) => a.includes(d))) {
    a = "https://" + a.slice("http://".length);
  }
  return a;
}
