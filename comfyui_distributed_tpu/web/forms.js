// Workflow parameter forms: pure prompt-editing logic (no DOM).
//
// The reference's L6 lives inside ComfyUI's graph editor, so every node
// input is editable for free (web/executionUtils.js:6-23 hooks a full
// authoring environment). This standalone dashboard instead GENERATES
// edit forms from the node interface specs served by
// `GET /distributed/object_info` (graph/node.py INPUTS/OPTIONAL), writing
// edits through to the prompt JSON — edit-then-run without touching raw
// JSON (VERDICT r3 next #3). DOM-free so node:test can exercise it
// (scripts/test-web.sh).

// ComfyUI type name → form field kind; anything else (IMAGE, LATENT,
// MODEL, "*", …) is a graph edge or opaque object — not form-editable.
const KIND_BY_TYPE = {
  INT: "int",
  FLOAT: "float",
  STRING: "string",
  BOOLEAN: "boolean",
};

// Inputs that already have dedicated widget UIs (valueWidgets.js /
// widgets.js) — keep them out of the generic form so the same field
// doesn't render twice with diverging behavior.
const WIDGETED_FIELDS = new Set(["worker_values", "divide_by"]);

export function isLink(value) {
  // graph-edge encoding: [source_node_id, output_index] (graph/node.py:63)
  return Array.isArray(value) && value.length === 2
    && typeof value[0] === "string" && Number.isInteger(value[1]);
}

export function fieldKind(typeName) {
  return KIND_BY_TYPE[String(typeName || "").toUpperCase()] || null;
}

// Long free text (prompts, file lists) wants a textarea, not a one-line
// input. Heuristic: field name says "text"/"prompt", or the current value
// is already long.
export function isMultiline(field) {
  if (field.kind !== "string") return false;
  const name = field.name.toLowerCase();
  if (name.includes("text") || name.includes("prompt")) return true;
  return typeof field.value === "string" && field.value.length > 60;
}

// Flatten a prompt graph + object_info specs into an ordered list of
// editable scalar fields. Skips links (wired inputs), widgeted fields,
// and inputs whose declared type isn't a form scalar. Unknown node
// classes contribute nothing (a foreign workflow still renders, just
// without forms for those nodes).
export function editableFields(prompt, specs) {
  const nodes = (specs && specs.nodes) || specs || {};
  const out = [];
  if (!prompt || typeof prompt !== "object") return out;
  for (const [nodeId, node] of Object.entries(prompt)) {
    if (!node || typeof node !== "object") continue;
    const spec = nodes[node.class_type];
    if (!spec) continue;
    const inputs = node.inputs || {};
    const declared = { ...(spec.required || {}), ...(spec.optional || {}) };
    for (const [name, typeName] of Object.entries(declared)) {
      const kind = fieldKind(typeName);
      if (!kind || WIDGETED_FIELDS.has(name)) continue;
      const value = inputs[name];
      if (isLink(value)) continue;          // wired from another node
      out.push({
        nodeId,
        classType: node.class_type,
        name,
        kind,
        value: value === undefined ? null : value,
        optional: !(spec.required && name in spec.required),
      });
    }
  }
  return out;
}

// Parse + validate a raw form string for a field kind. Throws on values
// that would corrupt the prompt (NaN seeds, non-integer steps).
export function coerceFieldValue(kind, raw) {
  // Number("") === 0 — a cleared numeric field must be rejected, not
  // silently written as 0 (a 0-step run)
  const empty = typeof raw === "string" && raw.trim() === "";
  switch (kind) {
    case "int": {
      const n = Number(raw);
      if (empty || !Number.isFinite(n) || !Number.isInteger(n)) {
        throw new Error(`not an integer: ${JSON.stringify(raw)}`);
      }
      return n;
    }
    case "float": {
      const n = Number(raw);
      if (empty || !Number.isFinite(n)) {
        throw new Error(`not a number: ${JSON.stringify(raw)}`);
      }
      return n;
    }
    case "boolean":
      if (typeof raw === "boolean") return raw;
      return raw === "true" || raw === "1" || raw === 1;
    default:
      return String(raw);
  }
}

// Write one coerced field edit into a prompt object (mutates; returns the
// coerced value so callers can reflect it back into the input).
export function applyFieldEdit(prompt, nodeId, name, kind, raw) {
  const node = prompt && prompt[nodeId];
  if (!node) throw new Error(`no node ${nodeId} in prompt`);
  const value = coerceFieldValue(kind, raw);
  node.inputs = node.inputs || {};
  node.inputs[name] = value;
  return value;
}

// Preflight prompt lint, mirroring the server's validate_prompt rules
// (graph/executor.py:37-79: unknown class, missing required input,
// dangling link, bad output index) plus an unknown-input-name warning
// (the executor silently drops those at run time). The reference's
// graph editor prevents these structurally; a JSON-first dashboard
// must lint instead. Returns [{nodeId, level: "error"|"warning",
// message}] — empty = clean.
export function lintPrompt(prompt, specs) {
  const nodes = (specs && specs.nodes) || specs || {};
  const issues = [];
  if (!prompt || typeof prompt !== "object") return issues;
  const push = (nodeId, level, message) =>
    issues.push({ nodeId, level, message });
  for (const [nodeId, node] of Object.entries(prompt)) {
    if (nodeId.startsWith("_")) continue;   // _meta etc. — server strips
    if (!node || typeof node !== "object" || !node.class_type) {
      push(nodeId, "error", "node must have class_type");
      continue;
    }
    const spec = nodes[node.class_type];
    if (!spec) {
      // only an error when we have specs at all (no specs = can't know)
      if (Object.keys(nodes).length) {
        push(nodeId, "error", `unknown node class ${node.class_type}`);
      }
      continue;
    }
    const inputs = node.inputs || {};
    for (const name of Object.keys(spec.required || {})) {
      if (inputs[name] === undefined) {
        push(nodeId, "error", `missing required input ${name}`);
      }
    }
    const declared = new Set([
      ...Object.keys(spec.required || {}),
      ...Object.keys(spec.optional || {}),
    ]);
    for (const [name, value] of Object.entries(inputs)) {
      if (!declared.has(name)) {
        push(nodeId, "warning",
             `input ${name} is not declared by ${node.class_type} ` +
             "(the executor ignores it)");
      }
      if (isLink(value)) {
        const [src, outIdx] = value;
        const srcNode = prompt[src];
        if (!srcNode) {
          push(nodeId, "error",
               `input ${name} links to missing node ${src}`);
        } else {
          const srcSpec = nodes[srcNode.class_type];
          if (srcSpec && outIdx >= (srcSpec.returns || []).length) {
            push(nodeId, "error",
                 `input ${name} links to output ${outIdx} of ` +
                 `${srcNode.class_type} which has ` +
                 `${(srcSpec.returns || []).length}`);
          }
        }
      }
    }
  }
  // cycle check (validate_prompt runs topo_order; a cyclic prompt must
  // not lint clean). Iterative DFS over link edges.
  const state = new Map();                 // nodeId → 0 visiting, 1 done
  const links = (nid) =>
    Object.values((prompt[nid] && prompt[nid].inputs) || {})
      .filter((v) => isLink(v) && prompt[v[0]])
      .map((v) => v[0]);
  for (const start of Object.keys(prompt)) {
    if (start.startsWith("_") || state.get(start) === 1) continue;
    const stack = [[start, 0]];
    while (stack.length) {
      const top = stack[stack.length - 1];
      const [nid] = top;
      if (top[1] === 0) state.set(nid, 0);
      const deps = links(nid);
      if (top[1] < deps.length) {
        const next = deps[top[1]++];
        if (state.get(next) === 0) {
          push(next, "error", `cycle involving node ${next}`);
          state.set(next, 1);
        } else if (state.get(next) === undefined) {
          stack.push([next, 0]);
        }
      } else {
        state.set(nid, 1);
        stack.pop();
      }
    }
  }
  return issues;
}

// Group fields by node for rendering: [[{nodeId, classType}, fields], …]
// in prompt order.
export function groupByNode(fields) {
  const groups = new Map();
  for (const f of fields) {
    if (!groups.has(f.nodeId)) {
      groups.set(f.nodeId, { nodeId: f.nodeId, classType: f.classType, fields: [] });
    }
    groups.get(f.nodeId).fields.push(f);
  }
  return [...groups.values()];
}
